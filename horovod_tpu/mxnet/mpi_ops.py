"""MXNet NDArray collectives bridged to the XLA eager runtime.

Reference: horovod/mxnet/mpi_ops.py (sync + async wrappers over the
``horovod_mxnet_*_async`` C functions, mxnet/mpi_ops.cc:638-705).

Semantics match the torch bridge (horovod_tpu/torch/mpi_ops.py): the input is
this *host's* tensor, replicated onto the local mesh slices; chip-axis
reductions return the host value for Average and value*size for Sum. The
bridge is duck-typed over anything exposing ``asnumpy()`` (real
``mx.nd.NDArray``, or array-likes in environments without MXNet), so the
frontend is fully exercisable without an MXNet install.

Parity scope: API-compatible bridge ONLY. The reference additionally
pushes collectives onto MXNet's async dependency engine
(mxnet/mpi_ops.cc:638-705) so they order against NDArray reads without
host syncs; that engine integration has no TPU analog (the data plane is
XLA) and is explicitly out of scope — calls here are synchronous at the
bridge boundary (docs/integrations.md "Parity scope").
"""

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min,  # noqa: F401
                                            Product, ReduceOp, Sum)

__all__ = ["allreduce", "allreduce_", "grouped_allreduce",
           "grouped_allreduce_", "allgather", "allgather_object",
           "broadcast_object",
           "grouped_allgather", "broadcast", "broadcast_", "alltoall",
           "reducescatter", "grouped_reducescatter", "barrier",
           "Average", "Sum", "Adasum", "Min", "Max", "Product", "ReduceOp"]


def _mx():
    try:
        import mxnet
        return mxnet
    except ImportError:
        return None


def _to_numpy(t):
    if hasattr(t, "asnumpy"):
        return np.asarray(t.asnumpy())
    return np.asarray(t)


def _like(template, arr):
    """Rebuild an output in the caller's tensor type (NDArray when MXNet is
    installed, else numpy)."""
    arr = np.asarray(arr)
    mx = _mx()
    if mx is not None and isinstance(template, mx.nd.NDArray):
        return mx.nd.array(arr, ctx=template.context, dtype=arr.dtype)
    return arr


def _copy_into(target, arr):
    """In-place variants: write the result back into the caller's NDArray
    (duck-typed: both mx.nd.NDArray and numpy support ``t[:] = value``)."""
    arr = np.asarray(arr)
    if hasattr(target, "__setitem__"):
        target[slice(None)] = arr
        return target
    return _like(target, arr)


def _stack(a, ps):
    # Local rows only under a multi-process launch (the eager stacked
    # contract of collective_ops._prepare, docs/api.md).
    ps = ps if ps is not None else C.global_process_set
    n_rows = C._expected_rows(ps.mesh, ps.size())
    return np.broadcast_to(a, (n_rows,) + a.shape)


def _first(out):
    return np.asarray(out)[0]


def _resolve_op(average, op):
    if op is not None and average is not None:
        raise ValueError("The op parameter supersedes average; "
                         "please provide only one of them.")
    if op is not None:
        return op
    return Average if (average is None or average) else Sum


def allreduce(tensor, average=None, name=None, priority=0, op=None,
              prescale_factor=1.0, postscale_factor=1.0, process_set=None):
    """reference: hvd.allreduce (mxnet/mpi_ops.py). ``priority`` is accepted
    for API parity; XLA schedules the compiled program itself."""
    del priority
    a = _to_numpy(tensor)
    out = C.allreduce(_stack(a, process_set), op=_resolve_op(average, op),
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor,
                      process_set=process_set, name=name)
    return _like(tensor, _first(out))


def allreduce_(tensor, average=None, name=None, priority=0, op=None,
               prescale_factor=1.0, postscale_factor=1.0, process_set=None):
    out = allreduce(tensor, average=average, name=name, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    process_set=process_set)
    return _copy_into(tensor, _to_numpy(out))


def grouped_allreduce(tensors, average=None, name=None, priority=0, op=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=None):
    del priority
    arrs = [_stack(_to_numpy(t), process_set) for t in tensors]
    outs = C.grouped_allreduce(arrs, op=_resolve_op(average, op),
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor,
                               process_set=process_set, name=name)
    return [_like(t, _first(o)) for t, o in zip(tensors, outs)]


def allgather(tensor, name=None, priority=0, process_set=None):
    del priority
    a = _to_numpy(tensor)
    out = C.allgather(_stack(a, process_set), process_set=process_set,
                      name=name)
    # Output slice [r] is already the concatenation of every rank's data
    # (collective_ops.allgather contract).
    return _like(tensor, _first(out))


def grouped_allgather(tensors, name=None, priority=0, process_set=None):
    return [allgather(t, name=name, process_set=process_set)
            for t in tensors]


def broadcast(tensor, root_rank, name=None, priority=0, process_set=None):
    del priority
    a = _to_numpy(tensor)
    out = C.broadcast(_stack(a, process_set), root_rank=root_rank,
                      process_set=process_set, name=name)
    return _like(tensor, _first(out))


def broadcast_(tensor, root_rank, name=None, priority=0, process_set=None):
    out = broadcast(tensor, root_rank, name=name, process_set=process_set)
    return _copy_into(tensor, _to_numpy(out))


def alltoall(tensor, splits=None, name=None, priority=0, process_set=None):
    """Returns ``(output, received_splits)`` when ``splits`` is given, else
    just the output — the reference's contract."""
    del priority
    a = _to_numpy(tensor)
    n = (process_set.size() if process_set is not None else
         basics.size())
    stacked = _stack(a, process_set)
    if splits is not None:
        # One split row per stacked row (this host's replicated tensor on
        # each mesh slice it owns; local rows only when multi-process).
        splits = np.broadcast_to(np.asarray(splits), (stacked.shape[0], n))
    res = C.alltoall(stacked, splits=splits,
                     process_set=process_set, name=name)
    if splits is None:
        return _like(tensor, _first(res))
    out, recv_splits = res
    return _like(tensor, _first(out)), np.asarray(recv_splits)[0]


def reducescatter(tensor, op=Sum, name=None, priority=0, process_set=None):
    del priority
    a = _to_numpy(tensor)
    out = C.reducescatter(_stack(a, process_set), op=op,
                          process_set=process_set, name=name)
    return _like(tensor, _first(out))


def grouped_reducescatter(tensors, op=Sum, name=None, priority=0,
                          process_set=None):
    return [reducescatter(t, op=op, name=name, process_set=process_set)
            for t in tensors]


def barrier(process_set=None):
    C.barrier(process_set=process_set)


def grouped_allreduce_(tensors, average=None, name=None, priority=0, op=None,
                       prescale_factor=1.0, postscale_factor=1.0,
                       process_set=None):
    """In-place grouped allreduce (reference: mxnet/mpi_ops.py
    grouped_allreduce_)."""
    outs = grouped_allreduce(tensors, average=average, name=name,
                             priority=priority, op=op,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor,
                             process_set=process_set)
    return [_copy_into(t, _to_numpy(o)) for t, o in zip(tensors, outs)]


def allgather_object(obj, name=None, process_set=None):
    """Gather one picklable object per rank (reference:
    mxnet/mpi_ops.py allgather_object)."""
    return C.allgather_object_single(obj, process_set=process_set, name=name)


def broadcast_object(obj, root_rank=0, name=None, process_set=None):
    return C.broadcast_object(obj, root_rank=root_rank, name=name,
                              process_set=process_set)
