"""MXNet frontend: ``import horovod_tpu.mxnet as hvd``.

Reference surface: horovod/mxnet/__init__.py (DistributedOptimizer:
gradient-averaging optimizer wrapper, DistributedTrainer: gluon Trainer with
allreduce'd ``_allreduce_grads``, broadcast_parameters) and
horovod/mxnet/mpi_ops.py (collectives).

The collective bridge is duck-typed (see mpi_ops.py), so everything except
:class:`DistributedTrainer` (which subclasses ``gluon.Trainer`` and therefore
needs a real MXNet install) works without MXNet — mirroring how the
reference's frontends gate on what is importable.
"""

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, mpi_threads_supported, mpi_enabled, mpi_built,
    gloo_enabled, gloo_built, nccl_built, ddl_built, ccl_built, cuda_built,
    rocm_built, start_timeline, stop_timeline,
)
from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, global_process_set, process_set_by_id,
    remove_process_set,
)
from horovod_tpu.common.util import (  # noqa: F401
    check_extension, check_installed_version, gpu_available,
    num_rank_is_power_2, split_list,
)
from horovod_tpu.mxnet.mpi_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum,
    allgather, allgather_object, allreduce, allreduce_, alltoall, barrier,
    broadcast, broadcast_, broadcast_object, grouped_allgather,
    grouped_allreduce, grouped_allreduce_, grouped_reducescatter,
    reducescatter,
)
# The mxnet bridge is numpy duck-typed, so the TF frontend's numpy
# compressors serve here too (reference: horovod/mxnet/compression.py).
from horovod_tpu.tensorflow import (Compression, Compressor,  # noqa: F401
                                    FP16Compressor, NoneCompressor)
from horovod_tpu.mxnet import mpi_ops as _ops


class DistributedOptimizer:
    """Optimizer wrapper averaging gradients across ranks before the update
    (reference: horovod/mxnet/__init__.py DistributedOptimizer).

    Works with any optimizer exposing the MXNet contract
    ``update(index, weight, grad, state)`` /
    ``update_multi_precision(...)``; gradients are allreduced (grouped when
    ``index`` is a list, like the reference's grouped path) before delegating.
    """

    def __init__(self, optimizer, gradient_predivide_factor=1.0,
                 num_groups=0, process_set=None):
        del num_groups  # grouping is signature-level here (fusion runtime)
        self._optimizer = optimizer
        self._predivide = float(gradient_predivide_factor)
        self._process_set = process_set

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _reduce(self, index, grad):
        # Reference semantics (horovod/mxnet/__init__.py): predivide only
        # rescales the wire intermediate — 1/f before the reduction, f after —
        # so the net result is still the plain average.
        pre = 1.0 / self._predivide if self._predivide != 1.0 else 1.0
        post = self._predivide if self._predivide != 1.0 else 1.0
        if isinstance(index, (tuple, list)):
            return _ops.grouped_allreduce(
                list(grad), op=Average, prescale_factor=pre,
                postscale_factor=post, process_set=self._process_set,
                name=f"grads_{index[0]}")
        return _ops.allreduce(grad, op=Average, prescale_factor=pre,
                              postscale_factor=post,
                              process_set=self._process_set,
                              name=f"grad_{index}")

    def update(self, index, weight, grad, state):
        self._optimizer.update(index, weight, self._reduce(index, grad),
                               state)

    def update_multi_precision(self, index, weight, grad, state):
        self._optimizer.update_multi_precision(
            index, weight, self._reduce(index, grad), state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def DistributedTrainer(params, optimizer, optimizer_params=None,
                       compression=None, gradient_predivide_factor=1.0,
                       process_set=None):
    """gluon ``Trainer`` whose ``_allreduce_grads`` averages over ranks
    (reference: horovod/mxnet/__init__.py DistributedTrainer). Requires a
    real MXNet install."""
    try:
        import mxnet as mx
    except ImportError as e:
        raise ImportError(
            "DistributedTrainer requires MXNet (gluon); for the"
            " optimizer-level wrapper use DistributedOptimizer") from e
    del compression  # wire dtype is the runtime's HOROVOD_WIRE_DTYPE knob

    class _Trainer(mx.gluon.Trainer):
        def __init__(self):
            super().__init__(params, optimizer,
                             optimizer_params, kvstore=None)
            # Scale Trainer's internal batch normalization by world size the
            # way the reference does (loss is averaged per worker, gradient
            # average across workers completes the global mean).
            self._scale /= size()

        def _allreduce_grads(self):
            pre = (1.0 / gradient_predivide_factor
                   if gradient_predivide_factor != 1.0 else 1.0)
            post = (gradient_predivide_factor
                    if gradient_predivide_factor != 1.0 else 1.0)
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for g in param.list_grad():
                        _ops.allreduce_(g, op=Average, prescale_factor=pre,
                                        postscale_factor=post,
                                        process_set=process_set,
                                        name=f"param_{i}")

    return _Trainer()


def broadcast_parameters(params, root_rank=0, prefix=None):
    """Broadcast a params collection from ``root_rank``
    (reference: horovod/mxnet/__init__.py broadcast_parameters). Accepts a
    dict of NDArray/arrays or a gluon ``ParameterDict``."""
    if params is None:
        return
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        raise ValueError("invalid params of type: %s" % type(params))
    for name, p in items:
        tag = f"{prefix or ''}{name}"
        try:
            tensor = p.data() if hasattr(p, "data") else p  # gluon Parameter
        except Exception:
            continue  # deferred-init parameter: nothing to broadcast yet
        out = _ops.broadcast(tensor, root_rank, name=tag)
        if hasattr(p, "set_data"):
            p.set_data(out)
        else:
            _ops._copy_into(p, _ops._to_numpy(out))
