"""Training-loop callbacks for flax/optax loops.

Reference: horovod/_keras/callbacks.py (:23-193) — the four Horovod Keras
callbacks: BroadcastGlobalVariablesCallback, MetricAverageCallback,
LearningRateScheduleCallback, LearningRateWarmupCallback.

Flax has no Model.fit, so these are loop-agnostic objects driven by a
``CallbackList`` the user invokes at the standard hook points; semantics match
the reference callback-for-callback.
"""

import numpy as np

from horovod_tpu.common import basics


class Callback:
    def on_train_begin(self, state):
        return state

    def on_epoch_begin(self, epoch, state):
        return state

    def on_epoch_end(self, epoch, state, metrics):
        return state, metrics

    def on_batch_begin(self, batch, state):
        return state


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def on_train_begin(self, state):
        for c in self.callbacks:
            state = c.on_train_begin(state)
        return state

    def on_epoch_begin(self, epoch, state):
        for c in self.callbacks:
            state = c.on_epoch_begin(epoch, state)
        return state

    def on_epoch_end(self, epoch, state, metrics):
        for c in self.callbacks:
            state, metrics = c.on_epoch_end(epoch, state, metrics)
        return state, metrics

    def on_batch_begin(self, batch, state):
        for c in self.callbacks:
            state = c.on_batch_begin(batch, state)
        return state


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial state from ``root_rank`` at train start so all ranks
    begin identical (reference: _keras/callbacks.py:23-49)."""

    def __init__(self, root_rank=0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        from horovod_tpu.optim import broadcast_parameters
        return broadcast_parameters(state, root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch metrics over ranks (reference: callbacks.py:52-84).

    Metrics computed by :func:`horovod_tpu.parallel.make_eval_step` arrive
    pre-averaged; this callback handles host-side python metrics given as
    ``{name: per_rank_list_or_scalar}``.
    """

    def on_epoch_end(self, epoch, state, metrics):
        out = {}
        for k, v in metrics.items():
            arr = np.asarray(v, np.float64)
            out[k] = float(arr.mean()) if arr.ndim else float(arr)
        return state, out


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier`` within [start_epoch, end_epoch)
    (reference: callbacks.py:87-143). ``multiplier`` may be a constant or a
    function of epoch; with ``staircase`` the epoch is floored.

    The reference's ``momentum_correction`` (rescaling the momentum buffer
    when LR jumps) is intentionally absent: it mutates optimizer-internal
    state, which in optax belongs to the optimizer — use
    ``optax.inject_hyperparams`` + a momentum-aware schedule instead.
    """

    def __init__(self, initial_lr, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, steps_per_epoch=None):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_lr = initial_lr
        self._epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
            self._constant = False
        else:
            self.multiplier = lambda epoch: multiplier
            self._constant = True

    def _in_range(self, epoch):
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def lr(self, epoch, batch=0):
        if not self._in_range(epoch):
            return self.current_lr
        e = epoch if self.staircase or not self.steps_per_epoch \
            else epoch + batch / float(self.steps_per_epoch)
        self.current_lr = self.initial_lr * self.multiplier(e)
        return self.current_lr

    def on_epoch_begin(self, epoch, state):
        self._epoch = epoch
        self.lr(epoch)
        return state

    def on_batch_begin(self, batch, state):
        # smooth (non-staircase) ramp advances within the epoch tracked by
        # on_epoch_begin (reference: callbacks.py on_batch_begin)
        if not self.staircase and self.steps_per_epoch:
            self.lr(self._epoch, batch)
        return state


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup from ``initial_lr`` to ``initial_lr * size`` over
    ``warmup_epochs`` — the "Facebook paper" ramp the reference implements
    (reference: callbacks.py:146-193)."""

    def __init__(self, initial_lr, warmup_epochs=5, steps_per_epoch=None,
                 verbose=False, size=None):
        self.size = size if size is not None else basics.size()
        warmup = warmup_epochs

        def multiplier(epoch):
            # epoch may be fractional when steps_per_epoch is given
            progress = min(max(epoch / float(warmup), 0.0), 1.0)
            return 1.0 / self.size + progress * (1.0 - 1.0 / self.size)

        super().__init__(initial_lr=initial_lr * self.size,
                         multiplier=multiplier, start_epoch=0,
                         end_epoch=warmup, staircase=False,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def _in_range(self, epoch):
        # The warmup multiplier clamps to 1 past warmup_epochs, so always
        # computing keeps LR at size*base after the ramp ends.
        return True
