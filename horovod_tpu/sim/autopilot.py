"""Twin-pretrained autopilot: run the REAL tuner loop on priced epochs.

The live autopilot (:mod:`horovod_tpu.autopilot.controller`) spends its
first ~dozen decision epochs cold: a categorical sweep over the
strategy/wire space, then Bayesian optimization over the numeric fusion
knobs — every sample a real training epoch at a possibly-bad
configuration. This module runs the SAME machinery — the real
:class:`~horovod_tpu.autotune.parameter_manager.ParameterManager`
``suggest()/observe()`` loop over the SAME
:func:`~horovod_tpu.autotune.parameter_manager.sweep_categoricals`
space — against *simulated* epoch scores, then exports the observation
history through the manager's serialization seam
(``export_observations``). A live controller pointed at the artifact via
``HOROVOD_AUTOPILOT_PRIOR`` skips the sweep and starts the numeric
search at the twin's best point.

The epoch model composes pieces that are already exact rather than
re-modeling them: collective bytes come from
:func:`analysis.cost.collective_step_tiers` (the same
``wire.hierarchical_wire_bytes`` integers the runtime records), the
per-tier walls from :func:`profile.roofline.tier_time_estimate` against
:func:`~horovod_tpu.profile.roofline.chip_peaks` (so the detuning knobs
— ``HOROVOD_PEAK_DCN_GBS`` et al. — shape the twin exactly like the
CPU-tier guards), and the epoch SCORE is the controller's own
``_score`` formula: reduced bytes per second with the epoch's DCN bytes
priced at the DCN roof and added to the denominator. Deterministic end
to end — the only stochastic piece is the BO's candidate sampler, which
is seeded.
"""

import json

from horovod_tpu.analysis.cost import collective_step_tiers
from horovod_tpu.profile import roofline

# Fixed per-flush dispatch overhead (virtual seconds): what makes the
# fusion-threshold knob a real trade-off in the twin — more buckets per
# step cost more dispatches — instead of a flat direction.
FLUSH_OVERHEAD_S = 200e-6


def _wire_width(name):
    return 2 if name in ("float16", "bfloat16") else 4


def epoch_frame(knobs, world, num_slices, *, per_rank_elems,
                steps_per_epoch, compute_s_per_step, peaks):
    """Price one decision epoch at ``knobs`` (a ``ParameterManager``
    ``suggest()`` triple). Returns the controller's signal-frame shape:
    ``reduced_bytes`` / ``elapsed_s`` / ``dcn_bytes``."""
    threshold, cycle_ms, cats = knobs
    strategy = cats.get("strategy", "flat")
    wire = cats.get("wire_dtype", "")
    if strategy == "torus_qcross":
        # int8 cross leg is the strategy's own; ICI stays payload dtype.
        width, cross = 4, ""
    elif strategy in ("hierarchical", "torus"):
        # a 16-bit cast wire moves every leg of the exact strategies
        width, cross = (_wire_width(wire) if wire else 4), (wire or "")
    else:
        width, cross = (_wire_width(wire) if wire else 4), ""
    tiers = collective_step_tiers(per_rank_elems, world, num_slices,
                                  strategy=strategy, width=width,
                                  cross_wire=cross)
    t = roofline.tier_time_estimate(tiers, world, num_slices, peaks=peaks)
    coll_s = (t["ici_s"] or 0.0) + (t["dcn_s"] or 0.0)
    step_bytes = per_rank_elems * width
    flushes = max(1, -(-step_bytes // max(int(threshold), 1)))
    step_s = compute_s_per_step + coll_s \
        + flushes * FLUSH_OVERHEAD_S + float(cycle_ms) * 1e-3
    return {
        "reduced_bytes": per_rank_elems * 4 * steps_per_epoch,
        "elapsed_s": step_s * steps_per_epoch,
        "dcn_bytes": tiers["dcn"] * steps_per_epoch,
    }


def score_frame(frame, peaks):
    """``controller._score`` verbatim: bytes/sec with the DCN bytes
    priced at the DCN roof in the denominator — the term that makes the
    hierarchy/wire levers separable when wall clock alone cannot."""
    dcn_peak_bps = max(float(peaks.get("dcn_gbs") or 0.0), 1e-3) * 1e9
    dcn_s = frame["dcn_bytes"] / dcn_peak_bps
    return frame["reduced_bytes"] / (frame["elapsed_s"] + dcn_s)


def pretrain(world, num_slices, *, strategy="flat", wire_dtype="",
             per_rank_elems=1 << 20, steps_per_epoch=10,
             compute_s_per_step=1e-3, initial_threshold=64 * 1024,
             initial_cycle_ms=1.0, bayes_opt_max_samples=4,
             max_move_log2=1.0, max_epochs=200, peaks=None):
    """Run the real tuner to convergence on twin-priced epochs and
    return ``{"prior": <export_observations artifact>, ...}``.

    Arguments mirror the live controller's ``_build_pm`` construction
    (zero warmup, one step per sample, bounded moves) so the exported
    prior validates against the space a live manager on the same layout
    builds. ``strategy``/``wire_dtype`` are the job's CONFIGURED values
    (the sweep's tie-break incumbents), not the expected winners."""
    from horovod_tpu.autotune import (ParameterManager,
                                      sweep_categoricals)
    peaks = peaks or roofline.chip_peaks()
    cats = sweep_categoricals(strategy, wire_dtype, int(num_slices) > 1)
    pm = ParameterManager(
        warmup_samples=0, steps_per_sample=1,
        bayes_opt_max_samples=int(bayes_opt_max_samples),
        initial_threshold=int(initial_threshold),
        initial_cycle_ms=float(initial_cycle_ms),
        categorical_knobs=cats, max_move_log2=float(max_move_log2))
    history = []
    epochs = 0
    while pm.tuning and epochs < int(max_epochs):
        knobs = pm.suggest()
        frame = epoch_frame(knobs, world, num_slices,
                            per_rank_elems=per_rank_elems,
                            steps_per_epoch=steps_per_epoch,
                            compute_s_per_step=compute_s_per_step,
                            peaks=peaks)
        score = score_frame(frame, peaks)
        history.append({"epoch": epochs, "categoricals": knobs[2],
                        "fusion_threshold": knobs[0],
                        "cycle_time_ms": round(knobs[1], 4),
                        "score": round(score, 3)})
        pm.observe(score)
        epochs += 1
    return {
        "prior": pm.export_observations(),
        "epochs": epochs,
        "frozen": not pm.tuning,
        "winner": {"categoricals": pm.categoricals,
                   "fusion_threshold": pm.fusion_threshold,
                   "cycle_time_ms": pm.cycle_time_ms},
        "history": history,
        "layout": {"world": int(world), "num_slices": int(num_slices),
                   "per_rank_elems": int(per_rank_elems),
                   "chip": peaks.get("chip")},
    }


def write_prior(path, result):
    """Write a :func:`pretrain` result's prior artifact where
    ``HOROVOD_AUTOPILOT_PRIOR`` / ``hvdrun --autopilot-prior`` point."""
    with open(path, "w") as f:
        json.dump(result["prior"], f, indent=1, sort_keys=True)
        f.write("\n")
