"""hvdsim — the event-driven scale digital twin (ROADMAP item 3).

Virtual ranks as generators over a deterministic event heap
(:mod:`~horovod_tpu.sim.core`), control-plane programs that mirror the
real exchange math (:mod:`~horovod_tpu.sim.control`), and a
twin-pretrained autopilot prior (:mod:`~horovod_tpu.sim.autopilot`).
``python -m horovod_tpu.sim`` runs the scale-guard battery with
lint-style exit codes (docs/scale_validation.md)."""

from horovod_tpu.sim.core import LatencyModel, Simulator, SimTimeout
from horovod_tpu.sim.control import (FLAT_WORLD_CAP, TwinJob,
                                     flat_reference, twin_exchange)

__all__ = [
    "FLAT_WORLD_CAP", "LatencyModel", "SimTimeout", "Simulator",
    "TwinJob", "flat_reference", "twin_exchange",
]
