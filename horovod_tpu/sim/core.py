"""hvdsim core: a deterministic discrete-event simulator.

ROADMAP item 3 — the scale digital twin. PR 14's dryrun guard drives the
real exchange code with one OS thread per virtual rank, which tops out
around n=512 on a small box; the 100k-rank regime the source papers
target (arXiv 2510.20171, 1802.05799) needs two orders of magnitude
more. This module removes the threads: virtual ranks are Python
generators that *yield* their KV operations, a single event heap orders
them on a virtual clock, and each get/put is priced by a small latency
model instead of being executed against wall time. A 65536-rank
hierarchical negotiation round is ~300k heap events — seconds of wall
time, zero threads.

Determinism contract (the twin's whole point): the event order is a pure
function of the spawned programs and the latency model — ties break on a
monotone sequence number, parked getters wake in park order, and nothing
reads wall clock or a shared RNG. Two runs of the same scenario produce
bit-identical event trails, which is what lets tests assert on the twin
like an invariant rather than a benchmark.

The pieces compose with the REAL runtime math, they do not re-model it:
rank programs (:mod:`horovod_tpu.sim.control`) mirror the
``control_plane`` exchange key layout verbatim, chaos triggers come from
:mod:`horovod_tpu.chaos.plan`'s pure seeded functions, and step pricing
comes from ``analysis/cost.py`` / ``profile/roofline.py``.
"""

import dataclasses
import heapq
import os


class SimTimeout(Exception):
    """A virtual KV get expired before the key landed (the twin analogue
    of ``LocalKV.get`` raising ``TimeoutError``)."""


@dataclasses.dataclass
class LatencyModel:
    """Per-operation pricing for the virtual control plane. Deliberately
    simple — the twin's guards are about RPC *counts* and relative
    shapes, not microsecond fidelity: one base cost per KV RPC, a DCN
    surcharge for cross-slice hops, and a bandwidth term for the payload
    bytes. Knobs: ``HOROVOD_SIM_KV_US`` / ``HOROVOD_SIM_DCN_US``
    (docs/scale_validation.md has the tuning notes)."""

    kv_us: float = 5.0       # base cost of one KV RPC (same-shard)
    dcn_us: float = 50.0     # surcharge when the RPC crosses slices
    gbps: float = 1.0        # payload term: value bytes / (gbps GB/s)

    @classmethod
    def from_env(cls, env=None):
        env = env if env is not None else os.environ
        m = cls()
        try:
            m.kv_us = float(env.get("HOROVOD_SIM_KV_US", m.kv_us))
            m.dcn_us = float(env.get("HOROVOD_SIM_DCN_US", m.dcn_us))
        except ValueError:
            pass
        return m

    def seconds(self, cross, nbytes=0):
        """Virtual seconds for one KV RPC: base + optional cross-slice
        surcharge + payload/bandwidth."""
        t = (self.kv_us + (self.dcn_us if cross else 0.0)) * 1e-6
        if nbytes:
            t += nbytes / (max(self.gbps, 1e-9) * 1e9)
        return t


def _nbytes(value):
    if isinstance(value, (str, bytes)):
        return len(value)
    return 0


class Simulator:
    """One negotiation-round-scale event simulation.

    Rank programs are generators yielding operation tuples:

    - ``("put", key, value, cross)`` — publish ``key``; resumes after the
      priced latency (``cross`` prices the DCN surcharge).
    - ``("get", key, cross, timeout_s)`` — resumes with the value once
      the key has landed (immediately after the get latency when it
      already has); raises :class:`SimTimeout` in the generator when
      ``timeout_s`` virtual seconds pass first.
    - ``("advance", dt)`` — sleep ``dt`` virtual seconds.

    ``kv_hook(rank, op, key)`` (optional) is consulted before pricing
    each KV operation and returns ``(extra_delay_s, kill)`` — the chaos
    seam: delays model stragglers/KV faults, ``kill`` terminates the
    rank's program on the spot. The hook must itself be deterministic
    (the chaos plan's trigger functions are).

    The ``trail`` (when recording) is a list of
    ``(t_us, rank, op, key)`` tuples in event order — the bit-identical
    artifact the determinism guard asserts on.
    """

    def __init__(self, latency=None, record_trail=False):
        self.latency = latency or LatencyModel()
        self.now = 0.0
        self.trail = [] if record_trail else None
        self.kv_hook = None
        self.results = {}        # rank -> program return value
        self.finish_t = {}       # rank -> virtual completion time
        self.killed = set()      # ranks terminated by the kv_hook
        self.stats = {"events": 0, "kv_ops": 0, "timeouts": 0}
        self._heap = []          # (t, seq, fn, args)
        self._seq = 0
        self._store = {}
        self._intern = {}        # value dedup (see _land)
        self._waiters = {}       # key -> [waiter dict]
        self._live = 0

    # --- scheduling ----------------------------------------------------

    def _push(self, t, fn, *args):
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, fn, args))

    def _record(self, t, rank, op, key):
        if self.trail is not None:
            self.trail.append((int(round(t * 1e6)), rank, op, key))

    def spawn(self, rank, gen):
        """Register a rank program; it takes its first step at the
        current virtual time."""
        self._live += 1
        self._push(self.now, self._resume, rank, gen, None, None)

    def run(self):
        """Drain the event heap until every spawned program finished.
        Stale timeout events past that point are dropped unprocessed so
        the final virtual times reflect real completions."""
        while self._heap and self._live > 0:
            t, _, fn, args = heapq.heappop(self._heap)
            self.now = t
            self.stats["events"] += 1
            fn(*args)
        self._heap.clear()
        return self.results

    # --- program stepping ----------------------------------------------

    def _resume(self, rank, gen, sendval, exc):
        try:
            if exc is not None:
                op = gen.throw(exc)
            else:
                op = gen.send(sendval)
        except StopIteration as e:
            self._live -= 1
            self.results[rank] = e.value
            self.finish_t[rank] = self.now
            return
        self._dispatch(rank, gen, op)

    def _kill(self, rank, gen):
        gen.close()
        self._live -= 1
        self.killed.add(rank)
        self.finish_t[rank] = self.now
        self._record(self.now, rank, "kill", "")

    def _chaos(self, rank, op, key):
        if self.kv_hook is None:
            return 0.0, False
        delay, kill = self.kv_hook(rank, op, key)
        return float(delay or 0.0), bool(kill)

    def _dispatch(self, rank, gen, op):
        kind = op[0]
        if kind == "put":
            _, key, value, cross = op
            self.stats["kv_ops"] += 1
            delay, kill = self._chaos(rank, "set", key)
            if kill:
                self._kill(rank, gen)
                return
            t1 = self.now + self.latency.seconds(cross, _nbytes(value)) \
                + delay
            self._push(t1, self._land, rank, gen, key, value)
        elif kind == "get":
            _, key, cross, timeout_s = op
            self.stats["kv_ops"] += 1
            delay, kill = self._chaos(rank, "get", key)
            if kill:
                self._kill(rank, gen)
                return
            t_ready = self.now + self.latency.seconds(cross) + delay
            if key in self._store:
                self._record(t_ready, rank, "get", key)
                self._push(t_ready, self._resume, rank, gen,
                           self._store[key], None)
            else:
                waiter = {"rank": rank, "gen": gen, "key": key,
                          "t_ready": t_ready, "done": False}
                self._waiters.setdefault(key, []).append(waiter)
                self._push(t_ready + float(timeout_s), self._expire,
                           waiter)
        elif kind == "advance":
            self._push(self.now + float(op[1]), self._resume, rank, gen,
                       None, None)
        else:
            raise ValueError(f"unknown sim op {kind!r}")

    def _land(self, rank, gen, key, value):
        """A put arrives: store the value, wake parked getters in park
        order, resume the putter. Equal string values are interned to
        one object — at n=65536 every slice leader publishes an EQUAL
        ~1 MB fan-back blob, and deduping them keeps memory O(1) in the
        slice count and makes downstream decode caches O(1)-hot (str
        hashes are cached per object)."""
        if isinstance(value, str):
            value = self._intern.setdefault(value, value)
        self._store[key] = value
        self._record(self.now, rank, "set", key)
        for waiter in self._waiters.pop(key, ()):
            if waiter["done"]:
                continue
            waiter["done"] = True
            t = max(self.now, waiter["t_ready"])
            self._record(t, waiter["rank"], "get", key)
            self._push(t, self._resume, waiter["rank"], waiter["gen"],
                       value, None)
        self._push(self.now, self._resume, rank, gen, None, None)

    def _expire(self, waiter):
        if waiter["done"]:
            return                      # satisfied before the deadline
        waiter["done"] = True
        self.stats["timeouts"] += 1
        self._record(self.now, waiter["rank"], "timeout", waiter["key"])
        self._push(self.now, self._resume, waiter["rank"], waiter["gen"],
                   None, SimTimeout(waiter["key"]))
