"""``python -m horovod_tpu.sim`` — the twin's scale-guard battery.

Lint-style exit codes (0 = every check passed, 1 = a check failed,
2 = usage error), gated in tier-1 under the same <30 s budget as the
self-lint: twin-vs-thread-dryrun parity at a thread-feasible world,
the n=16384 / n=65536 ``exchange_plan`` guards, flat-vs-hier payload
identity, and a double-run determinism check over a chaos plan.

``--pretrain PATH`` instead runs the twin autopilot to convergence and
writes the ``HOROVOD_AUTOPILOT_PRIOR`` artifact (see
docs/scale_validation.md).
"""

import argparse
import json
import sys


def _checks():
    from horovod_tpu.common.control_plane import (exchange_plan,
                                                  simulate_exchange)
    from horovod_tpu.chaos.plan import ChaosPlan, FaultSpec
    from horovod_tpu.sim.control import (TwinJob, flat_reference,
                                         twin_exchange)

    # 1. twin-vs-thread parity: the real exchange code under threads is
    # the ground truth the twin's generators must reproduce.
    thread = simulate_exchange(128, 8, rounds=2)
    twin = twin_exchange(128, 8, rounds=2)
    mismatch = [k for k in ("identical", "gets_total", "payload_bytes",
                            "member_gets_per_round",
                            "leader_gets_per_round")
                if thread[k] != twin[k]]
    if thread["result"] != twin["result"]:
        mismatch.append("result")
    yield ("twin-vs-thread parity n=128 s=8", not mismatch,
           f"diverging fields: {mismatch}" if mismatch else "12 fields")

    # 2/3. scale guards: per-role gets match exchange_plan, payload
    # identical to the analytic flat reference, at thread-infeasible n.
    for world, slices in ((16384, 64), (65536, 256)):
        plan = exchange_plan(world, slices)
        r = twin_exchange(world, slices)
        ok = (r["identical"]
              and r["member_gets_per_round"] == plan["member_gets"]
              and r["leader_gets_per_round"] == plan["leader_gets"]
              and r["gets_total"] == plan["round_gets_total"]
              and r["result"] == flat_reference(world, 0))
        yield (f"scale guard n={world} s={slices}", ok,
               f"member={r['member_gets_per_round']} "
               f"leader={r['leader_gets_per_round']} "
               f"events={r['events']} virtual_s={r['virtual_s']:.4f}")

    # 4. determinism: same (seed, world, slices, plan) twice -> byte-
    # identical trail and report.
    plan = ChaosPlan([
        FaultSpec(site="http_kv.request", kind="delay", p=0.02,
                  delay_ms=25),
        FaultSpec(site="negotiation.exchange", kind="crash", rank=37,
                  at=[1], max_fires=1),
    ], seed=7)
    runs = [TwinJob(256, 8, rounds=4,
                    plan=ChaosPlan.from_dict(plan.to_dict()),
                    record_trail=True).run() for _ in range(2)]
    blobs = [json.dumps(r, sort_keys=True) for r in runs]
    yield ("determinism (2 runs, chaos seed=7)", blobs[0] == blobs[1],
           f"{len(runs[0]['trail'])} trail events, "
           f"final_world={runs[0]['final_world']}")


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.sim", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--pretrain", metavar="PATH",
                   help="write a twin-pretrained autopilot prior "
                        "artifact to PATH and exit")
    p.add_argument("--world", type=int, default=8,
                   help="pretrain layout world size (default 8)")
    p.add_argument("--slices", type=int, default=2,
                   help="pretrain layout slice count (default 2)")
    p.add_argument("--strategy", default="flat",
                   help="pretrain configured/incumbent strategy")
    p.add_argument("--bo-samples", type=int, default=4,
                   dest="bo_samples",
                   help="numeric BO samples before freeze (default 4, "
                        "matching the CPU-tier guard)")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 0 if not e.code else 2

    if args.pretrain:
        from horovod_tpu.sim import autopilot as sim_autopilot
        res = sim_autopilot.pretrain(
            args.world, args.slices, strategy=args.strategy,
            bayes_opt_max_samples=args.bo_samples)
        sim_autopilot.write_prior(args.pretrain, res)
        print(f"twin pretrain: {res['epochs']} epochs, "
              f"winner {res['winner']['categoricals']}, "
              f"prior -> {args.pretrain}")
        return 0 if res["frozen"] else 1

    failed = 0
    for name, ok, detail in _checks():
        tag = "ok" if ok else "FAIL"
        print(f"{tag}: {name} ({detail})")
        failed += 0 if ok else 1
    if failed:
        print(f"{failed} twin check(s) failed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
