"""The control-plane digital twin: virtual ranks over the event heap.

Rank programs here mirror :mod:`horovod_tpu.common.control_plane`'s
``flat_exchange`` / ``hier_exchange`` *statement for statement* — same
key layout (``{base}/{p}``, ``{base}/agg/{s}``, ``{base}/fb/{s}``), same
rotated read order, same raw-JSON string aggregation, same counter
fields — with the blocking KV calls replaced by priced events. The
thread-per-rank dryrun (``simulate_exchange``) stays the ground-truth
anchor: ``tests/test_multiproc.py::TestControlPlaneDryrun`` asserts the
twin's per-role counters equal the thread dryrun's measured ones at
n=128/512 before anything trusts the extrapolation to n=65536.

:class:`TwinJob` composes the rest of the twin on top of one exchange:
chaos faults decided by the REAL :class:`~horovod_tpu.chaos.plan.
FaultSpec` triggers through a rank-keyed
:class:`~horovod_tpu.chaos.plan.TriggerCursor` (kill / straggle /
KV-delay at simulated scale), and elastic membership transitions decided
by the REAL :class:`~horovod_tpu.autopilot.remediate.RemediationPolicy`
running on the twin's virtual clock (``time_fn`` — the fake-clock seam
the policy was built with).
"""

import json

from horovod_tpu.common.control_plane import _rotated_after, exchange_plan
from horovod_tpu.common.topology import slice_layout
from horovod_tpu.sim.core import LatencyModel, Simulator, SimTimeout

# Virtual-seconds deadline for one blocking get. Costs nothing real —
# it only bounds how long a round with a dead participant stays open.
TIMEOUT_S = 30.0

# Full event simulation of the FLAT strategy is O(world^2) events; past
# this world size the twin refuses and points at the analytic
# ``exchange_plan`` (which is exact for flat — that is the point of the
# hierarchy guard).
FLAT_WORLD_CAP = 2048

_COUNTER_KEYS = ("sets", "gets", "attempts", "gets_local", "gets_cross",
                 "gets_fanback")


def flat_reference(world, rnd=0, payload_fn=None):
    """The ordered payload list ONE flat round trivially produces (every
    rank reads every peer's blob in proc order) — the twin's flat-vs-hier
    payload-identity oracle at world sizes where simulating the flat
    O(world^2) fan-out event-by-event is pointless."""
    payload_fn = payload_fn or _default_payload
    return [payload_fn(p, rnd) for p in range(int(world))]


def _default_payload(p, r):
    # The thread dryrun's default payload (control_plane.simulate_exchange)
    return [p + 1, r, p % 7]


def _loads_cached(blob, cache):
    """``json.loads`` with a shared per-call decode cache. Every reader
    decodes the SAME stored string object (the simulator interns landed
    values), so at n=65536 each distinct blob is parsed once instead of
    once per reader — a semantic no-op (results are never mutated) that
    turns the decode fan-out from O(world^2) into O(world)."""
    v = cache.get(blob)
    if v is None:
        v = json.loads(blob)
        cache[blob] = v
    return v


def _flatten_fanback(fanback, cache):
    """The fan-back decode (``[p for g in loads(fb) for p in g]``),
    shared across readers via ``cache`` — every slice publishes an
    equal blob, so all world ranks can share ONE ordered result list."""
    out = cache.get(fanback)
    if out is None:
        out = [p for g in json.loads(fanback) for p in g]
        cache[fanback] = out
    return out


def _flat_program(me, procs, base, blob, slice_size, counters, cache,
                  timeout_s):
    """``control_plane.flat_exchange`` as an event program. The bounded
    short-timeout sweep is a wall-clock head-of-line fix with no virtual
    analogue, so the twin prices every read as the blocking pass;
    ``gets`` (the guard's quantity) is identical, ``attempts`` may be
    lower than a thread run's sweep misses."""
    yield ("put", f"{base}/{me}", blob, False)
    counters["sets"] += 1
    got = {me: blob}
    my_slice = me // slice_size if slice_size else 0
    for p in _rotated_after(procs, me):
        cross = slice_size and (p // slice_size) != my_slice
        got[p] = yield ("get", f"{base}/{p}", bool(cross), timeout_s)
        counters["gets"] += 1
        counters["attempts"] += 1
    return [_loads_cached(got[q], cache) for q in procs]


def _hier_program(me, procs, groups, sid, base, blob, counters, cache,
                  timeout_s):
    """``control_plane.hier_exchange`` as an event program: slice-local
    gather, ONE leaders-only cross-slice round (the DCN-priced gets),
    leader->member fan-back. Same key layout, same JSON string
    aggregation, same counters. ``sid`` is precomputed by the caller
    (the real code's linear group scan is O(world) per rank — fine for
    threads at n<=512, O(world^2) for the twin at 65536)."""
    group = groups[sid]
    leader = group[0]
    yield ("put", f"{base}/{me}", blob, False)
    counters["sets"] += 1
    if me != leader:
        fanback = yield ("get", f"{base}/fb/{sid}", False, timeout_s)
        counters["gets"] += 1
        counters["attempts"] += 1
        counters["gets_fanback"] += 1
        return _flatten_fanback(fanback, cache)
    raw_by_proc = {me: blob}
    for p in _rotated_after(group, me):
        raw_by_proc[p] = yield ("get", f"{base}/{p}", False, timeout_s)
        counters["gets"] += 1
        counters["attempts"] += 1
        counters["gets_local"] += 1
    agg = "[" + ",".join(raw_by_proc[p] for p in group) + "]"
    yield ("put", f"{base}/agg/{sid}", agg, False)
    counters["sets"] += 1
    aggs = []
    for gi in range(len(groups)):
        if gi == sid:
            aggs.append(agg)
        else:
            aggs.append((yield ("get", f"{base}/agg/{gi}", True,
                                timeout_s)))
            counters["gets"] += 1
            counters["attempts"] += 1
            counters["gets_cross"] += 1
    fanback = "[" + ",".join(aggs) + "]"
    yield ("put", f"{base}/fb/{sid}", fanback, False)
    counters["sets"] += 1
    return _flatten_fanback(fanback, cache)


def _round_program(me, procs, groups, sid, base, blob, slice_size,
                   counters, cache, timeout_s):
    """One rank's round, timeout-guarded: a :class:`SimTimeout` (a dead
    or hung peer) aborts the round with a failure result instead of
    crashing the scheduler — the live analogue is the exchange raising
    and the caller re-rendezvousing."""
    try:
        if groups is None:
            out = yield from _flat_program(me, procs, base, blob,
                                           slice_size, counters, cache,
                                           timeout_s)
        else:
            out = yield from _hier_program(me, procs, groups, sid, base,
                                           blob, counters, cache,
                                           timeout_s)
    except SimTimeout as e:
        return {"ok": False, "error": f"timeout waiting for {e}"}
    return {"ok": True, "out": out}


def _layout(procs, num_slices):
    """Slice groups over the sorted live proc list — the thread dryrun's
    grouping rule (contiguous blocks of ``slice_size``), collapsing to
    flat exactly when :func:`topology.slice_layout` does."""
    k, per = slice_layout(len(procs), num_slices or None)
    if k <= 1:
        return None, 1, len(procs)
    groups = [procs[i * per:(i + 1) * per] for i in range(k)]
    return groups, k, per


def twin_exchange(world, num_slices, rounds=1, payload_fn=None,
                  strategy="hier", latency=None, record_trail=False,
                  plan=None):
    """The twin counterpart of ``control_plane.simulate_exchange``: same
    arguments, same result shape (so the two are drop-in comparable),
    plus ``virtual_s`` (priced round latency), ``events`` and — when
    ``record_trail`` — the deterministic event ``trail``. ``plan`` (a
    :class:`~horovod_tpu.chaos.plan.ChaosPlan`) arms KV-site faults; for
    kill/straggle + elastic membership use :class:`TwinJob`."""
    world = int(world)
    procs = list(range(world))
    k, per = slice_layout(world, num_slices or None)
    hier = strategy == "hier" and k > 1
    if not hier and world > FLAT_WORLD_CAP:
        raise ValueError(
            f"flat twin exchange at n={world} would be O(world^2) "
            f"events; past n={FLAT_WORLD_CAP} price it analytically "
            "with control_plane.exchange_plan instead")
    groups = [procs[i * per:(i + 1) * per] for i in range(k)] if hier \
        else None
    payload_fn = payload_fn or _default_payload
    counters = [dict.fromkeys(_COUNTER_KEYS, 0) for _ in procs]
    payload_bytes = [0] * world
    outs = [None] * world
    virtual_s = 0.0
    events = 0
    timeouts = 0
    trail = [] if record_trail else None
    cursor = None
    if plan is not None:
        from horovod_tpu.chaos.plan import TriggerCursor
        cursor = TriggerCursor(plan)

    for r in range(rounds):
        sim = Simulator(latency=latency or LatencyModel.from_env(),
                        record_trail=record_trail)
        if cursor is not None:
            sim.kv_hook = _kv_chaos_hook(cursor, r)
        base = f"sim/{r}"
        cache = {}
        for p in procs:
            blob = json.dumps(payload_fn(p, r))
            payload_bytes[p] += len(blob)
            sim.spawn(p, _round_program(p, procs, groups,
                                        p // per if hier else 0, base,
                                        blob, per if hier else 0,
                                        counters[p], cache, TIMEOUT_S))
        results = sim.run()
        for p in procs:
            res = results.get(p) or {"ok": False, "error": "killed"}
            outs[p] = res.get("out") if res.get("ok") else None
        virtual_s += max(sim.finish_t.values()) if sim.finish_t else 0.0
        events += sim.stats["events"]
        timeouts += sim.stats["timeouts"]
        if record_trail:
            trail.extend((r,) + e for e in sim.trail)

    # `is` shortcut first: the shared fan-back decode makes every rank's
    # result ONE list object at scale — a full O(world^2) elementwise
    # compare would defeat the decode cache.
    first = outs[0]
    identical = first is not None and all(
        o is first or o == first for o in outs)
    leaders = [g[0] for g in groups] if groups else []
    member_gets = [counters[p]["gets"] for p in procs
                   if p not in leaders] if groups else \
        [counters[p]["gets"] for p in procs]
    leader_gets = [counters[p]["gets"] for p in leaders]
    out = {
        "world": world, "num_slices": k if hier else 1,
        "slice_size": per if hier else world,
        "strategy": "hier" if hier else "flat", "rounds": rounds,
        "identical": identical, "per_proc": counters,
        "payload_bytes": sum(payload_bytes),
        "gets_total": sum(c["gets"] for c in counters),
        "member_gets_per_round": (max(member_gets) / rounds)
        if member_gets else 0.0,
        "leader_gets_per_round": (max(leader_gets) / rounds)
        if leader_gets else 0.0,
        "result": outs[0],
        "virtual_s": virtual_s, "events": events, "timeouts": timeouts,
        "plan": exchange_plan(world, k if hier else 1),
    }
    if record_trail:
        out["trail"] = trail
    return out


def _kv_chaos_hook(cursor, step):
    """Adapt the plan's ``http_kv.request`` site to the simulator's KV
    hook: ``delay`` prices the injected latency, ``drop``/``http_5xx``
    price one retry backoff (the client's retry loop absorbs them),
    ``crash`` kills the rank, ``hang`` parks it past any round
    deadline."""
    def hook(rank, op, key):
        delay = 0.0
        kill = False
        for spec in cursor.decide("http_kv.request", rank, step):
            if spec.kind == "crash":
                kill = True
            elif spec.kind == "hang":
                delay += float(spec.hang_s)
            else:                 # delay / drop / http_5xx: priced retry
                delay += float(spec.delay_ms) / 1e3
        return delay, kill
    return hook


class TwinJob:
    """A multi-round control-plane job at simulated scale: per round one
    negotiation exchange over the surviving members, chaos verdicts from
    the plan's pure triggers, health verdicts fed to the REAL
    :class:`RemediationPolicy` on the virtual clock, and membership
    shrink applied exactly where the live stack would re-rendezvous.

    Deterministic end to end: same ``(plan seed, world, slices)`` →
    bit-identical event trail and report across runs."""

    def __init__(self, world, num_slices, rounds=4, plan=None,
                 latency=None, payload_fn=None, record_trail=False,
                 hysteresis=2, max_removals=4, min_world=1,
                 round_gap_s=30.0, straggle_factor=3.0):
        from horovod_tpu.autopilot.remediate import RemediationPolicy
        from horovod_tpu.chaos.plan import TriggerCursor
        self.world0 = int(world)
        self.num_slices = int(num_slices)
        self.rounds = int(rounds)
        self.latency = latency or LatencyModel.from_env()
        self.payload_fn = payload_fn or _default_payload
        self.record_trail = record_trail
        self.round_gap_s = float(round_gap_s)
        self.straggle_factor = float(straggle_factor)
        self.t = 0.0                       # the job's virtual clock
        self.cursor = TriggerCursor(plan)
        self.policy = RemediationPolicy(
            hysteresis=hysteresis, max_removals=max_removals,
            min_world=min_world, protected=(0,),
            time_fn=lambda: self.t)        # the fake-clock seam
        self._dead = set()

    def _chaos_round_start(self, procs, rnd):
        """``negotiation.exchange``-site faults at round start: crash
        and hang take the rank out of the round (and every later one —
        a crashed process does not come back), delay defers its entry."""
        delays = {}
        for rank in procs:
            if rank in self._dead:
                continue
            for spec in self.cursor.decide("negotiation.exchange", rank,
                                           rnd):
                if spec.kind in ("crash", "hang"):
                    self._dead.add(rank)
                elif spec.kind == "delay":
                    delays[rank] = delays.get(rank, 0.0) \
                        + float(spec.delay_ms) / 1e3
        return delays

    def _delayed(self, program, delay_s):
        yield ("advance", delay_s)
        res = yield from program
        return res

    def run(self):
        live = list(range(self.world0))
        rounds_out = []
        membership = []
        trail = [] if self.record_trail else None
        stats = {"events": 0, "kv_ops": 0, "timeouts": 0}
        for rnd in range(self.rounds):
            procs = list(live)
            groups, k, per = _layout(procs, self.num_slices)
            delays = self._chaos_round_start(procs, rnd)
            sim = Simulator(latency=self.latency,
                            record_trail=self.record_trail)
            sim.kv_hook = _kv_chaos_hook(self.cursor, rnd)
            base = f"job/{rnd}"
            counters = {p: dict.fromkeys(_COUNTER_KEYS, 0)
                        for p in procs}
            cache = {}
            sid_of = {}
            if groups is not None:
                for gi, g in enumerate(groups):
                    for p in g:
                        sid_of[p] = gi
            for p in procs:
                if p in self._dead:
                    continue               # dead ranks publish nothing
                blob = json.dumps(self.payload_fn(p, rnd))
                prog = _round_program(p, procs, groups, sid_of.get(p, 0),
                                      base, blob, per if groups else 0,
                                      counters[p], cache, TIMEOUT_S)
                if p in delays:
                    prog = self._delayed(prog, delays[p])
                sim.spawn(p, prog)
            results = sim.run()
            self._dead |= sim.killed
            for key in stats:
                stats[key] += sim.stats[key]
            if self.record_trail:
                trail.extend((rnd,) + e for e in sim.trail)

            ok = {p for p, res in results.items()
                  if res and res.get("ok")}
            duration = max(sim.finish_t.values()) if sim.finish_t else 0.0
            verdicts = self._verdicts(procs, ok, sim.finish_t)
            self.t += duration + self.round_gap_s
            actions = self.policy.observe(verdicts, world=len(live),
                                          host_sizes=None)
            for action in actions:
                if action["rank"] in live:
                    live.remove(action["rank"])
                membership.append({"round": rnd, "t": round(self.t, 6),
                                   **action})
            rounds_out.append({
                "round": rnd, "world": len(procs),
                "num_slices": k if groups else 1,
                "strategy": "hier" if groups else "flat",
                "ok": len(ok), "failed": len(procs) - len(ok),
                "virtual_s": round(duration, 9),
                "worst_gets": max((c["gets"]
                                   for c in counters.values()),
                                  default=0),
            })
        report = {
            "world0": self.world0, "num_slices": self.num_slices,
            "rounds": rounds_out, "membership": membership,
            "final_world": len(live), "dead": sorted(self._dead),
            "virtual_s": round(self.t, 6),
            "chaos_fires": list(self.cursor.log), "stats": stats,
        }
        if self.record_trail:
            report["trail"] = trail
        return report

    def _verdicts(self, procs, ok, finish_t):
        """This round's health verdicts: a dead/hung rank is named
        ``dead`` (one host per rank — the worst-case layout the policy's
        host accounting degenerates to), a finisher far beyond the
        median is named ``straggler``."""
        verdicts = {}
        for p in procs:
            if p in self._dead:
                verdicts[p] = {"cause": "dead", "host": f"h{p}"}
        finishers = sorted(finish_t[p] for p in ok if p in finish_t)
        if finishers:
            median = finishers[len(finishers) // 2]
            floor = max(self.straggle_factor * median, median + 1e-3)
            for p in ok:
                if finish_t.get(p, 0.0) > floor:
                    verdicts[p] = {"cause": "straggler", "host": f"h{p}"}
        return verdicts
