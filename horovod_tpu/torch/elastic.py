"""Elastic state handlers and sampler for the torch frontend.

Reference: horovod/torch/elastic/state.py (TorchState with
ModelStateHandler/OptimizerStateHandler :30-255) and elastic/sampler.py
(ElasticSampler :24). State commit/restore is in-memory (deep copies); sync
broadcasts rank-0's state on rejoin.
"""

import copy

import torch

from horovod_tpu.elastic.state import ObjectState
from horovod_tpu.elastic.state import run  # noqa: F401  (re-export;
#   reference: horovod/torch/elastic/__init__.py:23 def run)
from horovod_tpu.torch.functions import (broadcast_object,
                                         broadcast_optimizer_state,
                                         broadcast_parameters)


class TorchState(ObjectState):
    """Elastic state wrapping torch models/optimizers plus arbitrary python
    attributes (reference: torch/elastic/state.py:30-125: model/optimizer get
    dedicated handlers, the rest ride the object-broadcast path)."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        self._saved_model_state = None
        self._saved_optimizer_state = None
        super().__init__(bcast_object=broadcast_object, model=model,
                         optimizer=optimizer, **kwargs)
        # model/optimizer are synced by their handlers below, not by the
        # pickled-object path.
        self._saved_state.pop("model", None)
        self._saved_state.pop("optimizer", None)

    def save(self):
        if self.model is not None:
            self._saved_model_state = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._saved_optimizer_state = copy.deepcopy(
                self.optimizer.state_dict())
        super().save()

    def restore(self):
        if self.model is not None and self._saved_model_state is not None:
            self.model.load_state_dict(self._saved_model_state)
        if self.optimizer is not None and \
                self._saved_optimizer_state is not None:
            self.optimizer.load_state_dict(self._saved_optimizer_state)
        super().restore()

    def sync(self):
        if self.model is not None:
            broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            broadcast_optimizer_state(self.optimizer, root_rank=0)
        super().sync()


class ElasticSampler(torch.utils.data.Sampler):
    """Shards a dataset over ranks and tracks processed indices so a resized
    job resumes mid-epoch without revisiting data
    (reference: torch/elastic/sampler.py:24-121).
    """

    def __init__(self, dataset, shuffle=True, seed=0):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices = set()

        from horovod_tpu.common import basics
        self.rank = basics.rank()
        self.num_replicas = basics.size()
        self.remaining_indices = []
        self.reset()

    def set_epoch(self, epoch):
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx, batch_size):
        start = batch_idx * batch_size
        new = set(self.indices[start:start + batch_size])
        self.processed_indices |= new

    def reset(self):
        """Recompute this rank's shard from the not-yet-processed remainder —
        called after set_epoch and on elastic resize (the rank/size may have
        changed)."""
        from horovod_tpu.common import basics
        self.rank = basics.rank()
        self.num_replicas = basics.size()

        all_indices = [i for i in range(len(self.dataset))
                       if i not in self.processed_indices]
        if self.shuffle:
            g = torch.Generator()
            g.manual_seed(self.seed + self.epoch)
            perm = torch.randperm(len(all_indices), generator=g).tolist()
            all_indices = [all_indices[i] for i in perm]
        # Pad so every rank draws the same number of samples.
        total = len(all_indices)
        if total % self.num_replicas:
            pad = self.num_replicas - total % self.num_replicas
            all_indices += all_indices[:pad]
        self.num_samples = len(all_indices) // self.num_replicas
        self.indices = all_indices[self.rank::self.num_replicas]

    def state_dict(self):
        return {"epoch": self.epoch,
                "processed_indices": sorted(self.processed_indices)}

    def load_state_dict(self, state):
        self.epoch = state["epoch"]
        self.processed_indices = set(state["processed_indices"])
        self.reset()

    def __iter__(self):
        return iter(self.indices)

    def __len__(self):
        return self.num_samples
