"""PyTorch frontend — the ``horovod.torch`` API surface over the TPU core.

Reference surface: horovod/torch/__init__.py + mpi_ops.py (134-1285) +
optimizer.py (517) + functions.py + compression.py + elastic/.

Design (TPU-native, not a port): torch here is a *host-side frontend*. Torch
tensors are bridged to the XLA data plane through the eager collective
runtime — each host's tensor rides that host's chips (its value is replicated
onto the local mesh slices), so a chip-axis Average equals the cross-host
average the reference computes with NCCL/MPI. There is no per-tensor C++
enqueue path because there is no background scheduler to feed; dispatch is
JAX's async dispatch, and handles wrap in-flight device arrays
(reference: horovod/torch/handle_manager.h).
"""

from horovod_tpu.common.basics import (init, shutdown, is_initialized, rank,
                                       local_rank, cross_rank, size,
                                       local_size, cross_size,
                                       mpi_threads_supported, mpi_enabled,
                                       mpi_built, gloo_enabled, gloo_built,
                                       nccl_built, ddl_built, ccl_built,
                                       cuda_built, rocm_built, xla_built,
                                       ici_built, is_homogeneous,
                                       start_timeline, stop_timeline)
from horovod_tpu.common.exceptions import HorovodInternalError
from horovod_tpu.common.util import (check_extension, check_installed_version,
                                     gpu_available, num_rank_is_power_2,
                                     split_list)
from horovod_tpu.elastic.worker import (mark_new_rank_ready,
                                        read_new_rank_ready)
from horovod_tpu.common.process_sets import (ProcessSet, add_process_set,
                                             global_process_set,
                                             process_set_by_id,
                                             remove_process_set)
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            ReduceOp, Sum)
from horovod_tpu.torch.compression import (Compression, Compressor,
                                           FP16Compressor, NoneCompressor)
from horovod_tpu.torch.functions import (allgather_object, broadcast_object,
                                         broadcast_optimizer_state,
                                         broadcast_parameters)
from horovod_tpu.torch.mpi_ops import (allgather, allgather_async, allreduce,
                                       allreduce_, allreduce_async,
                                       allreduce_async_, alltoall,
                                       alltoall_async, barrier, broadcast,
                                       broadcast_, broadcast_async,
                                       broadcast_async_, grouped_allgather,
                                       grouped_allgather_async,
                                       grouped_allreduce, grouped_allreduce_,
                                       grouped_allreduce_async,
                                       grouped_allreduce_async_,
                                       grouped_reducescatter,
                                       grouped_reducescatter_async, join,
                                       poll, reducescatter,
                                       reducescatter_async,
                                       sparse_allreduce_async, synchronize)
from horovod_tpu.torch.optimizer import DistributedOptimizer
from horovod_tpu.torch.elastic import ElasticSampler, TorchState
from horovod_tpu.torch.sync_batch_norm import SyncBatchNorm

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "cross_rank",
    "size", "local_size", "cross_size", "ProcessSet", "add_process_set",
    "global_process_set", "process_set_by_id", "remove_process_set",
    "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max", "Product",
    "Compression", "allreduce", "allreduce_", "allreduce_async",
    "allreduce_async_", "grouped_allreduce", "grouped_allreduce_async",
    "allgather", "allgather_async", "grouped_allgather", "broadcast",
    "broadcast_", "broadcast_async", "broadcast_async_", "alltoall",
    "alltoall_async", "reducescatter", "reducescatter_async",
    "grouped_reducescatter", "barrier", "join", "poll", "synchronize",
    "broadcast_parameters", "broadcast_optimizer_state", "broadcast_object",
    "allgather_object", "DistributedOptimizer", "ElasticSampler",
    "TorchState", "SyncBatchNorm",
    "grouped_allreduce_", "grouped_allreduce_async_",
    "grouped_allgather_async", "grouped_reducescatter_async",
    "sparse_allreduce_async", "Compressor", "NoneCompressor",
    "FP16Compressor", "HorovodInternalError", "check_extension",
    "check_installed_version", "gpu_available", "num_rank_is_power_2",
    "split_list", "is_homogeneous", "mpi_threads_supported",
    "start_timeline", "stop_timeline", "xla_built", "ici_built",
    "mark_new_rank_ready", "read_new_rank_ready",
]
