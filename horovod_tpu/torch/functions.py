"""State broadcast helpers for the torch frontend.

Reference: horovod/torch/functions.py (broadcast_parameters,
broadcast_optimizer_state, broadcast_object) — used at train start and on
elastic rejoin so every host begins from rank-0's state.
"""

import torch

from horovod_tpu.ops import collective_ops as C
from horovod_tpu.torch.mpi_ops import broadcast_


def broadcast_parameters(params, root_rank=0, process_set=None):
    """Broadcast a ``state_dict()`` or ``named_parameters`` iterable from
    root (reference: functions.py:36-84)."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    for name, p in items:
        if p is None or not isinstance(p, torch.Tensor):
            continue
        broadcast_(p.data if p.requires_grad else p, root_rank,
                   name=f"broadcast.{name}", process_set=process_set)


def broadcast_object(obj, root_rank=0, name=None, process_set=None):
    """Pickle-and-broadcast an arbitrary object (reference:
    functions.py:187-230)."""
    return C.broadcast_object(obj, root_rank=root_rank, name=name,
                              process_set=process_set)


def allgather_object(obj, name=None, process_set=None):
    """reference: functions.py:233-260 — returns the list of every rank's
    object. The caller's ``obj`` stands for each rank this process owns
    (all of them single-controller, the local chips multi-process)."""
    return C.allgather_object_single(obj, process_set=process_set,
                                     name=name)


def broadcast_optimizer_state(optimizer, root_rank=0, process_set=None):
    """Broadcast optimizer hyperparameters and per-parameter state tensors
    from root (reference: functions.py:87-184: state_dict walk, scalars ride
    a pickled blob, tensors ride broadcast)."""
    state = optimizer.state_dict()
    scalars = {}
    tensors = {}

    for gi, group in enumerate(state.get("param_groups", [])):
        for k, v in group.items():
            if k != "params":
                scalars[f"group.{gi}.{k}"] = v
    for pid, pstate in state.get("state", {}).items():
        for k, v in pstate.items():
            key = f"state.{pid}.{k}"
            if isinstance(v, torch.Tensor):
                tensors[key] = v
            else:
                scalars[key] = v

    synced = broadcast_object(scalars, root_rank=root_rank,
                              name="opt_scalars", process_set=process_set)
    for key, t in tensors.items():
        broadcast_(t, root_rank, name=f"opt.{key}", process_set=process_set)

    for gi, group in enumerate(state.get("param_groups", [])):
        for k in list(group.keys()):
            if k != "params":
                group[k] = synced[f"group.{gi}.{k}"]
    for pid, pstate in state.get("state", {}).items():
        for k in list(pstate.keys()):
            if not isinstance(pstate[k], torch.Tensor):
                pstate[k] = synced[f"state.{pid}.{k}"]
    optimizer.load_state_dict(state)
