"""Gradient compression for the torch frontend.

Reference: horovod/torch/compression.py (NoneCompressor/FP16Compressor
selected via ``hvd.Compression.fp16``). Operates on the numpy bridge arrays
(what actually crosses to the device), with bf16 added — the TPU-native wire
dtype.
"""

import numpy as np


class _NoneCompressor:
    @staticmethod
    def compress(a):
        return a, None

    @staticmethod
    def decompress(t, ctx):
        return t


class _CastCompressor:
    """Compress by casting to a 16-bit wire dtype; decompress restores the
    original dtype on the returned torch tensor."""

    def __init__(self, np_dtype_getter):
        self._get = np_dtype_getter

    def compress(self, a):
        a = np.asarray(a)
        if a.dtype in (np.float32, np.float64):
            return a.astype(self._get()), a.dtype
        return a, None

    def decompress(self, t, ctx):
        import torch
        if ctx is None:
            return t
        return t.to(torch.float32 if ctx == np.float32 else torch.float64)


class _Int8WireCompressor:
    """Routes the next collective through the quantized wire tier
    (horovod_tpu/ops/wire.py): the int8 block-scaled exchange happens
    INSIDE the collective (compress() arms a one-shot request the eager
    dispatch consumes), so the bridge array itself is untouched."""

    @staticmethod
    def compress(a):
        from horovod_tpu.ops import wire
        wire.request_wire_once("int8")
        return a, None

    @staticmethod
    def decompress(t, ctx):
        return t


class Compression:
    """reference: hvd.Compression registry (torch/compression.py:64-74)."""
    none = _NoneCompressor()
    fp16 = _CastCompressor(lambda: np.float16)
    bf16 = _CastCompressor(lambda: __import__("ml_dtypes").bfloat16)
    int8 = _Int8WireCompressor()


class Compressor:
    """Abstract compressor interface (reference: torch/compression.py:21-33).
    Implementations provide ``compress(tensor) -> (tensor, ctx)`` and
    ``decompress(tensor, ctx) -> tensor``."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


# Reference-parity aliases (reference: torch/compression.py class names).
# Bound INSTANCES, not the raw classes: reference code passes these directly
# as `compression=hvd.FP16Compressor`, so they must be usable as-is
# (_CastCompressor itself needs a dtype getter at construction).
NoneCompressor = Compression.none
FP16Compressor = Compression.fp16
BF16Compressor = Compression.bf16
