"""Synchronized BatchNorm for torch models.

Reference: horovod/torch/sync_batch_norm.py (218 LoC — `SyncBatchNorm`
module whose forward allreduces mean/var/count and whose custom autograd
backward allreduces the two gradient reduction terms `sum_dy` and
`sum_dy_xmu`). Same math here, with the collectives riding the XLA eager
bridge (horovod_tpu/torch/mpi_ops.py) instead of the C++ enqueue path.
"""

import torch
from torch.autograd.function import Function
from torch.nn.modules.batchnorm import _BatchNorm

from horovod_tpu.torch import mpi_ops


class SyncBatchNorm(_BatchNorm):
    """BatchNorm with cross-rank statistics
    (reference: torch/sync_batch_norm.py SyncBatchNorm).

    During training, batch statistics are averaged over all ranks so small
    per-rank batches normalize with global statistics; eval uses the running
    stats as usual.
    """

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True, process_set=None):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)
        self.process_set = process_set

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)")

    def forward(self, input):
        self._check_input_dim(input)
        momentum = self.momentum
        if self.training and self.track_running_stats:
            if self.num_batches_tracked is not None:
                self.num_batches_tracked.add_(1)
                if momentum is None:
                    # Cumulative moving average, the _BatchNorm contract for
                    # momentum=None.
                    momentum = 1.0 / float(self.num_batches_tracked)

        if not self.training and self.track_running_stats:
            return torch.nn.functional.batch_norm(
                input, self.running_mean, self.running_var, self.weight,
                self.bias, False, 0.0, self.eps)
        # Training — or eval without running stats, where _BatchNorm
        # normalizes with (synced) batch statistics.
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias,
            self.running_mean if self.training else None,
            self.running_var if self.training else None,
            self.eps, momentum, self.process_set)


class _SyncBatchNormFn(Function):
    """reference: torch/sync_batch_norm.py _SyncBatchNorm Function."""

    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum, process_set):
        x = input.contiguous()
        reduce_dims = [0] + list(range(2, x.dim()))
        count = x.numel() // x.shape[1]

        local_mean = x.mean(dim=reduce_dims)
        local_sqmean = (x * x).mean(dim=reduce_dims)
        # Average over ranks == global moments (equal per-rank counts, the
        # reference's count-weighted path reduces to this under the bridge's
        # replicated-host model).
        stats = torch.cat([local_mean, local_sqmean]).detach()
        stats = mpi_ops.allreduce(stats, op=mpi_ops.Average,
                                  process_set=process_set,
                                  name="sync_batch_norm.stats")
        mean, sqmean = stats[:x.shape[1]], stats[x.shape[1]:]
        var = sqmean - mean * mean
        invstd = torch.rsqrt(var + eps)

        # Distinct samples live per HOST (the bridge replicates a host's
        # tensor onto its chips), so global sample counts scale by the
        # number of hosts, not chips.
        world = max(1, mpi_ops.basics.cross_size())
        if running_mean is not None:
            n_total = count * world
            unbiased = var * (n_total / max(n_total - 1, 1))
            running_mean.mul_(1 - momentum).add_(momentum * mean.detach())
            running_var.mul_(1 - momentum).add_(momentum * unbiased.detach())

        shape = [1, -1] + [1] * (x.dim() - 2)
        xhat = (x - mean.reshape(shape)) * invstd.reshape(shape)
        out = xhat
        if weight is not None:
            out = out * weight.reshape(shape)
        if bias is not None:
            out = out + bias.reshape(shape)

        ctx.save_for_backward(x, weight, mean, invstd)
        ctx.process_set = process_set
        # Backward divisor: a chip-axis Sum counts each host's contribution
        # local_size times, so the normalizer is count * chips (not hosts).
        ctx.n_total = count * (process_set.size() if process_set is not None
                               else mpi_ops.basics.size())
        return out

    @staticmethod
    def backward(ctx, grad_output):
        x, weight, mean, invstd = ctx.saved_tensors
        process_set = ctx.process_set
        g = grad_output.contiguous()
        reduce_dims = [0] + list(range(2, x.dim()))
        shape = [1, -1] + [1] * (x.dim() - 2)

        xmu = x - mean.reshape(shape)
        sum_dy = g.sum(dim=reduce_dims)
        sum_dy_xmu = (g * xmu).sum(dim=reduce_dims)

        # The two reduction terms are means over the GLOBAL batch
        # (reference: backward allreduces sum_dy/sum_dy_xmu then divides by
        # the global count).
        red = torch.cat([sum_dy, sum_dy_xmu]).detach()
        red = mpi_ops.allreduce(red, op=mpi_ops.Sum, process_set=process_set,
                                name="sync_batch_norm.grads")
        mean_dy = red[:x.shape[1]] / ctx.n_total
        mean_dy_xmu = red[x.shape[1]:] / ctx.n_total

        gamma = weight if weight is not None else torch.ones_like(mean)
        grad_input = (
            g - mean_dy.reshape(shape)
            - xmu * (invstd * invstd * mean_dy_xmu).reshape(shape)
        ) * (invstd * gamma).reshape(shape)

        grad_weight = None
        if weight is not None and ctx.needs_input_grad[1]:
            grad_weight = (g * xmu * invstd.reshape(shape)).sum(
                dim=reduce_dims)
        grad_bias = None
        if ctx.needs_input_grad[2]:
            grad_bias = sum_dy

        return grad_input, grad_weight, grad_bias, None, None, None, None, \
            None
