"""DistributedOptimizer for torch.optim optimizers.

Reference: horovod/torch/optimizer.py (:132-344): per-parameter
grad-accumulator hooks fire an async (grouped) allreduce as each gradient
becomes ready during backward, overlapping communication with the rest of the
backward pass; ``synchronize()`` drains the handles before ``step()``;
``backward_passes_per_step`` accumulates locally before reducing.

TPU-native mechanics: hooks use torch's post-accumulate-grad hook; the
"communication" is the eager XLA allreduce bridge (mpi_ops), whose dispatch is
already asynchronous — so the overlap structure (enqueue per-ready-gradient,
drain at step) carries over 1:1 without a background thread.
"""

import torch

from horovod_tpu.flight import recorder as _flight
from horovod_tpu.torch import mpi_ops
from horovod_tpu.torch.compression import Compression
from horovod_tpu.torch.mpi_ops import Average, Sum


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step, op, process_set,
                 gradient_predivide_factor):
        super(self.__class__, self).__init__(params)
        self._compression = compression or Compression.none
        self._op = op
        self._process_set = process_set
        self._backward_passes_per_step = backward_passes_per_step
        self._gradient_predivide_factor = gradient_predivide_factor

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
            for gi, group in enumerate(self.param_groups):
                for pi, p in enumerate(group["params"]):
                    named.append((f"group{gi}.param{pi}", p))
        self._param_names = {p: name for name, p in named}

        self._handles = {}
        self._grad_accs = []
        self._passes = {}
        self._synchronized = False
        self._should_synchronize = True
        self._register_hooks()

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._passes[p] = 0
                    acc = p.register_post_accumulate_grad_hook(
                        self._make_hook())
                    self._grad_accs.append(acc)

    def _remove_hooks(self):
        """Deregister the gradient hooks — retire this wrapper. A caller
        that wraps a NEW optimizer over the same parameters (e.g. a
        second estimator fit calling configure_optimizers again) must
        retire the old wrapper first, or both hooks fire per backward
        and the orphaned one trips the duplicate-reduction check."""
        for acc in self._grad_accs:
            acc.remove()
        self._grad_accs = []
        self._handles.clear()

    def _make_hook(self):
        def hook(p):
            self._passes[p] += 1
            if self._passes[p] < self._backward_passes_per_step:
                return  # local aggregation; reduce on the final pass
            self._passes[p] = 0
            if p in self._handles:
                raise AssertionError(
                    "gradient reduced twice before step(); call "
                    "synchronize() between backward passes or raise "
                    "backward_passes_per_step "
                    "(matches reference optimizer.py duplicate-hook check)")
            self._handles[p] = self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._param_names.get(p, "param")
        prescale = 1.0
        postscale = 1.0
        op = self._op
        if self._gradient_predivide_factor != 1.0 and op == Average:
            # reference: gradient_predivide_factor splits the averaging
            # between pre- and post-scale (optimizer.py:188-200).
            prescale = 1.0 / self._gradient_predivide_factor
            postscale = self._gradient_predivide_factor / \
                self._process_size()
            op = Sum
        if self._backward_passes_per_step > 1:
            prescale = prescale / self._backward_passes_per_step
        return mpi_ops.allreduce_async(
            p.grad, op=op, name=f"allreduce.{name}",
            compression=self._compression, prescale_factor=prescale,
            postscale_factor=postscale, process_set=self._process_set)

    def _process_size(self):
        ps = self._process_set
        if ps is None:
            from horovod_tpu.common import basics
            return basics.size()
        return ps.size()

    def synchronize(self):
        """Drain outstanding reductions into ``p.grad``
        (reference: optimizer.py:256-304)."""
        for p, handle in list(self._handles.items()):
            out = handle.synchronize()
            p.grad.copy_(out.to(p.grad.dtype))
        self._handles.clear()
        self._synchronized = True

    def step(self, closure=None):
        if self._should_synchronize:
            self.synchronize()
        self._synchronized = False
        # Automatic step annotation: step() is the host-side training
        # step boundary, so the flight ring's step spans need no user
        # instrumentation on this frontend. Not gated on _flight.armed:
        # step_marker also feeds the step profiler's ledger (its own
        # switch) and applies the flight gate itself.
        _flight.step_marker()
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "zero_grad() with reductions in flight would race the "
                "gradient writeback; call step() or synchronize() first "
                "(matches reference optimizer.py:306-315)")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average,
                         gradient_predivide_factor=1.0, process_set=None):
    """Wrap a torch optimizer so gradients are averaged across hosts before
    each step (reference: hvd.DistributedOptimizer torch/optimizer.py:517).

    The returned object is a dynamically-created subclass of the wrapped
    optimizer's class (same trick as the reference) so isinstance checks and
    LR schedulers keep working.
    """
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    dist = cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, process_set,
               gradient_predivide_factor)
    # Preserve the wrapped optimizer's per-parameter state (momentum/Adam
    # buffers, e.g. restored from a checkpoint) — param_groups share the same
    # parameter objects, so the state transfers keyed as-is.
    for p, s in optimizer.state.items():
        dist.state[p] = s
    return dist
