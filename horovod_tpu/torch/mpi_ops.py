"""Torch-tensor collectives bridged to the XLA eager runtime.

Reference: horovod/torch/mpi_ops.py (sync + async wrappers, handles
:1245-1283) and torch/mpi_ops_v2.cc (per-dtype enqueue functions).

Semantics: the input is this *host's* tensor (per-rank layout, exactly like
the reference — NOT the stacked layout of the JAX eager API). The bridge
replicates it onto the host's local mesh slices, runs the chip-axis
collective, and returns a torch tensor. With one process the reduction over
identical slices is computed on-device and returns the mathematically
identical result the reference's np=1 path returns; with multiple hosts each
host contributes its own value, so chip-axis Average == cross-host average
(uniform chips per host).

torch.bfloat16/float16 cross numpy via a uint16 view (numpy has no bf16).
"""

import numpy as np
import torch

from horovod_tpu.common import basics
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            ReduceOp, Sum)

__all__ = ["allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
           "grouped_allreduce", "grouped_allreduce_async", "allgather",
           "allgather_async", "grouped_allgather", "broadcast", "broadcast_",
           "broadcast_async", "broadcast_async_", "alltoall", "alltoall_async",
           "reducescatter", "reducescatter_async", "grouped_reducescatter",
           "barrier", "join", "poll", "synchronize",
           "Average", "Sum", "Adasum", "Min", "Max", "Product", "ReduceOp"]


def _to_numpy(t):
    """torch.Tensor -> (numpy array, restore_info)."""
    if not isinstance(t, torch.Tensor):
        t = torch.as_tensor(t)
    t = t.detach()
    if t.device.type != "cpu":
        t = t.cpu()
    if t.dtype == torch.bfloat16:
        import ml_dtypes
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16), t.dtype
    return t.numpy(), t.dtype


def _to_torch(a, torch_dtype):
    a = np.asarray(a)
    if a.dtype.name == "bfloat16":
        # numpy has no native bf16 (this is ml_dtypes.bfloat16, e.g. off the
        # bf16 compression wire) — reinterpret the bits into torch.bfloat16.
        t = torch.from_numpy(a.view(np.uint16).copy()).view(torch.bfloat16)
        return t if torch_dtype == torch.bfloat16 else t.to(torch_dtype)
    # Copy: JAX-backed numpy views are read-only. Cast back to the caller's
    # dtype — float64 runs on-device as float32 (x64 is off by default on
    # TPU), so the wire dtype narrows but the torch-facing dtype is stable.
    return torch.from_numpy(a.copy()).to(torch_dtype)


def _stack_for_mesh(a, ps):
    """Replicate the host tensor onto this controller's mesh slices.

    Single-controller: leading axis == set size (every chip carries the
    host's value). Multi-process: only the local chips' rows — the eager
    stacked contract of ``collective_ops._prepare`` (docs/api.md)."""
    n_rows = C._expected_rows(ps.mesh, ps.size())
    return np.broadcast_to(a, (n_rows,) + a.shape)


def _unstack(out, torch_dtype):
    return _to_torch(np.asarray(out)[0], torch_dtype)


class _TorchHandle:
    """reference: HandleManager int handles + poll/synchronize
    (torch/handle_manager.h, mpi_ops.py:1245-1283).

    ``target``: optional tensor the result is copied into (the in-place
    ``*_async_`` contract); synchronize then returns ``target``.
    """

    __slots__ = ("_inner", "_dtype", "_postprocess", "_output", "_done",
                 "_target")

    def __init__(self, inner, dtype, postprocess=None, target=None):
        self._inner = inner
        self._dtype = dtype
        self._postprocess = postprocess
        self._output = None
        self._done = False
        self._target = target

    def poll(self):
        return self._inner.poll()

    def synchronize(self):
        if not self._done:
            res = self._inner.synchronize()
            out = _unstack(res[0] if isinstance(res, (list, tuple)) else res,
                           self._dtype)
            if self._postprocess is not None:
                out = self._postprocess(out)
            if self._target is not None:
                self._target.copy_(out.to(self._target.dtype))
                out = self._target
            self._output = out
            self._done = True
        return self._output

    wait = synchronize


def poll(handle):
    return handle.poll()


def synchronize(handle):
    if callable(handle) and not hasattr(handle, "synchronize"):
        return handle()  # sparse_allreduce_async returns a bare callable
    return handle.synchronize()


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def allreduce(tensor, average=None, name=None, compression=None,
              op=None, prescale_factor=1.0, postscale_factor=1.0,
              process_set=None):
    """reference: hvd.allreduce (torch/mpi_ops.py:294-360; the legacy
    ``average=`` flag maps onto op like the reference's handle_average)."""
    return allreduce_async(tensor, average=average, name=name,
                           compression=compression, op=op,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor,
                           process_set=process_set).synchronize()


def allreduce_(tensor, average=None, name=None, compression=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0, process_set=None):
    """In-place variant (reference: allreduce_ torch/mpi_ops.py:363-421)."""
    out = allreduce(tensor, average=average, name=name,
                    compression=compression, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    process_set=process_set)
    tensor.copy_(out.to(tensor.dtype))
    return tensor


def _resolve_op(average, op):
    if op is not None and average is not None:
        raise ValueError("specify either op or the legacy average flag, "
                         "not both (matches reference check)")
    if op is None:
        op = Average if (average is None or average) else Sum
    return op


def allreduce_async(tensor, average=None, name=None, compression=None,
                    op=None, prescale_factor=1.0, postscale_factor=1.0,
                    process_set=None):
    op = _resolve_op(average, op)
    from horovod_tpu.torch.compression import Compression
    compression = compression or Compression.none
    a, dtype = _to_numpy(tensor)
    compressed, ctx = compression.compress(a)
    ps = process_set if process_set is not None else C.global_process_set
    stacked = _stack_for_mesh(compressed, ps)
    inner = C.allreduce_async(stacked, op=op,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              process_set=process_set, name=name)
    return _TorchHandle(inner, dtype,
                        postprocess=lambda t: compression.decompress(t, ctx))


def allreduce_async_(tensor, average=None, name=None, compression=None,
                     op=None, prescale_factor=1.0, postscale_factor=1.0,
                     process_set=None):
    h = allreduce_async(tensor, average=average, name=name,
                        compression=compression, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        process_set=process_set)
    h._target = tensor
    return h


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=None):
    return [h.synchronize() for h in grouped_allreduce_async(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)]


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            prescale_factor=1.0, postscale_factor=1.0,
                            process_set=None):
    """One fused dispatch for the group (reference:
    EnqueueTensorAllreduces operations.cc:1480)."""
    op = _resolve_op(average, op)
    ps = process_set if process_set is not None else C.global_process_set
    arrs, dtypes = zip(*(_to_numpy(t) for t in tensors))
    stacked = [_stack_for_mesh(a, ps) for a in arrs]
    inner = C.grouped_allreduce_async(stacked, op=op,
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      process_set=process_set, name=name)

    class _GroupItem:
        def __init__(self, idx, dtype):
            self.idx, self.dtype = idx, dtype
            self._out, self._done = None, False

        def poll(self):
            return inner.poll()

        def synchronize(self):
            if not self._done:
                outs = inner.synchronize()
                self._out = _unstack(outs[self.idx], self.dtype)
                self._done = True
            return self._out

    return [_GroupItem(i, dt) for i, dt in enumerate(dtypes)]


# ---------------------------------------------------------------------------
# allgather / broadcast / alltoall / reducescatter
# ---------------------------------------------------------------------------

def allgather(tensor, name=None, process_set=None):
    """reference: hvd.allgather (torch/mpi_ops.py:655-712) — concatenation of
    every rank's tensor along axis 0."""
    return allgather_async(tensor, name=name,
                           process_set=process_set).synchronize()


def allgather_async(tensor, name=None, process_set=None):
    a, dtype = _to_numpy(tensor)
    ps = process_set if process_set is not None else C.global_process_set
    stacked = _stack_for_mesh(a, ps)
    inner = C.allgather_async(stacked, process_set=process_set, name=name)

    def reshape(t):
        # output slice is the concatenation (n*m, ...) flattened to 1-D rows
        n = ps.size()
        return t.reshape((n * a.shape[0],) + a.shape[1:]) if a.ndim else t
    return _TorchHandle(inner, dtype, postprocess=reshape)


def grouped_allgather(tensors, name=None, process_set=None):
    return [allgather(t, name=name, process_set=process_set) for t in tensors]


def broadcast(tensor, root_rank, name=None, process_set=None):
    """reference: hvd.broadcast (torch/mpi_ops.py:843-900)."""
    return broadcast_async(tensor, root_rank, name=name,
                           process_set=process_set).synchronize()


def broadcast_(tensor, root_rank, name=None, process_set=None):
    out = broadcast(tensor, root_rank, name=name, process_set=process_set)
    tensor.copy_(out.to(tensor.dtype))
    return tensor


def broadcast_async(tensor, root_rank, name=None, process_set=None):
    a, dtype = _to_numpy(tensor)
    ps = process_set if process_set is not None else C.global_process_set
    stacked = _stack_for_mesh(a, ps)
    inner = C.broadcast_async(stacked, root_rank, process_set=process_set,
                              name=name)
    return _TorchHandle(inner, dtype)


def broadcast_async_(tensor, root_rank, name=None, process_set=None):
    h = broadcast_async(tensor, root_rank, name=name, process_set=process_set)
    h._target = tensor
    return h


def alltoall(tensor, splits=None, name=None, process_set=None):
    """reference: hvd.alltoall (torch/mpi_ops.py:928-1014). The host tensor's
    axis-0 rows are scattered to peers; returns received rows (and received
    splits when ``splits`` is given)."""
    a, dtype = _to_numpy(tensor)
    ps = process_set if process_set is not None else C.global_process_set
    n = ps.size()
    stacked = _stack_for_mesh(a, ps)
    if splits is None:
        out = C.alltoall(stacked, process_set=process_set, name=name)
        return _unstack(out, dtype)
    splits = np.asarray(splits)
    if splits.ndim != 1 or splits.shape[0] != n:
        raise ValueError(f"splits must be a length-{n} vector of row counts")
    # One splits row per stacked row (local rows only when multi-process).
    mat = np.broadcast_to(splits, (stacked.shape[0], n))
    rows, received = C.alltoall(stacked, splits=mat, process_set=process_set,
                                name=name)
    return (_to_torch(np.asarray(rows[0]), dtype),
            torch.from_numpy(np.ascontiguousarray(received[0])))


def alltoall_async(tensor, splits=None, name=None, process_set=None):
    class _Imm:
        def __init__(self):
            self._out = alltoall(tensor, splits=splits, name=name,
                                 process_set=process_set)

        def poll(self):
            return True

        def synchronize(self):
            return self._out
    return _Imm()


def reducescatter(tensor, op=Sum, name=None, process_set=None,
                  prescale_factor=1.0, postscale_factor=1.0):
    """reference: hvd.reducescatter (torch/mpi_ops.py:1066-1123); this host
    receives its cross-host shard (rows rank*m/n:(rank+1)*m/n of the
    reduction)."""
    return reducescatter_async(tensor, op=op, name=name,
                               process_set=process_set,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor).synchronize()


def reducescatter_async(tensor, op=Sum, name=None, process_set=None,
                        prescale_factor=1.0, postscale_factor=1.0):
    a, dtype = _to_numpy(tensor)
    ps = process_set if process_set is not None else C.global_process_set
    stacked = _stack_for_mesh(a, ps)
    inner = C.reducescatter_async(stacked, op=op, process_set=process_set,
                                  name=name)
    return _TorchHandle(inner, dtype)


def grouped_reducescatter(tensors, op=Sum, name=None, process_set=None):
    return [reducescatter(t, op=op, name=name, process_set=process_set)
            for t in tensors]


def barrier(process_set=None, name=None):
    C.barrier(process_set=process_set, name=name)


def join(device=None, process_set=None):
    """reference: hvd.join (torch/mpi_ops_v2.cc DoJoin:972). ``device`` is
    accepted for API compatibility and ignored (chips are mesh-addressed).
    ``process_set`` scopes the join to a sub-set (core extension; the
    reference's joined_size is per-ProcessSet, controller.cc:269-327)."""
    return C.join(process_set=process_set)


class _InplaceGroupItem:
    """Group-handle item that copies its result into the original tensor on
    synchronize (reference: grouped_allreduce_async_,
    torch/mpi_ops.py:515-551)."""

    def __init__(self, item, target):
        self._item, self._target = item, target

    def poll(self):
        return self._item.poll()

    def synchronize(self):
        out = self._item.synchronize()
        self._target.copy_(out.to(self._target.dtype))
        return self._target

    wait = synchronize


def grouped_allreduce_async_(tensors, average=None, name=None, op=None,
                             prescale_factor=1.0, postscale_factor=1.0,
                             process_set=None):
    items = grouped_allreduce_async(tensors, average=average, name=name,
                                    op=op, prescale_factor=prescale_factor,
                                    postscale_factor=postscale_factor,
                                    process_set=process_set)
    return [_InplaceGroupItem(it, t) for it, t in zip(items, tensors)]


def grouped_allreduce_(tensors, average=None, name=None, op=None,
                       prescale_factor=1.0, postscale_factor=1.0,
                       process_set=None):
    """In-place grouped allreduce (reference: torch/mpi_ops.py:553-589)."""
    return [h.synchronize() for h in grouped_allreduce_async_(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)]


def grouped_allgather_async(tensors, name=None, process_set=None):
    return [allgather_async(t, name=name, process_set=process_set)
            for t in tensors]


def grouped_reducescatter_async(tensors, op=Sum, name=None,
                                process_set=None):
    return [reducescatter_async(t, op=op, name=name,
                                process_set=process_set) for t in tensors]


def sparse_allreduce_async(tensor, name, op, process_set=None):
    """Allreduce a ``torch.sparse_coo_tensor`` by allgathering indices and
    values — duplicate coordinates sum on coalesce, which IS the reduction
    (reference: torch/mpi_ops.py:591-612). Returns a callable handle like
    the reference; :func:`synchronize` accepts it too."""
    import jax.numpy as jnp

    ps = process_set if process_set is not None else C.global_process_set
    idx, _ = _to_numpy(tensor._indices().transpose(0, 1).contiguous())
    vals, vdtype = _to_numpy(tensor._values())
    n_rows = C._expected_rows(ps.mesh, ps.size())

    def handle():
        g_idx = np.asarray(C.allgather_ragged(
            [jnp.asarray(idx)] * n_rows, process_set=process_set,
            name=f"{name}.indices"))
        g_val = np.asarray(C.allgather_ragged(
            [jnp.asarray(vals)] * n_rows, process_set=process_set,
            name=f"{name}.values"))
        if op == Average:
            g_val = g_val / ps.size()
        return torch.sparse_coo_tensor(
            torch.as_tensor(g_idx.copy()).transpose(0, 1),
            _to_torch(g_val, vdtype), tensor.size())

    return handle
