"""CLI/config-file -> HOROVOD_* environment plumbing.

Reference: horovod/runner/common/util/config_parser.py (set_env_from_args:19-205
and the YAML ``--config-file`` loader) — every tunable flag maps to the env
var the core reads, so flags, YAML and raw env are interchangeable.
"""

# flag attribute -> env var (reference: config_parser.py constants)
_ARG_ENV_MAP = [
    ("fusion_threshold_mb", "HOROVOD_FUSION_THRESHOLD",
     lambda v: str(int(v * 1024 * 1024))),
    ("cycle_time_ms", "HOROVOD_CYCLE_TIME", str),
    ("cache_capacity", "HOROVOD_CACHE_CAPACITY", str),
    ("hierarchical_allreduce", "HOROVOD_HIERARCHICAL_ALLREDUCE",
     lambda v: "1" if v else None),
    ("hierarchical_allgather", "HOROVOD_HIERARCHICAL_ALLGATHER",
     lambda v: "1" if v else None),
    ("torus_allreduce", "HOROVOD_TORUS_ALLREDUCE",
     lambda v: "1" if v else None),
    ("autotune", "HOROVOD_AUTOTUNE", lambda v: "1" if v else None),
    ("autotune_log_file", "HOROVOD_AUTOTUNE_LOG", str),
    ("autotune_warmup_samples", "HOROVOD_AUTOTUNE_WARMUP_SAMPLES", str),
    ("autotune_steps_per_sample", "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", str),
    ("autotune_bayes_opt_max_samples",
     "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", str),
    ("autotune_gaussian_process_noise",
     "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", str),
    ("timeline_filename", "HOROVOD_TIMELINE", str),
    ("timeline_mark_cycles", "HOROVOD_TIMELINE_MARK_CYCLES",
     lambda v: "1" if v else None),
    ("no_stall_check", "HOROVOD_STALL_CHECK_DISABLE",
     lambda v: "1" if v else None),
    ("stall_check_warning_time_seconds", "HOROVOD_STALL_CHECK_TIME_SECONDS",
     str),
    ("stall_check_shutdown_time_seconds",
     "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", str),
    ("order_check", "HOROVOD_ORDER_CHECK",
     lambda v: "1" if v else None),
    ("log_level", "HOROVOD_LOG_LEVEL", str),
    ("log_hide_timestamp", "HOROVOD_LOG_HIDE_TIME",
     lambda v: "1" if v else None),
    ("wire_dtype", "HOROVOD_WIRE_DTYPE", str),
    ("hierarchical_alltoall", "HOROVOD_HIERARCHICAL_ALLTOALL",
     lambda v: "1" if v else None),
    ("alltoall_cross_dtype", "HOROVOD_ALLTOALL_CROSS_DTYPE", str),
    ("no_wire_error_feedback", "HOROVOD_WIRE_ERROR_FEEDBACK",
     lambda v: "0" if v else None),
    ("compile_cache_dir", "HOROVOD_COMPILE_CACHE_DIR", str),
    ("control_plane", "HOROVOD_CONTROL_PLANE", str),
    ("kv_shard_count", "HOROVOD_KV_SHARD_COUNT", str),
    ("kv_shard_port_base", "HOROVOD_KV_SHARD_PORT_BASE", str),
    ("control_lease_ms", "HOROVOD_CONTROL_LEASE_MS", str),
    ("elastic_timeout", "HOROVOD_ELASTIC_TIMEOUT", str),
    ("gloo_timeout_seconds", "HOROVOD_GLOO_TIMEOUT_SECONDS", str),
    ("nics", "HOROVOD_NICS", str),
    ("nics", "HOROVOD_GLOO_IFACE", str),
    ("num_nccl_streams", "HOROVOD_NUM_NCCL_STREAMS", str),
    ("thread_affinity", "HOROVOD_THREAD_AFFINITY", str),
    ("mpi_threads_disable", "HOROVOD_MPI_THREADS_DISABLE",
     lambda v: "1" if v else None),
    ("blacklist_cooldown_range", "HOROVOD_BLACKLIST_COOLDOWN_RANGE",
     lambda v: f"{v[0]},{v[1]}"),
    ("flight_dir", "HOROVOD_FLIGHT_DIR", str),
    ("no_flight_recorder", "HOROVOD_FLIGHT_RECORDER",
     lambda v: "0" if v else None),
    ("chaos_plan", "HOROVOD_CHAOS_PLAN", str),
    ("chaos_seed", "HOROVOD_CHAOS_SEED", str),
    ("chaos_ledger", "HOROVOD_CHAOS_LEDGER", str),
    ("no_step_profiler", "HOROVOD_STEP_PROFILER",
     lambda v: "0" if v else None),
    ("no_telemetry", "HOROVOD_TELEMETRY",
     lambda v: "0" if v else None),
    ("telemetry_interval", "HOROVOD_TELEMETRY_INTERVAL", str),
    ("step_report_file", "HVD_STEP_REPORT_FILE", str),
    ("profile_steps", "HOROVOD_PROFILE_STEPS", str),
    ("profile_dir", "HOROVOD_PROFILE_DIR", str),
    ("profile_publish_steps", "HOROVOD_PROFILE_PUBLISH_STEPS", str),
    ("autopilot", "HOROVOD_AUTOPILOT", lambda v: "1" if v else None),
    ("no_autopilot", "HOROVOD_AUTOPILOT", lambda v: "0" if v else None),
    ("autopilot_interval", "HOROVOD_AUTOPILOT_INTERVAL", str),
    ("autopilot_prior", "HOROVOD_AUTOPILOT_PRIOR", str),
    ("serving", "HOROVOD_SERVING", lambda v: "1" if v else None),
    ("serving_port", "HOROVOD_SERVING_PORT", str),
    ("serving_slots", "HOROVOD_SERVING_SLOTS", str),
    ("serving_queue_limit", "HOROVOD_SERVING_QUEUE_LIMIT", str),
    ("trace", "HOROVOD_TRACE", lambda v: "1" if v else None),
    ("no_trace", "HOROVOD_TRACE", lambda v: "0" if v else None),
    ("trace_dir", "HOROVOD_TRACE_DIR", str),
    ("goodput", "HOROVOD_GOODPUT", lambda v: "1" if v else None),
    ("no_goodput", "HOROVOD_GOODPUT", lambda v: "0" if v else None),
    ("goodput_dir", "HOROVOD_GOODPUT_DIR", str),
    ("run_history_dir", "HOROVOD_RUN_HISTORY_DIR", str),
]


def set_env_from_args(env, args):
    """reference: config_parser.py set_env_from_args."""
    for attr, var, conv in _ARG_ENV_MAP:
        v = getattr(args, attr, None)
        if v is None or v is False or v == "":
            continue
        cv = conv(v)
        if cv is not None:
            env[var] = cv
    return env


def parse_config_file(args, path):
    """YAML config overriding CLI defaults (reference: config_parser.py:205
    --config-file). Only keys matching known arg names are applied."""
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    known = {a for a, _, _ in _ARG_ENV_MAP} | {
        "np", "hosts", "hostfile", "verbose", "min_np", "max_np",
        "slots_per_host", "ssh_port", "ssh_identity_file", "start_timeout"}
    for section, values in cfg.items():
        if isinstance(values, dict):
            items = values.items()
        else:
            items = [(section, values)]
        for k, v in items:
            k = k.replace("-", "_")
            # Explicit CLI flags win over the config file; YAML only fills
            # defaults (reference precedence: config_parser.py applies config
            # where the arg wasn't set).
            if k in known and getattr(args, k, None) in (None, False, ""):
                setattr(args, k, v)
    return args
