"""Elastic host discovery.

Reference: horovod/runner/elastic/discovery.py — ``HostDiscovery`` interface,
``HostDiscoveryScript`` (runs a user script printing ``hostname:slots`` lines,
:146+), and ``HostManager`` with per-host blacklist + cooldown (:33-110) so a
flapping host isn't immediately reused.
"""

import os
import subprocess
import time
import threading

from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.runner.hosts import HostInfo


class HostDiscovery:
    def find_available_hosts_and_slots(self):
        """Return {hostname: slots}."""
        raise NotImplementedError


class FixedHosts(HostDiscovery):
    def __init__(self, hosts):
        self._hosts = {h.hostname: h.slots for h in hosts}

    def find_available_hosts_and_slots(self):
        return dict(self._hosts)


class HostDiscoveryScript(HostDiscovery):
    """reference: discovery.py HostDiscoveryScript — executes the script,
    parses ``host`` or ``host:slots`` lines."""

    def __init__(self, discovery_script, default_slots=1):
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self):
        out = self._execute_discovery_script()
        hosts = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            hi = HostInfo.from_string(line)
            hosts[hi.hostname] = hi.slots if ":" in line \
                else self._default_slots
        return hosts

    def _execute_discovery_script(self):
        return subprocess.check_output(
            self._script, shell=True, timeout=60).decode()


class HostState:
    """Per-host blacklist/cooldown bookkeeping
    (reference: discovery.py:33-110 HostState with exponential cooldown)."""

    COOLDOWN_BASE = 10.0
    COOLDOWN_MAX = 600.0

    def __init__(self):
        self.blacklisted = False
        self.failures = 0
        self.cooldown_until = 0.0
        # --blacklist-cooldown-range "base max" (reference:
        # discovery.py cooldown_range / launch.py flag).
        rng = os.environ.get("HOROVOD_BLACKLIST_COOLDOWN_RANGE")
        if rng:
            try:
                base, cap = (float(x) for x in rng.split(","))
                self.COOLDOWN_BASE, self.COOLDOWN_MAX = base, cap
            except ValueError:
                pass

    def record_failure(self):
        self.failures += 1
        cooldown = min(self.COOLDOWN_BASE * (2 ** (self.failures - 1)),
                       self.COOLDOWN_MAX)
        self.cooldown_until = time.time() + cooldown

    def blacklist(self):
        self.blacklisted = True

    def usable(self):
        return not self.blacklisted and time.time() >= self.cooldown_until


class HostManager:
    """Tracks current hosts + their health; computes the usable set
    (reference: driver.py + discovery.py host bookkeeping)."""

    def __init__(self, discovery):
        self._discovery = discovery
        self._states = {}
        self._lock = threading.Lock()

    def state(self, host):
        with self._lock:
            return self._states.setdefault(host, HostState())

    def record_failure(self, host):
        hvd_logging.warning("host %s failed; cooling down", host)
        self.state(host).record_failure()

    def blacklist(self, host):
        hvd_logging.warning("blacklisting host %s", host)
        self.state(host).blacklist()

    def current_hosts(self):
        available = self._discovery.find_available_hosts_and_slots()
        return {h: s for h, s in available.items()
                if self.state(h).usable()}
