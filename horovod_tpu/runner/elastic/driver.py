"""Elastic driver: membership management + worker lifecycle.

Reference: horovod/runner/elastic/driver.py (ElasticDriver:69 — discovery
thread :188-208, rank-preserving reassignment :240-283, worker respawn
:284-302, exit handling + blacklist :304+), rendezvous.py (workers fetch their
SlotInfo per membership version), registration.py/worker.py (host-update push).

TPU adaptation: membership is per *host* (a host owns all its chips; TPU
slices don't shrink by one chip). Workers learn about membership changes by
polling a version counter in the KV store (replacing the push
WorkerNotificationService). On a version bump the (re)spawn is
DIFFERENTIAL, matching the reference's no-restart UX for survivors
(reference: driver.py:240-283 preserves surviving ranks, :284-302 only
spawns new slots): workers on surviving hosts keep their PROCESS and
re-initialize jax.distributed in place from the ``@elastic.run`` wrapper
(shutdown → refresh assignment env → re-init at the new
coordinator/world, elastic/state.py `_reset`); workers on removed hosts
are terminated; only added hosts get new processes. Each version also
publishes its update kind ("add"/"removal") so workers can skip the
state re-sync on removal-only changes — preserving uncommitted progress
exactly like the reference's ``HostUpdateResult.removed`` →
``skip_sync`` path (common/elastic.py check_host_updates).
"""

import threading
import time

from horovod_tpu.chaos import injector as _chaos
from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.runner.elastic.discovery import HostManager
from horovod_tpu.runner.hosts import HostInfo, get_host_assignments

DISCOVER_INTERVAL_SECS = 1.0


class ElasticDriver:
    def __init__(self, discovery, min_np, max_np=None, reset_limit=None,
                 spawn_fn=None, shutdown_fn=None, remediation_fn=None):
        """``spawn_fn(assignment, version)`` starts workers for the host set;
        ``shutdown_fn(reason)`` stops them. Injected for testability — the
        reference tests drive ``_update_host_assignments`` the same way
        (reference: test_elastic_driver.py:46-509).

        ``remediation_fn(hosts)`` is the autopilot's driver arm
        (horovod_tpu/autopilot/remediate.DriverArm.poll): called on every
        discovery poll with the freshly discovered host dict, it applies
        any pending controller-requested blacklists through the
        HostManager cooldown path and returns the hosts it removed this
        poll — which are then excluded from this round's assignment
        immediately (the cooldown keeps them out of later rounds until
        re-admission)."""
        self._host_manager = HostManager(discovery)
        self._remediation_fn = remediation_fn
        self._min_np = min_np
        self._max_np = max_np
        self._reset_limit = reset_limit
        self._spawn_fn = spawn_fn or (lambda assignment, version: None)
        self._shutdown_fn = shutdown_fn or (lambda reason: None)

        self._assignment = []          # list[SlotInfo]
        self._last_hosts = None        # last discovered {host: slots}
        self._host_order = []          # rank-ordered hostnames
        self._disrupted = {}           # version -> membership shrank/changed
        self._version = 0
        self._reset_count = 0
        self._shutdown = threading.Event()
        self._assignment_cv = threading.Condition()
        self._thread = None
        self.results = {}

    # --- lifecycle -----------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._discover_loop,
                                        daemon=True)
        self._thread.start()

    def stop(self, reason="driver stop"):
        self._shutdown.set()
        # stop() may be reached from the discovery thread itself (e.g. the
        # reset limit firing inside _discover_loop) — never join ourselves.
        if self._thread and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        self._shutdown_fn(reason)

    def wait_for_available_slots(self, min_np, timeout=600):
        """Block until discovery finds >= min_np slots
        (reference: driver.py:153 wait_for_available_slots)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            hosts = self._host_manager.current_hosts()
            if sum(hosts.values()) >= min_np:
                return hosts
            if self._shutdown.is_set():
                raise RuntimeError("driver shut down while waiting for slots")
            time.sleep(0.2)
        raise TimeoutError(
            f"fewer than min_np={min_np} slots available after {timeout}s")

    # --- membership ----------------------------------------------------
    def _discover_loop(self):
        while not self._shutdown.is_set():
            try:
                hosts = self._host_manager.current_hosts()
                if _chaos.armed:
                    # Chaos site: host_remove specs drop a victim from the
                    # discovered set for their window — simulated host
                    # preemption, recovered through the exact reassignment
                    # path a real removal takes.
                    hosts = _chaos.filter_hosts("driver.discovery", hosts)
                if self._remediation_fn is not None:
                    removed = self._remediation_fn(hosts) or ()
                    if removed:
                        hosts = {h: s for h, s in hosts.items()
                                 if h not in removed}
                self._maybe_update(hosts)
            except Exception as e:  # discovery script hiccup: keep going
                if self._shutdown.is_set():
                    break  # stop() already ran (e.g. reset limit exceeded)
                hvd_logging.warning("discovery error: %s", e)
            self._shutdown.wait(DISCOVER_INTERVAL_SECS)

    def _maybe_update(self, hosts):
        # Compare against the last DISCOVERED hosts (per-host slot counts,
        # not just the host set: resource-based discovery, e.g.
        # RayHostDiscovery, can resize a host in place). Comparing against
        # the assignment would churn whenever max_np clamps it below the
        # available slots.
        # _assignment is published under _assignment_cv (workers block on
        # it in wait_for_available_slots); read it under the same guard.
        with self._assignment_cv:
            have_assignment = bool(self._assignment)
        if hosts == self._last_hosts and have_assignment:
            return
        if sum(hosts.values()) < self._min_np:
            hvd_logging.warning(
                "available slots %s below min_np %d; waiting",
                hosts, self._min_np)
            return
        self.update_host_assignments(hosts)

    def update_host_assignments(self, hosts):
        """Recompute SlotInfos, preserving the rank order of surviving hosts
        so their state stays rank-stable (reference: driver.py:240-283)."""
        # Membership DISRUPTION (vs the previous membership): a host left,
        # or an existing host's slot count changed (its worker is
        # terminated + respawned either way). Survivors' in-flight
        # collectives can then never complete, and the worker-side
        # watchdog must abort them; a pure addition leaves them
        # completable. Decided against the last membership, NOT the live
        # worker table — a crashed worker is reaped from that table before
        # the respawn runs, which would mask its own removal.
        prev = self._last_hosts or {}
        disrupted = any(h not in hosts or hosts[h] != prev[h]
                        for h in prev)
        self._last_hosts = dict(hosts)
        with self._assignment_cv:
            surviving = [h for h in self._host_order if h in hosts]
            new = [h for h in hosts if h not in surviving]
            order = surviving + new
            host_infos = [HostInfo(h, hosts[h]) for h in order]
            np_target = sum(hosts.values())
            if self._max_np:
                np_target = min(np_target, self._max_np)
            assignment = get_host_assignments(host_infos, np_target)
            self._host_order = order
            self._assignment = assignment
            self._version += 1
            version = self._version
            self._disrupted[version] = disrupted
            self._disrupted.pop(version - 3, None)
            self._assignment_cv.notify_all()
        hvd_logging.info("new assignment v%d over hosts %s", version, order)
        self._reset_count += 1
        if self._reset_limit is not None \
                and self._reset_count > self._reset_limit:
            self.stop(f"reset limit {self._reset_limit} exceeded")
            raise RuntimeError(
                f"elastic reset limit {self._reset_limit} exceeded")
        self._spawn_fn(assignment, version)

    def assignment(self):
        with self._assignment_cv:
            return list(self._assignment), self._version

    def version_disrupted(self, version):
        """Whether ``version``'s membership change disrupted the previous
        one (host removed / slot count changed) — i.e. whether in-flight
        collectives of earlier memberships must be aborted. Unknown
        (GC'd) versions count as disruptive: a worker that stale is
        wedged by construction."""
        with self._assignment_cv:
            return self._disrupted.get(version, True)

    def wait_for_assignment_change(self, known_version, timeout=None):
        with self._assignment_cv:
            self._assignment_cv.wait_for(
                lambda: self._version != known_version, timeout=timeout)
            return list(self._assignment), self._version

    # --- worker results ------------------------------------------------
    def record_worker_exit(self, host, exit_code):
        """reference: driver.py:304+ _handle_worker_exit — failed hosts are
        cooled down/blacklisted and the assignment recomputed."""
        self.results[host] = exit_code
        if exit_code != 0:
            self._host_manager.record_failure(host)
            hosts = self._host_manager.current_hosts()
            if sum(hosts.values()) >= self._min_np:
                self.update_host_assignments(hosts)


def run_elastic_driver(args, kv_preload=None, harvest=None,
                       discovery_override=None, extra_env=None):
    """CLI glue for ``hvdrun --min-np … --host-discovery-script …``.

    ``kv_preload`` seeds the KV store before workers start (e.g. the pickled
    function for the ``run_elastic()`` API); ``harvest(kv)`` runs after a
    successful job to collect worker-reported results;
    ``discovery_override`` substitutes a :class:`HostDiscovery` object for
    the script/hosts sources (e.g. Ray cluster discovery,
    horovod_tpu/ray/elastic.py)."""
    import socket

    from horovod_tpu.runner.elastic.discovery import (FixedHosts,
                                                      HostDiscoveryScript)
    from horovod_tpu.runner.exec import WorkerProcess
    from horovod_tpu.runner.http_kv import KVStoreServer
    from horovod_tpu.runner.launch import _free_port, build_worker_env
    from horovod_tpu.runner.hosts import (host_assignment_by_host, parse_hosts)

    if discovery_override is not None:
        discovery = discovery_override
    elif args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        args.slots_per_host or 1)
    elif args.hosts:
        discovery = FixedHosts(parse_hosts(args.hosts))
    else:
        raise ValueError("elastic mode needs --host-discovery-script or -H")

    import os as _os

    from horovod_tpu.runner.secret import SECRET_ENV, make_secret_key
    _os.environ.setdefault(SECRET_ENV, make_secret_key())
    # Driver-side knobs (HostState cooldowns etc.) read the DRIVER's env;
    # set_env_from_args otherwise only reaches the per-worker env dicts, so
    # flags like --blacklist-cooldown-range would silently stay defaulted.
    from horovod_tpu.runner.config_parser import set_env_from_args
    set_env_from_args(_os.environ, args)
    # Arm the driver-side chaos plan (host_remove rides the discovery
    # loop); workers arm their own copy from the propagated env at init.
    from horovod_tpu import chaos as _chaos_api
    from horovod_tpu.flight import recorder as _flight
    _chaos_api.set_role("driver")
    _flight.set_role("driver")
    # The roles claimed above MUST be restored on every exit path —
    # including KV/driver startup failures, not just after
    # driver.start() succeeds — so the try covers everything from the
    # claim onward (regression: the PR-14 test_runner -> test_chaos
    # ordering leak came back through the startup-failure window).
    try:
        _chaos_api.install_from_env()
        # recorder.armed was fixed at import time — before set_env_from_args
        # above applied --no-flight-recorder to this process's env. Re-read it
        # (the chaos install_from_env() parallel), or the driver would write
        # disruption markers for a run the operator opted out of.
        from horovod_tpu.common.config import _env_bool
        _flight.set_enabled(_env_bool("HOROVOD_FLIGHT_RECORDER", True))
        # Same flight-dir default the workers get (launch.build_worker_env):
        # the driver's disruption markers must land in the SAME directory as
        # the worker dumps or the analyzer loses the kill-to-membership-change
        # correlation. set_env_from_args above only covers an explicit
        # --flight-dir; this covers the defaulted elastic launch.
        _os.environ.setdefault(
            "HOROVOD_FLIGHT_DIR",
            _flight.default_collection_dir(
                getattr(args, "output_filename", None)))
        # Elastic launches shard the KV plane too (slice-local scopes off the
        # root listener); the shard count keys off the LARGEST world the job
        # may reach — membership changes must not restart listeners.
        from horovod_tpu.common import control_plane as _cp
        from horovod_tpu.common.config import _env_int
        kv = KVStoreServer(
            shards=_cp.kv_shard_count(args.max_np or args.np or args.min_np
                                      or 1),
            shard_port_base=_env_int("HOROVOD_KV_SHARD_PORT_BASE", 0))
        kv_port = kv.start()
        for (scope, key), value in (kv_preload or {}).items():
            kv.put(scope, key, value)
        coordinator_addr = socket.gethostname()
        state = {"workers": {}, "slots": {}, "done": threading.Event(), "rc": 0,
                 "version": 0, "completing": False, "lock": threading.Lock(),
                 "spawn_lock": threading.Lock()}

        def spawn(assignment, version):
            """Differential (re)spawn: workers on surviving hosts keep running
            and re-initialize in place when they observe the version bump
            (reference: surviving ranks re-rendezvous without restarting,
            §3.4 / elastic/driver.py:284-302 only spawns NEW slots); workers on
            removed hosts are terminated; workers on added hosts are started."""
            # Serialize whole (re)spawns: discovery-thread updates and
            # worker-crash updates (record_worker_exit from a _watch thread)
            # can race, and an older version's KV writes landing after a newer
            # one's would roll the membership backwards.
            with state["spawn_lock"]:
                with state["lock"]:
                    if version < state["version"]:
                        hvd_logging.info(
                            "dropping superseded spawn v%d (current v%d)",
                            version, state["version"])
                        return
                    completing = state.get("completing")
                # The KV marker closes the window between a worker's final
                # result write and its _watch thread observing the exit
                # (runner/task.py writes it just before exiting).
                if completing or kv.get("elastic", "finished"):
                    # A worker already finished cleanly: rebalancing now
                    # would wedge the new membership waiting on exited
                    # peers. Let the remaining workers drain.
                    hvd_logging.info(
                        "dropping spawn v%d: job is completing", version)
                    return
                with state["lock"]:
                    if version < state["version"]:
                        return
                    state["version"] = version
                _spawn_locked(assignment, version)

        def _spawn_locked(assignment, version):
            import json

            coordinator_port = _free_port()
            by_host = host_assignment_by_host(assignment)
            with state["lock"]:
                # Pop removed hosts first so their _watch threads see them as
                # stale and don't report the termination as a host failure.
                # A host whose slot count changed in place cannot re-init
                # in-process (its XLA local device count was pinned at spawn):
                # treat it as removed + added.
                removed = [h for h in list(state["workers"])
                           if h not in by_host
                           or state["slots"].get(h) != len(by_host[h])]
                removed_workers = [state["workers"].pop(h) for h in removed]
                for h in removed:
                    state["slots"].pop(h, None)
                survivors = set(state["workers"])
            # terminate() blocks until each removed worker is reaped, so no
            # stale process can write results/mark itself ready after the KV
            # reset below.
            for w in removed_workers:
                w.terminate()
            # Results are version-scoped (a stale write can't pollute the final
            # harvest); pruning here is garbage collection of superseded
            # memberships' results — NOT a blanket delete: a worker finishing
            # under the previous version concurrently with this rebalance must
            # not lose its result row (its finished marker may land between
            # spawn()'s probe and now). Assignment rows and ready marks are
            # pruned to the previous + new version likewise — a worker that
            # read the previous version string just before this bump can still
            # fetch its row — bounding KV growth under membership churn.
            keep = (f"{version}/", f"{version - 1}/")
            for scope in ("results", "assignment", "new_rank_ready"):
                kv.prune_scope(scope, keep)
            # Telemetry keys are generation-scoped the same way (rank
            # numbering changes across memberships); the unscoped "job" view
            # survives so the new generation's leader can diff the previous
            # membership's hosts and record who was lost.
            kv.prune_scope("telemetry",
                           (f"g{version}/", f"g{version - 1}/", "job"))
            # Assignment rows and nhosts must land before the version bump:
            # surviving workers re-rendezvous the moment they observe the bump
            # (elastic/worker.py refresh_assignment_env), and the
            # new-rank-ready barrier keys off the observed version.
            for host, slots in by_host.items():
                first = slots[0]
                # Two-segment key (not scope) so HTTP clients — whose paths
                # parse as /scope/rest-of-path — resolve the same cell.
                kv.put("assignment", f"{version}/{host}", json.dumps({
                    "rank": first.rank, "size": first.size,
                    "local_size": first.local_size,
                    "cross_rank": first.cross_rank,
                    "cross_size": first.cross_size,
                    "coordinator_port": coordinator_port,
                }).encode())
            # Last-moment finished re-check, atomic with the bump from the
            # workers' perspective (they only act on the version write): a
            # worker that completed during this rebalance must not be counted
            # as a survivor of a membership it will never join — that would
            # wedge the others at the new-rank barrier. The nhosts writes come
            # AFTER this check: an aborted spawn must not leave the unscoped
            # count describing a membership that never activated (the final
            # harvest sizes itself from it).
            if kv.get("elastic", "finished"):
                hvd_logging.info(
                    "aborting spawn v%d: job finished during rebalance", version)
                return
            # Version-scoped host count: a worker configured for version v must
            # never pair v's ready marks with v+1's count (premature barrier
            # release on scale-down). The unscoped key serves the final harvest
            # (api._elastic_harvester).
            kv.put("elastic", f"nhosts/{version}", str(len(by_host)).encode())
            kv.delete("elastic", f"nhosts/{version - 2}")
            # Update kind for this version: removal-only changes let survivors
            # skip the state re-sync and keep uncommitted progress (reference:
            # HostUpdateResult.removed -> skip_sync, common/elastic.py).
            kind = b"add" if any(h not in survivors for h in by_host) \
                else b"removal"
            kv.put("elastic", f"update_kind/{version}", kind)
            kv.delete("elastic", f"update_kind/{version - 2}")
            # Disruption marker for the worker-side membership watchdog
            # (elastic/worker.py): a version whose membership change makes
            # in-flight collectives uncompletable (host removed / resized)
            # must ABORT them on every survivor; a pure addition leaves them
            # completable and is picked up at the next commit boundary.
            disrupted = driver.version_disrupted(version)
            kv.put("elastic", f"removed/{version}",
                   b"1" if disrupted else b"0")
            kv.delete("elastic", f"removed/{version - 2}")
            if disrupted:
                # Collection-point marker: workers dump their rings into
                # HOROVOD_FLIGHT_DIR (one directory for the whole launch —
                # the env is propagated to every worker), and this line ties
                # those dumps to the membership change that triggered them.
                _flight.driver_mark(version, removed, list(by_host))
            kv.put("elastic", "nhosts", str(len(by_host)).encode())
            kv.put("elastic", "version", str(version).encode())
            for host, slots in by_host.items():
                if host in survivors:
                    continue  # stays alive; re-inits in place on the bump
                env = build_worker_env(
                    {**(extra_env or {}), "HOROVOD_ELASTIC": "1"}, slots,
                    coordinator_addr, coordinator_port, kv_port, args,
                    kv_shard_ports=kv.shard_ports)
                env["HOROVOD_HOST_KEY"] = host
                # Workers key their results by the membership version they run
                # under (updated in-place on re-init), so a survivor finishing
                # against a superseded membership can never pollute the final
                # harvest.
                env["HOROVOD_ELASTIC_INIT_VERSION"] = str(version)
                w = WorkerProcess(host, args.command, env, tag=f"{host}@v{version}")
                with state["lock"]:
                    state["workers"][host] = w
                    state["slots"][host] = len(slots)
                threading.Thread(target=_watch, args=(host, w),
                                 daemon=True).start()

        def _watch(host, worker):
            rc = worker.wait()
            with state["lock"]:
                stale = state["workers"].get(host) is not worker
                if not stale:
                    state["workers"].pop(host, None)
                    if rc == 0:
                        # A clean finish means the job is winding down: further
                        # membership bumps must not respawn/rebalance (peers
                        # that already exited can never re-join a rendezvous).
                        state["completing"] = True
            if stale:
                return  # superseded/removed assignment; expected termination
            driver.record_worker_exit(host, rc)
            # Only after record_worker_exit: a crash may have just respawned a
            # replacement (blacklist -> reassign -> spawn); a pre-exit snapshot
            # of the worker table would declare the job dead mid-recovery.
            with state["lock"]:
                remaining = bool(state["workers"])
            if not remaining:
                state["rc"] = max(abs(rc or 0), state["rc"])
                state["done"].set()

        def shutdown(reason):
            with state["lock"]:
                workers = list(state["workers"].values())
            for w in workers:
                w.terminate()
            if reason != "driver stop":
                state["rc"] = max(state["rc"], 1)
            state["done"].set()

        # Autopilot driver arm: controller-requested removals ride the
        # discovery loop exactly like chaos host_remove — blacklist via the
        # HostManager cooldown, then the normal reassignment re-rendezvouses
        # the survivors. The arm exists whether or not workers run the
        # controller (requests only appear when they do); floor/rate are
        # re-validated here with the driver's authoritative world view.
        from horovod_tpu.autopilot import remediate as _ap_remediate
        _arm_box = []

        def _remediation_poll(hosts):
            return _arm_box[0].poll(hosts) if _arm_box else ()

        driver = ElasticDriver(discovery, args.min_np or 1, args.max_np,
                               args.reset_limit, spawn_fn=spawn,
                               shutdown_fn=shutdown,
                               remediation_fn=_remediation_poll)
        _arm_box.append(_ap_remediate.DriverArm(
            kv, driver._host_manager,
            min_world=max(_env_int("HOROVOD_AUTOPILOT_MIN_WORLD", 0),
                          args.min_np or 1),
            max_removals=_env_int("HOROVOD_AUTOPILOT_MAX_REMOVALS", 1)))
        driver.start()
        try:
            driver.wait_for_available_slots(args.min_np or 1,
                                            timeout=args.start_timeout)
            state["done"].wait()
            # Halt discovery BEFORE harvesting: a membership change landing in
            # this window would call spawn(), whose kv.delete("results") wipes
            # the finished run's results mid-harvest.
            driver.stop()
            if state["rc"] == 0 and harvest is not None:
                harvest(kv)
            return state["rc"]
        finally:
            driver.stop()
            kv.stop()
    finally:
        # The driver may run IN-PROCESS (tests, run_elastic API): restore
        # the chaos/flight roles claimed above, or the next in-process
        # workload's ledger entries and dumps are mislabeled "driver"
        # (the PR-14 test_runner -> test_chaos ordering leak).
        _chaos_api.set_role("worker")
        _flight.set_role("worker")
