"""Process fan-out: local subprocess and ssh remote execution.

Reference: horovod/runner/util/remote.py + common/util/safe_shell_exec.py —
per-slot ssh commands with pty capture, exit-code monitoring threads, and
index-tagged output streaming (gloo_run.py:242-287).
"""

import os
import shlex
import subprocess
import sys
import threading

from horovod_tpu.common import logging as hvd_logging


def _stream(proc, tag, out, sink=None, prefix_timestamp=False):
    for line in iter(proc.stdout.readline, b""):
        text = line.decode(errors="replace")
        if prefix_timestamp:
            import datetime
            text = f"{datetime.datetime.now().isoformat(sep=' ')} {text}"
        out.write(f"[{tag}]<stdout> {text}")
        out.flush()
        if sink is not None:
            sink.write(text)
            sink.flush()
    # The reader owns the sink: close only after the pipe is fully drained,
    # so a slow drain can never race a closed file.
    if sink is not None:
        sink.close()


def build_launch_command(hostname, command, env, local, ssh_port=None,
                         ssh_identity_file=None):
    """Returns ``(argv, run_env, secret_env)``. Secrets must NEVER ride the
    remote argv — /proc/*/cmdline is world-readable on the worker host
    (the reference likewise keeps its per-job key off the command line:
    runner/common/util/secret.py travels through the task-service
    channel) — so env vars whose name contains SECRET are shipped over ssh
    stdin (``read -r`` before exec) instead of inline ``env`` assignments."""
    if local:
        return command, {**os.environ, **env}, {}
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    if ssh_identity_file:
        ssh += ["-i", ssh_identity_file]
    secret_env = {k: v for k, v in env.items() if "SECRET" in k}
    plain = {k: v for k, v in env.items() if "SECRET" not in k}
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in plain.items())
    reads = "".join(f"read -r {k} && export {k} && "
                    for k in sorted(secret_env))
    full = ssh + [hostname,
                  f"cd {shlex.quote(os.getcwd())} && {reads}"
                  f"env {exports} "
                  + " ".join(shlex.quote(c) for c in command)]
    return full, os.environ.copy(), secret_env


class WorkerProcess:
    def __init__(self, hostname, command, env, tag, use_ssh=None,
                 ssh_port=None, ssh_identity_file=None, output_dir=None,
                 rank=None, prefix_timestamp=False):
        self.hostname = hostname
        self.tag = tag
        # --output-filename: mirror each worker's merged stdout/stderr to
        # <dir>/rank.<NN>/stdout (reference: launch.py:332 + gloo_run's
        # per-rank capture files).
        self._sink = None
        if output_dir:
            sub = os.path.join(output_dir,
                               f"rank.{rank:02d}" if rank is not None
                               else f"host.{hostname}")
            os.makedirs(sub, exist_ok=True)
            self._sink = open(os.path.join(sub, "stdout"), "a")
        # Any 127.0.0.0/8 address is this machine (loopback aliases let tests
        # model N distinct "hosts" locally, like the reference's
        # localhost-based integration tier).
        local = (hostname in ("localhost", "::1", os.uname().nodename)
                 or hostname.startswith("127.")) \
            if use_ssh is None else not use_ssh
        full, run_env, secret_env = build_launch_command(
            hostname, command, env, local, ssh_port, ssh_identity_file)
        hvd_logging.debug("launching worker %s: %s", tag, full)
        self.proc = subprocess.Popen(
            full, env=run_env,
            stdin=subprocess.PIPE if secret_env else None,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        if secret_env:
            for k in sorted(secret_env):
                self.proc.stdin.write(secret_env[k].encode() + b"\n")
            self.proc.stdin.close()
        self._thread = threading.Thread(
            target=_stream, args=(self.proc, tag, sys.stdout, self._sink,
                                  prefix_timestamp), daemon=True)
        self._thread.start()

    def wait(self, timeout=None):
        rc = self.proc.wait(timeout)
        self._thread.join(timeout=30)
        return rc

    def terminate(self):
        """Stop the worker and reap it; returns only once the process is
        gone, so callers may safely reset shared state afterwards."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()


def wait_for_any_failure_or_all_success(workers):
    """Monitor workers; on any nonzero exit terminate the rest
    (reference: gloo_run.py:277-287 exit-code monitoring)."""
    codes = {}
    codes_lock = threading.Lock()
    threads = []

    def watch(w):
        rc = w.wait()
        with codes_lock:
            codes[w.tag] = rc

    for w in workers:
        t = threading.Thread(target=watch, args=(w,), daemon=True)
        t.start()
        threads.append(t)
    while threads:
        for t in list(threads):
            t.join(timeout=0.2)
            if not t.is_alive():
                threads.remove(t)
        with codes_lock:
            snapshot = dict(codes)
        failed = {k: v for k, v in snapshot.items() if v not in (None, 0)}
        if failed:
            for w in workers:
                if w.tag not in snapshot:
                    w.terminate()
            for t in threads:
                t.join()
            return failed
    with codes_lock:
        return {k: v for k, v in codes.items() if v != 0}
