"""Launch workers with ``mpirun`` instead of ssh fan-out.

Reference: horovod/runner/mpi_run.py (implementation detection via
``mpirun --version`` :60-131, per-implementation flags, ``-x`` env forwarding,
``-H`` host list). Differences by design:

- mpirun is only a *process launcher* here: one worker process per host, each
  of which bootstraps ``jax.distributed`` and owns all local chips. MPI is NOT
  a data plane (XLA collectives over ICI/DCN are) and NOT a controller — so no
  btl/pml tuning matters beyond picking TCP-friendly defaults.
- Workers learn their process index from the MPI-provided environment
  (``OMPI_COMM_WORLD_RANK``/``PMI_RANK``; see Config.from_env fallbacks) since
  per-rank env cannot be forwarded through a single mpirun invocation.
"""

import os
import shutil
import subprocess

# Implementation names (reference: mpi_run.py:26-31).
OPENMPI = "OpenMPI"
SPECTRUM_MPI = "SpectrumMPI"
MPICH = "MPICH"
INTEL_MPI = "IntelMPI"
UNKNOWN = "Unknown"
MISSING = "Missing"

# Env prefixes always forwarded to workers, mirroring the reference's
# env filtering (reference: horovod/runner/common/util/env.py).
_FORWARD_PREFIXES = ("HOROVOD_", "JAX_", "XLA_", "LIBTPU_", "TPU_", "PATH",
                     "PYTHONPATH", "LD_LIBRARY_PATH")


def _impl_from_version_output(output):
    """Classify an ``mpirun --version`` banner (reference: mpi_run.py:80-131)."""
    if "Open MPI" in output or "OpenRTE" in output or "OpenMPI" in output:
        return OPENMPI
    if "IBM Spectrum MPI" in output:
        return SPECTRUM_MPI
    if "Intel(R) MPI" in output:
        return INTEL_MPI
    if "MPICH" in output or "HYDRA" in output:
        return MPICH
    return UNKNOWN


def get_mpi_implementation(env=None):
    env = dict(env) if env is not None else dict(os.environ)
    mpirun = shutil.which("mpirun", path=env.get("PATH"))
    if mpirun is None:
        return MISSING
    try:
        out = subprocess.run([mpirun, "--version"], capture_output=True,
                             text=True, timeout=30, env=env)
    except (OSError, subprocess.TimeoutExpired):
        return MISSING
    return _impl_from_version_output(out.stdout + out.stderr)


def mpi_available(env=None):
    return get_mpi_implementation(env) not in (UNKNOWN, MISSING)


def is_open_mpi(env=None):
    return get_mpi_implementation(env) == OPENMPI


def _forwarded_env_flags(impl, env):
    names = sorted(k for k in env
                   if k.startswith(_FORWARD_PREFIXES) or k in
                   ("HOME", "USER", "SHELL"))
    flags = []
    if impl in (OPENMPI, SPECTRUM_MPI):
        for n in names:
            flags += ["-x", n]
    elif impl == INTEL_MPI or impl == MPICH:
        if names:
            flags += ["-genvlist", ",".join(names)]
    return flags


def build_mpi_command(impl, hosts, env, command, extra_mpi_args=None):
    """Compose the mpirun command line.

    ``hosts``: ``[(host, slots)]``. One MPI process per host (it owns the
    host's chips), hence ``-H host:1`` regardless of chip count — the chip
    count reaches workers via ``HOROVOD_*`` env instead.
    """
    nhosts = len(hosts)
    cmd = ["mpirun", "--allow-run-as-root" if impl == OPENMPI else None,
           "-np", str(nhosts)]
    cmd = [c for c in cmd if c]
    if nhosts > 1 or hosts[0][0] not in ("localhost", "127.0.0.1"):
        cmd += ["-H", ",".join(f"{h}:1" for h, _ in hosts)]
    if impl == OPENMPI:
        # One worker per host; let it use every core (reference pins with
        # -bind-to none -map-by slot, mpi_run.py:46).
        cmd += ["--bind-to", "none", "--map-by", "node"]
        cmd += ["--mca", "pml", "ob1", "--mca", "btl", "^openib"]
    elif impl == SPECTRUM_MPI:
        cmd += ["-tcp"]
    cmd += _forwarded_env_flags(impl, env)
    if extra_mpi_args:
        cmd += list(extra_mpi_args)
    cmd += list(command)
    return cmd


def mpi_run(hosts, env, command, extra_mpi_args=None, dry_run=False):
    """Run the training command across hosts via mpirun; returns exit code."""
    # Probe and forward with the user's full shell environment — the probe
    # needs PATH et al. (wrapper-script mpiruns), and HOROVOD_/JAX_/XLA_ vars
    # exported in the shell must reach remote workers.
    full_env = {**os.environ, **env}
    impl = get_mpi_implementation(full_env)
    if impl == MISSING:
        raise RuntimeError(
            "hvdrun --launcher mpi requires an MPI installation with mpirun "
            "on PATH. Install Open MPI / MPICH, or use the default ssh "
            "launcher.")
    if impl == UNKNOWN:
        # Proceeding would emit no env-forwarding flags and the remote
        # workers would silently train unsynchronized.
        raise RuntimeError(
            "hvdrun --launcher mpi: could not classify the installed MPI "
            "from `mpirun --version` (need Open MPI, Spectrum MPI, MPICH, "
            "or Intel MPI); use the default ssh launcher instead.")
    cmd = build_mpi_command(impl, hosts, full_env, command,
                            extra_mpi_args=extra_mpi_args)
    if dry_run:
        return cmd
    proc = subprocess.run(cmd, env=full_env)
    return proc.returncode
