"""Host parsing and slot assignment.

Reference: horovod/runner/common/util/hosts.py (parse_host_files,
get_host_assignments:100) — hosts are given as ``host1:4,host2:4`` (host:slots)
or a hostfile with ``host slots=N`` lines; assignment produces per-slot
SlotInfo(rank, local_rank, cross_rank, size, local_size, cross_size).

TPU adaptation: a "slot" is a chip. Processes are launched per *host*; each
host process receives the full rank range it owns. Host-major ordering matches
the topology module's rank-major device sort (topology.py _sorted_devices).
"""

import dataclasses


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(s):
        if ":" in s:
            host, slots = s.rsplit(":", 1)
            return HostInfo(host, int(slots))
        return HostInfo(s, 1)


@dataclasses.dataclass
class SlotInfo:
    """reference: horovod/runner/common/util/hosts.py SlotInfo."""
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_response_string(self):
        return ",".join(str(v) for v in (
            self.rank, self.size, self.local_rank, self.local_size,
            self.cross_rank, self.cross_size))


def parse_hosts(hosts_string):
    """``host1:2,host2:2`` -> [HostInfo] (reference: hosts.py parse_hosts)."""
    return [HostInfo.from_string(p) for p in hosts_string.split(",") if p]


def parse_host_files(filename):
    """Hostfile with ``hostname slots=N`` or ``hostname:N`` lines
    (reference: hosts.py parse_host_files)."""
    hosts = []
    with open(filename) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            if "slots=" in line:
                host, _, slots = line.partition("slots=")
                hosts.append(HostInfo(host.strip(), int(slots.strip())))
            else:
                hosts.append(HostInfo.from_string(line))
    return hosts


def get_host_assignments(hosts, min_np, max_np=None):
    """Assign ranks host-major (reference: hosts.py:100 get_host_assignments).

    Returns (slot_infos, host_slots): one SlotInfo per chip, plus the per-host
    aggregate used to spawn one process per host.
    """
    np_total = sum(h.slots for h in hosts)
    if min_np is not None and np_total < min_np:
        raise ValueError(
            f"Requested np={min_np} but only {np_total} slots available on "
            f"{[h.hostname for h in hosts]}")
    size = min(max_np, np_total) if max_np else (min_np or np_total)

    slots = []
    rank = 0
    cross_size = 0
    for h in hosts:
        if rank >= size:
            break
        cross_size += 1
        take = min(h.slots, size - rank)
        for lr in range(take):
            slots.append(dict(hostname=h.hostname, rank=rank, local_rank=lr,
                              cross_rank=cross_size - 1))
            rank += 1
    local_sizes = {}
    for s in slots:
        local_sizes[s["hostname"]] = local_sizes.get(s["hostname"], 0) + 1
    infos = [SlotInfo(hostname=s["hostname"], rank=s["rank"],
                      local_rank=s["local_rank"], cross_rank=s["cross_rank"],
                      size=size, local_size=local_sizes[s["hostname"]],
                      cross_size=cross_size)
             for s in slots]
    return infos


def host_assignment_by_host(slot_infos):
    """Group SlotInfos per host for one-process-per-host launch."""
    by_host = {}
    for s in slot_infos:
        by_host.setdefault(s.hostname, []).append(s)
    return by_host
