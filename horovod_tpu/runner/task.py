"""Worker-side bootstrap for ``horovod_tpu.run()``.

Reference: horovod/runner/run_task.py + task_fn.py — loads the cloudpickled
function, initializes, executes, reports the result.
"""

import os
import sys

import cloudpickle


def _load_payload(spec):
    """``kv:scope/key`` fetches the pickled function from the launcher's KV
    store (works without a shared filesystem — reference ships the pickle
    over its driver/task socket RPC, runner/run_task.py); anything else is a
    local path."""
    if spec.startswith("kv:"):
        from horovod_tpu.runner.http_kv import KVStoreClient
        scope, key = spec[3:].split("/", 1)
        client = KVStoreClient(os.environ["HOROVOD_KV_ADDR"],
                               int(os.environ["HOROVOD_KV_PORT"]))
        return client.wait_for(scope, key, timeout=60)
    with open(spec, "rb") as f:
        return f.read()


def _register_bootstrap():
    """Reachability probe: record which address this worker routes to the
    driver from, keyed by its host slot, BEFORE collective init.

    Reference: task_fn.py:23-54 — tasks probe routable NICs and report
    their interfaces so the driver can diagnose dead launches early.
    Here the successful signed KV write IS the routability proof (worker →
    driver control plane), and the recorded source address tells operators
    which interface that was. Failures are non-fatal — the probe is a
    diagnostic, not a gate."""
    kv_addr = os.environ.get("HOROVOD_KV_ADDR")
    kv_port = os.environ.get("HOROVOD_KV_PORT")
    if not (kv_addr and kv_port):
        return
    try:
        import json
        import socket
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((kv_addr, int(kv_port)))
            src = s.getsockname()[0]
        from horovod_tpu.common.config import Config
        from horovod_tpu.runner.http_kv import KVStoreClient
        # Config.from_env resolves the process index for ALL launch paths
        # (HOROVOD_CROSS_RANK for ssh; OMPI/PMI/Slurm env for mpirun/jsrun,
        # which export no per-host HOROVOD rank) — a plain env read here
        # would collapse every MPI worker onto slot "0".
        slot = str(Config.from_env().cross_rank)
        KVStoreClient(kv_addr, int(kv_port)).put(
            "bootstrap", slot,
            json.dumps({"hostname": socket.gethostname(), "src_addr": src,
                        "pid": os.getpid()}).encode())
    except Exception as e:  # diagnostic only
        print(f"# bootstrap probe failed (continuing): {e}", file=sys.stderr)


def main():
    _register_bootstrap()
    func, args, kwargs = cloudpickle.loads(_load_payload(sys.argv[1]))

    # Site hooks may force a platform via jax.config at interpreter start,
    # overriding JAX_PLATFORMS; re-assert the launcher's env choice.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    import horovod_tpu as hvd
    hvd.init()
    result = func(*args, **kwargs)

    kv_addr = os.environ.get("HOROVOD_KV_ADDR")
    kv_port = os.environ.get("HOROVOD_KV_PORT")
    if kv_addr and kv_port:
        from horovod_tpu.runner.http_kv import KVStoreClient
        key = str(hvd.cross_rank())
        init_version = os.environ.get("HOROVOD_ELASTIC_INIT_VERSION")
        if os.environ.get("HOROVOD_ELASTIC") and init_version:
            # Version-scoped so results computed under a superseded
            # membership are ignored by the harvest (see elastic driver).
            key = f"{init_version}/{key}"
        client = KVStoreClient(kv_addr, int(kv_port))
        client.put("results", key, cloudpickle.dumps(result))
        if os.environ.get("HOROVOD_ELASTIC"):
            # Declare the job winding down BEFORE exiting: the driver must
            # not rebalance on a discovery blip once any worker finished
            # cleanly (its result would be wiped and the new membership
            # would wait forever on this exited rank).
            client.put("elastic", "finished", b"1")
    # Finalize the goodput run journal on the clean-exit path, while the
    # telemetry agent is still alive to contribute the cluster view.
    # Relying on atexit is not enough: compat elastic workers end in
    # os._exit (see _compat_exit), which skips atexit entirely.
    try:
        from horovod_tpu.goodput import ledger as _goodput
        _goodput.shutdown()
    except Exception:
        pass
    hvd.shutdown()
    _orderly_distributed_exit()


def _orderly_distributed_exit():
    """CLEAN-finish disconnect from the jax.distributed cluster: run the
    real shutdown barrier, then dismantle local state.

    The elastic FAILURE-recovery teardown must never run the barrier (a
    dead peer can't join it — common/basics.py teardown_distributed drops
    references instead), but a clean finish is the opposite case: every
    rank is alive and exiting together, so the barrier completes, each
    agent sends a proper ShutdownTask (stopping its heartbeat/error-poll
    threads first), and nobody's teardown looks like a task death to the
    coordination service. Skipping this and letting references (or
    interpreter finalization) destroy clients abruptly races each
    client's destructor against its own polling thread and the service's
    stream-break detection — either race ends in the hardwired fatal
    callback, turning a clean exit into a crash the driver then
    blacklists."""
    if not os.environ.get("HOROVOD_ELASTIC"):
        return
    from horovod_tpu.common import basics
    if not basics._distributed_client_active():
        # No live cluster, but a membership change may have left leaked
        # compat objects (e.g. a survivor that shrank to a single-process
        # world re-inits with no distributed client at all) — those still
        # forbid interpreter finalization.
        if basics.elastic_compat_leaks():
            _compat_exit()
        return
    try:
        from jax._src import distributed as _dist
        client = _dist.global_state.client
        if client is not None:
            client.shutdown()
    except Exception as e:  # peer died post-training: fall through
        print(f"# distributed shutdown barrier failed (continuing): {e}",
              file=sys.stderr)
    basics.teardown_distributed()
    if basics.elastic_compat_leaks():
        _compat_exit()


def _compat_exit():
    """End a jax-0.4.x compat elastic worker with ``os._exit(0)``,
    coordinator last.

    The process holds LEAKED compat coordination clients/services
    (common/basics.py): destroying a connected 0.4.x client races its own
    error-polling thread, and a service dying while any peer's client
    still polls it fires every poller's hardwired fatal callback. Normal
    interpreter exit would run exactly those destructors during
    finalization, so the only clean ending is ``os._exit`` — no atexit
    (``hvd.shutdown()`` already ran), no GC, no finalizers. Ordering
    matters too: every peer's leaked clients poll services hosted by the
    rank-0 PROCESS (superseded memberships' services live where their
    rank 0 ran — with in-place recovery that is the current rank 0; a
    coordinator-host death recovers by full restart, not in place, so no
    leaks cross it). Rank 0 therefore exits LAST: peers post an
    exit-ready mark to the runner KV and die; rank 0 waits for the marks
    plus a short grace, then dies, taking all leaked services with it
    once nobody is left to poll them."""
    import time
    rank = int(os.environ.get("HOROVOD_CROSS_RANK", "0") or 0)
    size = int(os.environ.get("HOROVOD_CROSS_SIZE", "1") or 1)
    version = os.environ.get("HOROVOD_ELASTIC_INIT_VERSION", "0")
    kv_addr = os.environ.get("HOROVOD_KV_ADDR")
    kv_port = os.environ.get("HOROVOD_KV_PORT")
    try:
        if kv_addr and kv_port:
            from horovod_tpu.runner.http_kv import KVStoreClient
            client = KVStoreClient(kv_addr, int(kv_port))
            if rank == 0:
                want = {str(r) for r in range(1, size)}
                deadline = time.monotonic() + 30
                while want and time.monotonic() < deadline:
                    want = {r for r in want if not client.get(
                        "exit_ready", f"{version}/{r}")}
                    if want:
                        time.sleep(0.2)
                # Grace: a peer posts its mark a few syscalls before its
                # os._exit actually severs its leaked-client connections.
                time.sleep(1.0)
            else:
                client.put("exit_ready", f"{version}/{rank}", b"1")
    except Exception as e:  # KV gone: exit anyway, driver reaps us
        print(f"# compat exit coordination failed (continuing): {e}",
              file=sys.stderr)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
