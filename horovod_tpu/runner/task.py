"""Worker-side bootstrap for ``horovod_tpu.run()``.

Reference: horovod/runner/run_task.py + task_fn.py — loads the cloudpickled
function, initializes, executes, reports the result.
"""

import os
import sys

import cloudpickle


def main():
    fn_path = sys.argv[1]
    with open(fn_path, "rb") as f:
        func, args, kwargs = cloudpickle.load(f)

    # Site hooks may force a platform via jax.config at interpreter start,
    # overriding JAX_PLATFORMS; re-assert the launcher's env choice.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    import horovod_tpu as hvd
    hvd.init()
    result = func(*args, **kwargs)

    kv_addr = os.environ.get("HOROVOD_KV_ADDR")
    kv_port = os.environ.get("HOROVOD_KV_PORT")
    if kv_addr and kv_port:
        from horovod_tpu.runner.http_kv import KVStoreClient
        KVStoreClient(kv_addr, int(kv_port)).put(
            "results", str(hvd.cross_rank()), cloudpickle.dumps(result))
    hvd.shutdown()


if __name__ == "__main__":
    main()
