"""Worker-side bootstrap for ``horovod_tpu.run()``.

Reference: horovod/runner/run_task.py + task_fn.py — loads the cloudpickled
function, initializes, executes, reports the result.
"""

import os
import sys

import cloudpickle


def _load_payload(spec):
    """``kv:scope/key`` fetches the pickled function from the launcher's KV
    store (works without a shared filesystem — reference ships the pickle
    over its driver/task socket RPC, runner/run_task.py); anything else is a
    local path."""
    if spec.startswith("kv:"):
        from horovod_tpu.runner.http_kv import KVStoreClient
        scope, key = spec[3:].split("/", 1)
        client = KVStoreClient(os.environ["HOROVOD_KV_ADDR"],
                               int(os.environ["HOROVOD_KV_PORT"]))
        return client.wait_for(scope, key, timeout=60)
    with open(spec, "rb") as f:
        return f.read()


def _register_bootstrap():
    """Reachability probe: record which address this worker routes to the
    driver from, keyed by its host slot, BEFORE collective init.

    Reference: task_fn.py:23-54 — tasks probe routable NICs and report
    their interfaces so the driver can diagnose dead launches early.
    Here the successful signed KV write IS the routability proof (worker →
    driver control plane), and the recorded source address tells operators
    which interface that was. Failures are non-fatal — the probe is a
    diagnostic, not a gate."""
    kv_addr = os.environ.get("HOROVOD_KV_ADDR")
    kv_port = os.environ.get("HOROVOD_KV_PORT")
    if not (kv_addr and kv_port):
        return
    try:
        import json
        import socket
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((kv_addr, int(kv_port)))
            src = s.getsockname()[0]
        from horovod_tpu.common.config import Config
        from horovod_tpu.runner.http_kv import KVStoreClient
        # Config.from_env resolves the process index for ALL launch paths
        # (HOROVOD_CROSS_RANK for ssh; OMPI/PMI/Slurm env for mpirun/jsrun,
        # which export no per-host HOROVOD rank) — a plain env read here
        # would collapse every MPI worker onto slot "0".
        slot = str(Config.from_env().cross_rank)
        KVStoreClient(kv_addr, int(kv_port)).put(
            "bootstrap", slot,
            json.dumps({"hostname": socket.gethostname(), "src_addr": src,
                        "pid": os.getpid()}).encode())
    except Exception as e:  # diagnostic only
        print(f"# bootstrap probe failed (continuing): {e}", file=sys.stderr)


def main():
    _register_bootstrap()
    func, args, kwargs = cloudpickle.loads(_load_payload(sys.argv[1]))

    # Site hooks may force a platform via jax.config at interpreter start,
    # overriding JAX_PLATFORMS; re-assert the launcher's env choice.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    import horovod_tpu as hvd
    hvd.init()
    result = func(*args, **kwargs)

    kv_addr = os.environ.get("HOROVOD_KV_ADDR")
    kv_port = os.environ.get("HOROVOD_KV_PORT")
    if kv_addr and kv_port:
        from horovod_tpu.runner.http_kv import KVStoreClient
        key = str(hvd.cross_rank())
        init_version = os.environ.get("HOROVOD_ELASTIC_INIT_VERSION")
        if os.environ.get("HOROVOD_ELASTIC") and init_version:
            # Version-scoped so results computed under a superseded
            # membership are ignored by the harvest (see elastic driver).
            key = f"{init_version}/{key}"
        client = KVStoreClient(kv_addr, int(kv_port))
        client.put("results", key, cloudpickle.dumps(result))
        if os.environ.get("HOROVOD_ELASTIC"):
            # Declare the job winding down BEFORE exiting: the driver must
            # not rebalance on a discovery blip once any worker finished
            # cleanly (its result would be wiped and the new membership
            # would wait forever on this exited rank).
            client.put("elastic", "finished", b"1")
    hvd.shutdown()


if __name__ == "__main__":
    main()
