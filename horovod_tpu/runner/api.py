"""In-process ``run()`` / ``run_elastic()`` APIs.

Reference: horovod/runner/__init__.py:95-247 — ``horovod.run(func, np=…)``
cloudpickles ``func`` and launches it on every rank, returning the per-rank
results; with elastic args it routes through ``gloo_run_elastic``
(runner/launch.py:689).

TPU adaptation: with one process per host, ``func`` executes once per host;
the pickled function ships through the launcher's KV store (no shared
filesystem needed) and results are collected back through it, host-major. On
a single host this degenerates to "init and call" with zero serialization.
"""

import os
import sys

import cloudpickle

from horovod_tpu.runner import launch as launch_mod

_TASK_CMD = [sys.executable, "-m", "horovod_tpu.runner.task", "kv:func/pickle"]


def _harvester(out):
    def harvest(kv):
        # Workers PUT pickled results into the KV store keyed by
        # cross_rank — reachable from remote hosts, unlike a local tmpdir
        # (reference: run collects per-rank results, runner/__init__.py).
        idx = 0
        while True:
            v = kv.get("results", str(idx))
            if v is None:
                break
            out[idx] = cloudpickle.loads(v)
            idx += 1
    return harvest


def _elastic_harvester(out, expected):
    """Elastic harvest: the driver records the final world's host count under
    elastic/nhosts; use it so a gap in the results scope raises (via
    :func:`_validate_elastic_results`) instead of silently truncating to the
    contiguous prefix."""

    def harvest(kv):
        raw = kv.get("elastic", "nhosts")
        if raw is None:
            _harvester(out)(kv)
            return
        expected["n"] = int(raw.decode() if isinstance(raw, bytes) else raw)
        fv = kv.get("elastic", "version")
        final_version = (fv.decode() if isinstance(fv, bytes) else fv) or "0"
        for i in range(expected["n"]):
            # Results are version-scoped: only the final membership's count
            # (runner/task.py keys writes by HOROVOD_ELASTIC_INIT_VERSION).
            v = kv.get("results", f"{final_version}/{i}")
            if v is not None:
                out[i] = cloudpickle.loads(v)

    return harvest


def _validate_elastic_results(harvested, expected):
    n = expected.get("n")
    if n is not None:
        missing = [i for i in range(n) if i not in harvested]
        if missing:
            raise RuntimeError(
                f"elastic run completed but results from host indices "
                f"{missing} were not reported")
    elif not harvested:
        raise RuntimeError("elastic run completed but no results reported")
    return [harvested[i] for i in sorted(harvested)]


def run(func, args=(), kwargs=None, np=None, hosts=None, hostfile=None,
        use_ssh=None, ssh_port=None, ssh_identity_file=None, verbose=False,
        extra_env=None):
    """Run ``func(*args, **kwargs)`` under horovod_tpu on the given hosts and
    return the list of per-host results (reference: runner/__init__.py run)."""
    kwargs = kwargs or {}
    single_host = hosts is None and hostfile is None
    if single_host:
        import horovod_tpu as hvd
        if extra_env:
            os.environ.update(extra_env)
        hvd.init()
        return [func(*args, **kwargs)]

    payload = cloudpickle.dumps((func, args, kwargs))
    argv = []
    if np:
        argv += ["-np", str(np)]
    if hosts:
        argv += ["-H", hosts]
    if hostfile:
        argv += ["--hostfile", hostfile]
    if ssh_port:
        argv += ["--ssh-port", str(ssh_port)]
    if ssh_identity_file:
        argv += ["--ssh-identity-file", ssh_identity_file]
    if verbose:
        argv += ["--verbose"]
    argv += _TASK_CMD

    parsed = launch_mod.parse_args(argv)
    harvested = {}
    rc = launch_mod._run_static(parsed, extra_env=extra_env,
                                harvest=_harvester(harvested),
                                kv_preload={("func", "pickle"): payload})
    if rc != 0:
        raise RuntimeError(f"hvdrun failed with exit code {rc}")
    n_hosts = len(set(s.hostname for s in _assignments_for(parsed)))
    missing = [i for i in range(n_hosts) if i not in harvested]
    if missing:
        raise RuntimeError(
            f"run() completed but results from host indices {missing} "
            f"were not reported")
    return [harvested[i] for i in range(n_hosts)]


def _assignments_for(parsed_args):
    from horovod_tpu.runner.hosts import get_host_assignments
    from horovod_tpu.runner.launch import _resolve_hosts
    return get_host_assignments(_resolve_hosts(parsed_args),
                                parsed_args.np or None)


def run_elastic(func, args=(), kwargs=None, min_np=1, max_np=None,
                host_discovery_script=None, slots_per_host=None,
                reset_limit=None, start_timeout=600, verbose=False):
    """Elastic variant (reference: horovod.run with elastic args routing to
    launch.py:689 ``_run_elastic`` → gloo_run_elastic).

    Surviving workers keep their process across membership changes and
    re-initialize in place (``horovod_tpu.elastic.TpuState`` +
    ``@horovod_tpu.elastic.run`` restore the last commit and resume at the
    new world size, reference: common/elastic.py run_fn); workers on removed
    hosts are reaped and workers on added hosts start fresh.
    Returns the per-host results of the final (surviving) assignment.
    """
    kwargs = kwargs or {}
    if host_discovery_script is None:
        # Single-host elastic degenerates to plain run.
        return run(func, args, kwargs)

    from horovod_tpu.runner.elastic.driver import run_elastic_driver

    payload = cloudpickle.dumps((func, args, kwargs))
    argv = ["--min-np", str(min_np)]
    if max_np:
        argv += ["--max-np", str(max_np)]
    argv += ["--host-discovery-script", host_discovery_script]
    if slots_per_host:
        argv += ["--slots-per-host", str(slots_per_host)]
    if reset_limit is not None:
        argv += ["--reset-limit", str(reset_limit)]
    argv += ["--start-timeout", str(start_timeout)]
    if verbose:
        argv += ["--verbose"]
    argv += _TASK_CMD

    parsed = launch_mod.parse_args(argv)
    harvested = {}
    expected = {}
    rc = run_elastic_driver(parsed,
                            harvest=_elastic_harvester(harvested, expected),
                            kv_preload={("func", "pickle"): payload})
    if rc != 0:
        raise RuntimeError(f"elastic run failed with exit code {rc}")
    return _validate_elastic_results(harvested, expected)
