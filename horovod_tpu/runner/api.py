"""In-process ``run()`` API.

Reference: horovod/runner/__init__.py:95-247 — ``horovod.run(func, np=…)``
cloudpickles ``func`` and launches it on every rank, returning the per-rank
results.

TPU adaptation: with one process per host, ``func`` executes once per host;
results are collected through the KV store and returned host-major. On a
single host this degenerates to "init and call" with zero serialization.
"""

import os
import sys
import tempfile

import cloudpickle

from horovod_tpu.runner import launch as launch_mod


def run(func, args=(), kwargs=None, np=None, hosts=None, hostfile=None,
        use_ssh=None, ssh_port=None, ssh_identity_file=None, verbose=False,
        extra_env=None):
    """Run ``func(*args, **kwargs)`` under horovod_tpu on the given hosts and
    return the list of per-host results (reference: runner/__init__.py run)."""
    kwargs = kwargs or {}
    single_host = hosts is None and hostfile is None
    if single_host:
        import horovod_tpu as hvd
        if extra_env:
            os.environ.update(extra_env)
        hvd.init()
        return [func(*args, **kwargs)]

    with tempfile.TemporaryDirectory(prefix="hvdtpu_run_") as tmp:
        fn_path = os.path.join(tmp, "func.pkl")
        with open(fn_path, "wb") as f:
            cloudpickle.dump((func, args, kwargs), f)

        argv = []
        if np:
            argv += ["-np", str(np)]
        if hosts:
            argv += ["-H", hosts]
        if hostfile:
            argv += ["--hostfile", hostfile]
        if ssh_port:
            argv += ["--ssh-port", str(ssh_port)]
        if ssh_identity_file:
            argv += ["--ssh-identity-file", ssh_identity_file]
        if verbose:
            argv += ["--verbose"]
        argv += [sys.executable, "-m", "horovod_tpu.runner.task", fn_path]

        parsed = launch_mod.parse_args(argv)
        harvested = {}

        def harvest(kv):
            # Workers PUT pickled results into the KV store keyed by
            # cross_rank — reachable from remote hosts, unlike a local
            # tmpdir (reference: run collects per-rank results,
            # runner/__init__.py).
            idx = 0
            while True:
                v = kv.get("results", str(idx))
                if v is None:
                    break
                harvested[idx] = cloudpickle.loads(v)
                idx += 1

        rc = launch_mod._run_static(parsed, harvest=harvest)
        if rc != 0:
            raise RuntimeError(f"hvdrun failed with exit code {rc}")
        n_hosts = len(set(
            s.hostname for s in _assignments_for(parsed)))
        missing = [i for i in range(n_hosts) if i not in harvested]
        if missing:
            raise RuntimeError(
                f"run() completed but results from host indices {missing} "
                f"were not reported")
        return [harvested[i] for i in range(n_hosts)]


def _assignments_for(parsed_args):
    from horovod_tpu.runner.hosts import get_host_assignments
    from horovod_tpu.runner.launch import _resolve_hosts
    return get_host_assignments(_resolve_hosts(parsed_args),
                                parsed_args.np or None)


def run_elastic(func, args=(), kwargs=None, min_np=1, max_np=None,
                host_discovery_script=None, reset_limit=None, verbose=False):
    """Elastic variant (reference: horovod.run with elastic args +
    gloo_run_elastic)."""
    kwargs = kwargs or {}
    if host_discovery_script is None:
        # Single-host elastic degenerates to plain run
        return run(func, args, kwargs)
    raise NotImplementedError(
        "multi-host elastic run() API lands with the elastic driver CLI; "
        "use `hvdrun --min-np/--max-np --host-discovery-script` meanwhile")
