"""LSF cluster detection and host discovery.

Reference: horovod/runner/util/lsf.py (LSFUtils: ``using_lsf``, compute-host
discovery via CSM queries) and horovod/runner/launch.py's implicit LSF default
(when ``-H``/``-hostfile`` are absent and an LSF job is active, hosts come from
the allocation).

TPU-native simplification: we read the standard ``LSB_*`` environment directly
(``LSB_DJOB_HOSTFILE`` — one line per slot — preferred, ``LSB_MCPU_HOSTS``
fallback) instead of shelling out to IBM CSM utilities, because the launcher
only needs *hosts* (one worker process per host owns all its chips), not
per-core binding data.
"""

import collections
import os
import shutil


def using_lsf(env=None):
    """True when the current process runs inside an LSF job."""
    env = env if env is not None else os.environ
    return "LSB_JOBID" in env


def using_jsrun(env=None):
    """True when LSF is active and ``jsrun`` is on PATH (Spectrum LSF CSM)."""
    return using_lsf(env) and shutil.which("jsrun") is not None


def get_compute_hosts(env=None):
    """Ordered ``[(host, slots)]`` for the current LSF allocation.

    ``LSB_DJOB_HOSTFILE`` lists one hostname per allocated slot; counting
    occurrences yields slots per host. ``LSB_MCPU_HOSTS`` is the inline
    equivalent: ``"host1 n1 host2 n2 ..."``. The first host is the launch
    node in batch jobs; it is kept (matching the reference, which trains on
    the launch node too unless CSM says otherwise).
    """
    env = env if env is not None else os.environ
    hostfile = env.get("LSB_DJOB_HOSTFILE", "")
    counts = collections.OrderedDict()
    if hostfile and os.path.exists(hostfile):
        with open(hostfile) as f:
            for line in f:
                host = line.strip()
                if host:
                    counts[host] = counts.get(host, 0) + 1
        return list(counts.items())
    mcpu = env.get("LSB_MCPU_HOSTS", "")
    if mcpu:
        toks = mcpu.split()
        for host, n in zip(toks[::2], toks[1::2]):
            counts[host] = counts.get(host, 0) + int(n)
        return list(counts.items())
    raise ValueError(
        "LSF job detected (LSB_JOBID set) but neither LSB_DJOB_HOSTFILE nor "
        "LSB_MCPU_HOSTS is available to derive hosts")


def get_num_hosts(env=None):
    return len(get_compute_hosts(env))


def get_num_slots(env=None):
    return sum(n for _, n in get_compute_hosts(env))


def lsf_hosts_string(env=None):
    """``host1:n1,host2:n2`` string consumable by ``hvdrun -H``."""
    return ",".join(f"{h}:{n}" for h, n in get_compute_hosts(env))
