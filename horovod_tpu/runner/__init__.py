"""hvdrun launcher package.

Reference: horovod/runner/ (CLI launch.py:841 LoC, gloo_run, elastic driver,
HTTP rendezvous). The TPU control plane is much thinner: there is no per-rank
worker process to place — one process per *host* joins a
``jax.distributed`` cluster and owns that host's chips — so the launcher's
job is host bookkeeping, env plumbing, ssh fan-out, and elastic membership.
"""

from horovod_tpu.runner.api import run, run_elastic  # noqa: F401
