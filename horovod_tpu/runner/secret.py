"""Per-job shared secret for control-plane authentication.

Reference: horovod/runner/common/util/secret.py (random per-job key) +
network.py:306 (every driver/task message carries an HMAC digest computed
with that key, and unsigned/mis-signed messages are rejected).

The TPU build's control plane is the HTTP KV store (runner/http_kv.py); the
same model applies: the launcher mints one key per job, ships it to workers
through the environment (``HOROVOD_SECRET_KEY``), and every KV request and
response is HMAC-SHA256-signed.  This authenticates traffic — it does not
encrypt it, matching the reference's threat model.
"""

import hmac
import secrets

SECRET_ENV = "HOROVOD_SECRET_KEY"
DIGEST = "sha256"


def make_secret_key() -> str:
    """Random 256-bit hex key (reference: secret.py make_secret_key)."""
    return secrets.token_hex(32)


def compute_digest(secret: str, *parts: bytes) -> str:
    mac = hmac.new(secret.encode(), digestmod=DIGEST)
    for p in parts:
        mac.update(p)
        mac.update(b"\x00")
    return mac.hexdigest()


def check_digest(secret: str, digest: str, *parts: bytes) -> bool:
    if not digest:
        return False
    return hmac.compare_digest(compute_digest(secret, *parts), digest)
