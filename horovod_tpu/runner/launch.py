"""``hvdrun`` — the launcher CLI.

Reference: horovod/runner/launch.py (``horovodrun``; arg surface :286-596,
``_run``:806, ``run_commandline``:830). Differences by design:

- No gloo/mpi/jsrun controller selection — the data plane is XLA over ICI/DCN
  and bootstrap is ``jax.distributed``; the launcher only chooses hosts.
- One worker process per *host* (it owns all local chips), not per slot.
- The rendezvous HTTP-KV server still exists, serving elastic membership and
  out-of-band metadata (reference: RendezvousServer http_server.py:192).

Example::

    hvdrun -np 8 -H host1:4,host2:4 python train.py
    hvdrun -np 2 --min-np 1 --max-np 4 --host-discovery-script ./disc.sh \
        python train.py     # elastic
"""

import argparse
import os
import socket
import sys

from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.runner import config_parser
from horovod_tpu.runner.exec import (WorkerProcess,
                                     wait_for_any_failure_or_all_success)
from horovod_tpu.runner.hosts import (get_host_assignments,
                                      host_assignment_by_host, parse_host_files,
                                      parse_hosts)
from horovod_tpu.runner.http_kv import KVStoreServer
from horovod_tpu.runner.secret import SECRET_ENV, make_secret_key


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch TPU-native Horovod training across hosts.")
    p.add_argument("-v", "--version", action="store_true")
    p.add_argument("-np", "--num-proc", dest="np", type=int,
                   help="Total number of chips (ranks).")
    p.add_argument("-H", "--hosts", dest="hosts",
                   help="host1:chips,host2:chips list.")
    p.add_argument("-hostfile", "--hostfile", dest="hostfile",
                   help="Hostfile with 'host slots=N' lines.")
    p.add_argument("--ssh-port", type=int, dest="ssh_port")
    p.add_argument("--ssh-identity-file", dest="ssh_identity_file")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--config-file", dest="config_file")
    p.add_argument("--check-build", action="store_true")
    p.add_argument("--start-timeout", type=int, default=600,
                   dest="start_timeout")
    p.add_argument("--disable-cache", action="store_true")
    p.add_argument("--launcher", dest="launcher", default="auto",
                   choices=["auto", "ssh", "mpi", "jsrun"],
                   help="Process launcher: ssh fan-out (default), mpirun, or "
                        "jsrun on LSF. 'auto' picks jsrun inside an LSF "
                        "allocation with jsrun available, else ssh. "
                        "(reference: horovodrun --gloo/--mpi/... selection, "
                        "launch.py:286-596 + js_run path)")
    # Launcher-selection aliases (reference: --gloo/--mpi/--jsrun,
    # launch.py:286-596). "gloo" maps to the native ssh/KV rendezvous path —
    # the role gloo plays in the reference.
    p.add_argument("--gloo", action="store_true", dest="use_gloo")
    p.add_argument("--mpi", action="store_true", dest="use_mpi")
    p.add_argument("--jsrun", action="store_true", dest="use_jsrun")
    p.add_argument("--network-interface", "--network-interfaces",
                   dest="nics",
                   help="Comma-separated NICs workers may bind/probe on")
    p.add_argument("--output-filename", dest="output_filename",
                   help="Mirror each worker's output to "
                        "<dir>/rank.<NN>/stdout")
    p.add_argument("--prefix-output-with-timestamp", action="store_true",
                   dest="prefix_output_with_timestamp")
    p.add_argument("--tcp", action="store_true", dest="tcp_flag",
                   help="Accepted for compatibility (TPU data plane is ICI)")
    p.add_argument("--num-nccl-streams", type=int, dest="num_nccl_streams",
                   help="Accepted for compatibility; n/a on TPU")
    p.add_argument("--thread-affinity", type=int, dest="thread_affinity")
    p.add_argument("--binding-args", dest="binding_args")
    p.add_argument("--mpi-threads-disable", action="store_true",
                   dest="mpi_threads_disable", default=None)
    p.add_argument("--no-mpi-threads-disable", action="store_false",
                   dest="mpi_threads_disable")
    p.add_argument("--gloo-timeout-seconds", type=int,
                   dest="gloo_timeout_seconds")
    p.add_argument("--mpi-args", dest="mpi_args", default="",
                   help="Extra args appended to mpirun/jsrun.")

    tuning = p.add_argument_group("tuning")
    tuning.add_argument("--fusion-threshold-mb", type=float,
                        dest="fusion_threshold_mb")
    tuning.add_argument("--cycle-time-ms", type=float, dest="cycle_time_ms")
    tuning.add_argument("--cache-capacity", type=int, dest="cache_capacity")
    tuning.add_argument("--no-hierarchical-allreduce", action="store_false",
                        dest="hierarchical_allreduce", default=False)
    tuning.add_argument("--no-hierarchical-allgather", action="store_false",
                        dest="hierarchical_allgather", default=False)
    tuning.add_argument("--no-torus-allreduce", action="store_false",
                        dest="torus_allreduce", default=False)
    tuning.add_argument("--hierarchical-allreduce", action="store_true",
                        dest="hierarchical_allreduce")
    tuning.add_argument("--hierarchical-allgather", action="store_true",
                        dest="hierarchical_allgather")
    tuning.add_argument("--torus-allreduce", action="store_true",
                        dest="torus_allreduce",
                        help="2-level ICI/DCN torus allreduce "
                             "(fork knob HOROVOD_TORUS_ALLREDUCE)")
    tuning.add_argument("--wire-dtype", dest="wire_dtype",
                        choices=["", "bfloat16", "float16", "bf16", "fp16",
                                 "int8", "fp8"],
                        help="Collective wire dtype: 16-bit casts the fused "
                             "buckets; int8/fp8 ride the block-scaled "
                             "quantized exchange with error feedback "
                             "(HOROVOD_WIRE_DTYPE; docs/performance.md)")
    tuning.add_argument("--hierarchical-alltoall", action="store_true",
                        dest="hierarchical_alltoall",
                        help="2-level ICI/DCN alltoall for eligible "
                             "equal-splits exchanges and MoE expert "
                             "dispatch/combine when a slice hierarchy "
                             "exists (HOROVOD_HIERARCHICAL_ALLTOALL; "
                             "docs/performance.md)")
    tuning.add_argument("--alltoall-cross-dtype",
                        dest="alltoall_cross_dtype",
                        choices=["", "bfloat16", "float16", "bf16", "fp16",
                                 "int8", "fp8"],
                        help="Wire dtype of the hierarchical alltoall's "
                             "cross-slice (DCN) leg; int8/fp8 ride the "
                             "block-scaled exchange, 16-bit names keep it "
                             "exact (HOROVOD_ALLTOALL_CROSS_DTYPE). "
                             "Deliberately independent of --wire-dtype: "
                             "alltoall payloads are activations.")
    tuning.add_argument("--no-wire-error-feedback", action="store_true",
                        dest="no_wire_error_feedback",
                        help="Disable the quantized wire's error-feedback "
                             "residuals (HOROVOD_WIRE_ERROR_FEEDBACK=0)")
    tuning.add_argument("--control-plane", dest="control_plane",
                        choices=["flat", "hier"],
                        help="Control-plane strategy "
                             "(HOROVOD_CONTROL_PLANE): hier decomposes "
                             "negotiation + fusion-boundary sync into "
                             "slice-local rounds with leaders-only "
                             "cross-slice (DCN) rendezvous and shards "
                             "the HTTP-KV per slice; default is hier "
                             "whenever the slice layout has >1 slice. "
                             "See docs/scale_validation.md.")
    tuning.add_argument("--kv-shard-count", type=int,
                        dest="kv_shard_count",
                        help="Per-slice HTTP-KV shard listeners "
                             "(HOROVOD_KV_SHARD_COUNT; 0 = one per "
                             "slice when the hierarchical control "
                             "plane is armed).")
    tuning.add_argument("--kv-shard-port-base", type=int,
                        dest="kv_shard_port_base",
                        help="First shard listener port; shard k binds "
                             "base + k (HOROVOD_KV_SHARD_PORT_BASE; "
                             "0 = ephemeral).")
    tuning.add_argument("--control-lease-ms", type=float,
                        dest="control_lease_ms",
                        help="Boundary-stream leader lease in ms "
                             "(HOROVOD_CONTROL_LEASE_MS): a member "
                             "whose slice leader goes stale past this "
                             "window takes the re-publish over.")
    tuning.add_argument("--compile-cache-dir", dest="compile_cache_dir",
                        help="Persistent XLA compile-cache directory "
                             "exported to every worker "
                             "(HOROVOD_COMPILE_CACHE_DIR). Elastic launches "
                             "default it to <output-dir or cwd>/"
                             ".horovod_compile_cache so re-rendezvoused "
                             "workers skip XLA recompiles.")

    autotune = p.add_argument_group("autotune")
    autotune.add_argument("--autotune", action="store_true", dest="autotune")
    autotune.add_argument("--no-autotune", action="store_false",
                          dest="autotune")
    autotune.add_argument("--autotune-log-file", dest="autotune_log_file")
    autotune.add_argument("--autotune-warmup-samples", type=int,
                          dest="autotune_warmup_samples")
    autotune.add_argument("--autotune-steps-per-sample", type=int,
                          dest="autotune_steps_per_sample")
    autotune.add_argument("--autotune-bayes-opt-max-samples", type=int,
                          dest="autotune_bayes_opt_max_samples")
    autotune.add_argument("--autotune-gaussian-process-noise", type=float,
                          dest="autotune_gaussian_process_noise")

    autopilot = p.add_argument_group("autopilot")
    autopilot.add_argument("--autopilot", action="store_true",
                           dest="autopilot", default=False,
                           help="Arm the online self-driving controller "
                                "(HOROVOD_AUTOPILOT=1): closed-loop "
                                "tuning of fusion threshold/cycle, "
                                "dispatch strategy and wire dtype from "
                                "the signal plane, plus automated "
                                "straggler/dead-rank blacklist + "
                                "re-rendezvous under elastic launches. "
                                "See docs/performance.md.")
    autopilot.add_argument("--no-autopilot", action="store_true",
                           dest="no_autopilot",
                           help="Explicitly disarm the autopilot "
                                "(HOROVOD_AUTOPILOT=0), overriding an "
                                "ambient env opt-in.")
    autopilot.add_argument("--autopilot-interval", type=float,
                           dest="autopilot_interval",
                           help="Decision-epoch cadence in seconds "
                                "(HOROVOD_AUTOPILOT_INTERVAL, "
                                "default 10).")
    autopilot.add_argument("--autopilot-prior", type=str,
                           dest="autopilot_prior",
                           help="Twin-pretrained warm start: path to an "
                                "export_observations JSON artifact "
                                "written by horovod_tpu.sim.autopilot "
                                "(HOROVOD_AUTOPILOT_PRIOR). The "
                                "controller skips the categorical sweep "
                                "and starts the numeric search at the "
                                "twin's best point; a mismatched prior "
                                "is rejected with a warning, never "
                                "fatal. See docs/scale_validation.md.")

    tracing = p.add_argument_group("tracing")
    tracing.add_argument("--trace", action="store_true", dest="trace",
                         default=False,
                         help="Arm request/step span tracing "
                              "(HOROVOD_TRACE=1; the default — this flag "
                              "overrides an ambient env opt-out). Serving "
                              "requests expose their span tree at "
                              "GET /debug/trace/<rid>; merge per-rank "
                              "shards with `python -m "
                              "horovod_tpu.trace.analyze`.")
    tracing.add_argument("--no-trace", action="store_true",
                         dest="no_trace",
                         help="Disarm tracing (HOROVOD_TRACE=0).")
    tracing.add_argument("--trace-dir", dest="trace_dir",
                         help="Directory for per-rank trace shard dumps "
                              "(HOROVOD_TRACE_DIR).")

    goodput = p.add_argument_group("goodput accounting")
    goodput.add_argument("--goodput", action="store_true", dest="goodput",
                         default=False,
                         help="Arm job goodput/badput accounting "
                              "(HOROVOD_GOODPUT=1; the default — this "
                              "flag overrides an ambient env opt-out). "
                              "See docs/observability.md.")
    goodput.add_argument("--no-goodput", action="store_true",
                         dest="no_goodput",
                         help="Disarm goodput accounting "
                              "(HOROVOD_GOODPUT=0).")
    goodput.add_argument("--goodput-dir", dest="goodput_dir",
                         help="Directory for per-rank goodput summary "
                              "dumps at exit (HOROVOD_GOODPUT_DIR).")
    goodput.add_argument("--run-history-dir", dest="run_history_dir",
                         help="Durable cross-run history root "
                              "(HOROVOD_RUN_HISTORY_DIR): rank 0 appends "
                              "a per-run JSONL journal here; render and "
                              "regress with `python -m "
                              "horovod_tpu.goodput.report`.")

    timeline = p.add_argument_group("timeline")
    timeline.add_argument("--timeline-filename", dest="timeline_filename")
    timeline.add_argument("--no-timeline-mark-cycles", action="store_false",
                          dest="timeline_mark_cycles", default=False)
    timeline.add_argument("--timeline-mark-cycles", action="store_true",
                          dest="timeline_mark_cycles")

    stall = p.add_argument_group("stall")
    stall.add_argument("--stall-check", action="store_false",
                       dest="no_stall_check", default=False)
    stall.add_argument("--no-stall-check", action="store_true",
                       dest="no_stall_check")
    stall.add_argument("--stall-check-warning-time-seconds", type=float,
                       dest="stall_check_warning_time_seconds")
    stall.add_argument("--stall-check-shutdown-time-seconds", type=float,
                       dest="stall_check_shutdown_time_seconds")
    stall.add_argument("--order-check", action="store_true",
                       dest="order_check", default=False,
                       help="debug: cross-check every eager collective's "
                            "op/shape/dtype signature across ranks before "
                            "dispatch (catches SPMD order divergence as an "
                            "error instead of a hang)")

    flight = p.add_argument_group("flight recorder")
    flight.add_argument("--flight-dir", dest="flight_dir",
                        help="Directory for per-rank flight-recorder crash "
                             "dumps (HOROVOD_FLIGHT_DIR), exported to every "
                             "worker so an elastic disruption collects all "
                             "ranks' dumps in one place for "
                             "`python -m horovod_tpu.flight.analyze`. "
                             "See docs/observability.md.")
    flight.add_argument("--no-flight-recorder", action="store_true",
                        dest="no_flight_recorder",
                        help="Disable the always-armed flight recorder "
                             "(HOROVOD_FLIGHT_RECORDER=0).")

    profiler = p.add_argument_group("step profiler")
    profiler.add_argument("--no-step-profiler", action="store_true",
                          dest="no_step_profiler",
                          help="Disable the always-on step profiler "
                               "(HOROVOD_STEP_PROFILER=0).")
    profiler.add_argument("--step-report-file", dest="step_report_file",
                          help="Per-step attribution JSONL stream "
                               "(HVD_STEP_REPORT_FILE), exported to every "
                               "worker; render with `python -m "
                               "horovod_tpu.profile.report`.")
    profiler.add_argument("--profile-steps", dest="profile_steps",
                          help="a:b — capture a jax.profiler trace from "
                               "the step-a marker to the step-b marker "
                               "(HOROVOD_PROFILE_STEPS).")
    profiler.add_argument("--profile-dir", dest="profile_dir",
                          help="Trace-capture output directory "
                               "(HOROVOD_PROFILE_DIR).")
    profiler.add_argument("--profile-publish-steps", type=int,
                          dest="profile_publish_steps",
                          help="Watchdog cross-rank publish cadence in "
                               "steps (HOROVOD_PROFILE_PUBLISH_STEPS; "
                               "0 = local-only).")

    telemetry = p.add_argument_group("cluster telemetry")
    telemetry.add_argument("--no-telemetry", action="store_true",
                           dest="no_telemetry",
                           help="Disable the hierarchical cluster "
                                "telemetry plane (HOROVOD_TELEMETRY=0). "
                                "See docs/observability.md.")
    telemetry.add_argument("--telemetry-interval", type=float,
                           dest="telemetry_interval",
                           help="Telemetry beacon/aggregation cadence in "
                                "seconds (HOROVOD_TELEMETRY_INTERVAL, "
                                "default 2.0). Health thresholds derive "
                                "from it unless overridden.")

    chaos = p.add_argument_group("chaos")
    chaos.add_argument("--chaos-plan", dest="chaos_plan",
                       help="Fault-injection plan exported to every worker "
                            "(HOROVOD_CHAOS_PLAN): a YAML/JSON file path "
                            "(must be readable on the worker hosts) or "
                            "inline YAML/JSON. See docs/robustness.md.")
    chaos.add_argument("--chaos-seed", type=int, dest="chaos_seed",
                       help="Seed overriding the plan's own "
                            "(HOROVOD_CHAOS_SEED) — probabilistic triggers "
                            "are a counter-hash of it, so a seed pins the "
                            "whole injection schedule.")
    chaos.add_argument("--chaos-ledger", dest="chaos_ledger",
                       help="Directory for the per-rank JSONL injection "
                            "ledgers (HOROVOD_CHAOS_LEDGER).")

    serving = p.add_argument_group("serving")
    serving.add_argument("--serving", action="store_true", dest="serving",
                         default=False,
                         help="Serving mode (HOROVOD_SERVING=1): workers "
                              "run the continuous-batching inference "
                              "engine instead of a training loop — e.g. "
                              "`hvdrun --serving -np 8 python -m "
                              "horovod_tpu.serving`. Composes with the "
                              "elastic flags for zero-drop rolling "
                              "restarts (docs/inference.md).")
    serving.add_argument("--serving-port", type=int, dest="serving_port",
                         help="Request-frontend base port "
                              "(HOROVOD_SERVING_PORT); each process binds "
                              "port + local_rank, like --metrics-port.")
    serving.add_argument("--serving-slots", type=int, dest="serving_slots",
                         help="Decode-batch slot count "
                              "(HOROVOD_SERVING_SLOTS; the continuous "
                              "batch's fixed width).")
    serving.add_argument("--serving-queue-limit", type=int,
                         dest="serving_queue_limit",
                         help="Admission-queue capacity "
                              "(HOROVOD_SERVING_QUEUE_LIMIT; 0 = "
                              "unbounded). At the limit the frontend "
                              "answers 503 and /serving/health reports "
                              "saturated.")

    elastic = p.add_argument_group("elastic")
    elastic.add_argument("--min-np", "--min-num-proc", type=int,
                         dest="min_np")
    elastic.add_argument("--max-np", "--max-num-proc", type=int,
                         dest="max_np")
    elastic.add_argument("--elastic-timeout", type=int,
                         dest="elastic_timeout")
    elastic.add_argument("--blacklist-cooldown-range", nargs=2, type=float,
                         dest="blacklist_cooldown_range",
                         help="Base and cap (seconds) of the per-host "
                              "blacklist exponential cooldown")
    elastic.add_argument("--slots-per-host", type=int, dest="slots_per_host")
    elastic.add_argument("--host-discovery-script",
                         dest="host_discovery_script")
    elastic.add_argument("--reset-limit", type=int, dest="reset_limit")

    logg = p.add_argument_group("logging")
    logg.add_argument("--log-level", dest="log_level",
                      choices=["trace", "debug", "info", "warning", "error",
                               "fatal"])
    logg.add_argument("--log-without-timestamp", action="store_true",
                      dest="log_hide_timestamp")
    logg.add_argument("--log-with-timestamp", "--no-log-hide-timestamp",
                      action="store_false", dest="log_hide_timestamp")
    logg.add_argument("--log-hide-timestamp", action="store_true",
                      dest="log_hide_timestamp")

    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="Training command, e.g. python train.py")
    args = p.parse_args(argv)
    if args.config_file:
        config_parser.parse_config_file(args, args.config_file)
    # Launcher-selection aliases override --launcher auto-detection.
    if getattr(args, "use_mpi", False):
        args.launcher = "mpi"
    elif getattr(args, "use_jsrun", False):
        args.launcher = "jsrun"
    elif getattr(args, "use_gloo", False) and args.launcher == "auto":
        args.launcher = "ssh"
    return args


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _resolve_hosts(args):
    if args.hostfile:
        return parse_host_files(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    # Inside an LSF allocation with no explicit hosts, use the allocation
    # (reference: launch.py LSF default via util/lsf.py).
    from horovod_tpu.runner import lsf
    if lsf.using_lsf():
        return parse_hosts(lsf.lsf_hosts_string())
    # Default: all local chips, single host (reference defaults to
    # localhost:np, launch.py).
    nlocal = args.np or 1
    return parse_hosts(f"localhost:{nlocal}")


def _resolve_launcher(args):
    if getattr(args, "launcher", "auto") != "auto":
        return args.launcher
    # jsrun places tasks according to the LSF allocation, so auto-selecting it
    # is only valid when the user did not name hosts explicitly.
    if not args.hosts and not args.hostfile:
        from horovod_tpu.runner import lsf
        if lsf.using_jsrun():
            return "jsrun"
    return "ssh"


def build_worker_env(base_env, slot_infos_for_host, coordinator_addr,
                     coordinator_port, kv_port, args,
                     kv_shard_ports=None):
    """Per-host env (reference: gloo_run.py:66-78, 203-227 — the rank/size env
    contract between launcher and core)."""
    first = slot_infos_for_host[0]
    env = dict(base_env)
    env.update({
        "HOROVOD_RANK": str(first.rank),
        "HOROVOD_SIZE": str(first.size),
        "HOROVOD_LOCAL_RANK": str(first.local_rank),
        "HOROVOD_LOCAL_SIZE": str(first.local_size),
        "HOROVOD_CROSS_RANK": str(first.cross_rank),
        "HOROVOD_CROSS_SIZE": str(first.cross_size),
        "HOROVOD_COORDINATOR_ADDR": coordinator_addr,
        "HOROVOD_COORDINATOR_PORT": str(coordinator_port),
        "HOROVOD_KV_ADDR": coordinator_addr,
        "HOROVOD_KV_PORT": str(kv_port),
    })
    # Sharded KV plane: slice-local scopes resolve to these per-slice
    # listeners through the KVStoreClient scope router (the hierarchical
    # control plane's HTTP tier — common/control_plane.py).
    if kv_shard_ports:
        env["HOROVOD_KV_SHARD_PORTS"] = ",".join(
            str(p) for p in kv_shard_ports)
    if os.environ.get(SECRET_ENV):
        env[SECRET_ENV] = os.environ[SECRET_ENV]
    # Persistent XLA compile cache: propagate the launcher's dir; elastic
    # launches (whose whole point is fast recovery — every re-rendezvous
    # otherwise recompiles every program from scratch) default it to a
    # stable per-host path under the run's base dir. Workers on different
    # hosts each keep a local cache at the same relative path.
    cache_dir = os.environ.get("HOROVOD_COMPILE_CACHE_DIR") \
        or getattr(args, "compile_cache_dir", None)
    if not cache_dir and env.get("HOROVOD_ELASTIC"):
        cache_dir = os.path.join(
            getattr(args, "output_filename", None) or ".",
            ".horovod_compile_cache")
    if cache_dir:
        env.setdefault("HOROVOD_COMPILE_CACHE_DIR", cache_dir)
    # Flight-recorder collection point: every worker dumps into the same
    # directory so a disruption leaves one analyzable set of per-rank
    # rings (flight.analyze merges them). Elastic launches default it —
    # that is exactly the launch mode whose failures need forensics.
    flight_dir = os.environ.get("HOROVOD_FLIGHT_DIR") \
        or getattr(args, "flight_dir", None)
    if not flight_dir and env.get("HOROVOD_ELASTIC"):
        from horovod_tpu.flight.recorder import default_collection_dir
        flight_dir = default_collection_dir(
            getattr(args, "output_filename", None))
    if flight_dir:
        env.setdefault("HOROVOD_FLIGHT_DIR", flight_dir)
    if os.environ.get("HOROVOD_FLIGHT_RECORDER"):
        env.setdefault("HOROVOD_FLIGHT_RECORDER",
                       os.environ["HOROVOD_FLIGHT_RECORDER"])
    # Step-profiler knobs ride through to every worker (the ledger/
    # watchdog/capture run per process; the JSONL stream and capture dirs
    # are shared collection points like the flight dir).
    for var in ("HOROVOD_STEP_PROFILER", "HVD_STEP_REPORT_FILE",
                "HOROVOD_PROFILE_STEPS", "HOROVOD_PROFILE_DIR",
                "HOROVOD_PROFILE_PUBLISH_STEPS"):
        if os.environ.get(var):
            env.setdefault(var, os.environ[var])
    # Cluster-telemetry knobs + the virtual-slice override (slice
    # membership and health thresholds must agree across ranks), and every
    # remaining declared knob (common/config.py::Config) — logging,
    # elastic control, profiler tuning, roofline peaks, flash kernels and
    # the bench progress stream — ride through so a knob set on the
    # launcher is never silently single-process. scripts/lint.py (HVL002)
    # pins the "declared implies propagated" contract this loop exists
    # for.
    for var in ("HOROVOD_TELEMETRY", "HOROVOD_TELEMETRY_INTERVAL",
                "HOROVOD_TELEMETRY_METRICS", "HOROVOD_TELEMETRY_DEAD_AFTER",
                "HOROVOD_TELEMETRY_STALL_AFTER",
                "HOROVOD_TELEMETRY_STEP_LAG", "HOROVOD_TELEMETRY_SEQ_LAG",
                "HOROVOD_MESH_SLICES",
                "HOROVOD_LOG_LEVEL", "HOROVOD_LOG_HIDE_TIME",
                "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT",
                "HOROVOD_BLACKLIST_COOLDOWN_RANGE",
                "HOROVOD_ABORT_EXCLUDE_PORTS",
                "HOROVOD_PROFILE_HISTORY",
                "HOROVOD_PROFILE_PUBLISH_TIMEOUT_MS",
                "HOROVOD_PROFILE_Z_THRESHOLD",
                "HOROVOD_PROFILE_STRAGGLER_MIN_MS",
                "HOROVOD_PEAK_TFLOPS", "HOROVOD_PEAK_HBM_GBS",
                "HOROVOD_PEAK_ICI_GBS", "HOROVOD_PEAK_DCN_GBS",
                "HVD_FLASH_BLOCK", "HVD_FLASH_ALLOW_PADDED",
                "HVD_BENCH_PROGRESS_FILE", "HOROVOD_DCN_BYTES_BUDGET",
                "HOROVOD_WIRE_DTYPE", "HOROVOD_WIRE_ERROR_FEEDBACK",
                "HOROVOD_WIRE_DTYPE_DCN", "HOROVOD_HIERARCHICAL_DISPATCH",
                "HOROVOD_CROSS_OVERLAP", "HOROVOD_HIERARCHICAL_ALLTOALL",
                "HOROVOD_ALLTOALL_CROSS_DTYPE",
                "HOROVOD_CONTROL_PLANE", "HOROVOD_KV_SHARD_COUNT",
                "HOROVOD_KV_SHARD_PORT_BASE", "HOROVOD_CONTROL_LEASE_MS",
                "HOROVOD_AUTOPILOT", "HOROVOD_AUTOPILOT_INTERVAL",
                "HOROVOD_AUTOPILOT_MAX_REMOVALS",
                "HOROVOD_AUTOPILOT_HYSTERESIS",
                "HOROVOD_AUTOPILOT_MIN_WORLD",
                "HOROVOD_AUTOPILOT_PRIOR",
                "HOROVOD_SIM_KV_US", "HOROVOD_SIM_DCN_US",
                "HOROVOD_SERVING", "HOROVOD_SERVING_PORT",
                "HOROVOD_SERVING_SLOTS", "HOROVOD_SERVING_MAX_LEN",
                "HOROVOD_SERVING_PREFILL_CHUNK",
                "HOROVOD_SERVING_QUEUE_LIMIT",
                "HOROVOD_SERVING_MIGRATE_KV", "HOROVOD_SERVING_MODEL",
                "HOROVOD_SERVING_COMMIT_STEPS",
                "HOROVOD_TRACE", "HOROVOD_TRACE_CAPACITY",
                "HOROVOD_TRACE_DIR",
                "HOROVOD_DONATE_BUFFERS", "HOROVOD_DYNAMIC_PROCESS_SETS",
                "HOROVOD_JOIN_MODE", "HOROVOD_FLIGHT_CAPACITY",
                "HOROVOD_KV_RETRIES", "HOROVOD_KV_RETRY_BACKOFF_MS",
                "HOROVOD_KV_RETRY_BACKOFF_MAX_MS",
                "HOROVOD_SLO_TTFT_P99_MS", "HOROVOD_SLO_TPS",
                "HOROVOD_SLO_WINDOW_S",
                "HOROVOD_GOODPUT", "HOROVOD_GOODPUT_DIR",
                "HOROVOD_GOODPUT_JOURNAL_S", "HOROVOD_RUN_HISTORY_DIR",
                "HOROVOD_RUN_ID",
                "HOROVOD_METRICS", "HOROVOD_METRICS_PORT",
                "HOROVOD_METRICS_ADDR", "HOROVOD_METRICS_PREFIX"):
        if os.environ.get(var):
            env.setdefault(var, os.environ[var])
    # On the virtual-CPU tier (tests, dry runs) a rank is a virtual XLA CPU
    # device: pin each worker's device count to its slot count so the world
    # size equals the requested slots regardless of ambient XLA_FLAGS.
    ambient = {**os.environ, **env}
    if ambient.get("JAX_PLATFORMS", "").startswith("cpu"):
        flags = [f for f in ambient.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count="
                     f"{len(slot_infos_for_host)}")
        env["XLA_FLAGS"] = " ".join(flags)
    config_parser.set_env_from_args(env, args)
    return env


def _start_rendezvous(args):
    """Shared bootstrap for all static launchers: host assignment, coordinator
    address/port selection, KV server (reference: RendezvousServer start in
    launch_gloo, gloo_run.py:242-260)."""
    hosts = _resolve_hosts(args)
    slot_infos = get_host_assignments(hosts, args.np or None)
    by_host = host_assignment_by_host(slot_infos)
    coordinator_addr = socket.gethostname() \
        if len(by_host) > 1 else "localhost"
    coordinator_port = _free_port()
    # Mint a per-job secret so all KV control-plane traffic is HMAC-signed
    # (reference: secret.py per-job key + network.py:306 signed messages).
    os.environ.setdefault(SECRET_ENV, make_secret_key())
    # Per-slice shard listeners when the hierarchical control plane is
    # armed (HOROVOD_CONTROL_PLANE + slice layout / explicit
    # HOROVOD_KV_SHARD_COUNT): slice-local scopes never touch the root
    # listener, so no single HTTP socket carries O(world) traffic.
    from horovod_tpu.common import control_plane as _cp
    from horovod_tpu.common.config import _env_int
    kv = KVStoreServer(
        shards=_cp.kv_shard_count(slot_infos[0].size),
        shard_port_base=_env_int("HOROVOD_KV_SHARD_PORT_BASE", 0))
    kv_port = kv.start()
    kv.put("global", "size", str(slot_infos[0].size).encode())
    return slot_infos, by_host, coordinator_addr, coordinator_port, kv, kv_port


def _run_static_mpi(args, launcher, extra_env=None):
    """mpirun/jsrun fan-out: a single launcher invocation starts one worker
    per host; workers derive their process index from the MPI-provided env
    (OMPI_COMM_WORLD_RANK / PMI_RANK, see Config.from_env fallbacks) instead
    of per-host HOROVOD_CROSS_RANK."""
    from horovod_tpu.runner import js_run as js_mod
    from horovod_tpu.runner import mpi_run as mpi_mod

    slot_infos, by_host, coordinator_addr, coordinator_port, kv, kv_port = \
        _start_rendezvous(args)
    first = slot_infos[0]

    env = dict(extra_env or {})
    env.update({
        "HOROVOD_SIZE": str(first.size),
        "HOROVOD_LOCAL_SIZE": str(first.local_size),
        "HOROVOD_CROSS_SIZE": str(len(by_host)),
        "HOROVOD_COORDINATOR_ADDR": coordinator_addr,
        "HOROVOD_COORDINATOR_PORT": str(coordinator_port),
        "HOROVOD_KV_ADDR": coordinator_addr,
        "HOROVOD_KV_PORT": str(kv_port),
    })
    if kv.shard_ports:
        env["HOROVOD_KV_SHARD_PORTS"] = ",".join(
            str(p) for p in kv.shard_ports)
    if os.environ.get(SECRET_ENV):
        env[SECRET_ENV] = os.environ[SECRET_ENV]
    config_parser.set_env_from_args(env, args)
    import shlex
    extra = shlex.split(args.mpi_args) if getattr(args, "mpi_args", "") \
        else None
    host_slots = [(h, slots[0].local_size) for h, slots in by_host.items()]
    try:
        if launcher == "jsrun":
            return js_mod.js_run(host_slots, env, args.command,
                                 extra_js_args=extra)
        return mpi_mod.mpi_run(host_slots, env, args.command,
                               extra_mpi_args=extra)
    finally:
        kv.stop()


def _bootstrap_watchdog(kv, expected_cross_ranks, warn_after=45.0):
    """Diagnose dead launches early: workers register a reachability probe
    in the KV before collective init (runner/task.py _register_bootstrap,
    the analog of the reference's NIC probing task_fn.py:23-54). If some
    never do, warn naming the missing host slots — long before
    jax.distributed's multi-minute init timeout expires silently."""
    import threading as _threading
    import time as _time

    done = _threading.Event()

    def watch():
        deadline = _time.time() + warn_after
        missing = set(expected_cross_ranks)
        while missing and _time.time() < deadline:
            for r in list(missing):
                if kv.get("bootstrap", str(r)) is not None:
                    missing.discard(r)
            if done.wait(1.0):
                return  # run finished: a missing probe is moot, not a fault
        if missing and not done.is_set():
            hvd_logging.warning(
                "no bootstrap probe from host slot(s) %s after %.0fs — "
                "check ssh/network reachability from those hosts to the "
                "driver (KV port)", sorted(missing), warn_after)

    t = _threading.Thread(target=watch, daemon=True)
    t.start()
    t.cancel = done.set
    return t


def _run_static(args, extra_env=None, harvest=None, kv_preload=None):
    slot_infos, by_host, coordinator_addr, coordinator_port, kv, kv_port = \
        _start_rendezvous(args)
    for (scope, key), value in (kv_preload or {}).items():
        kv.put(scope, key, value)

    workers = []
    try:
        for host, slots in by_host.items():
            env = build_worker_env(dict(extra_env or {}), slots,
                                   coordinator_addr, coordinator_port,
                                   kv_port, args,
                                   kv_shard_ports=kv.shard_ports)
            workers.append(WorkerProcess(
                host, args.command, env, tag=f"{host}",
                ssh_port=args.ssh_port,
                ssh_identity_file=args.ssh_identity_file,
                output_dir=getattr(args, "output_filename", None),
                rank=slots[0].rank,
                prefix_timestamp=getattr(args, "prefix_output_with_timestamp",
                                         False)))
        expected_slots = [slots[0].cross_rank for slots in by_host.values()]
        watchdog = _bootstrap_watchdog(kv, expected_slots)
        failures = wait_for_any_failure_or_all_success(workers)
        watchdog.cancel()
        if failures:
            hvd_logging.error("workers failed: %s", failures)
            # Immediate reachability diagnosis (the watchdog was cancelled):
            # slots that never probed in likely couldn't reach the driver.
            missing = [r for r in expected_slots
                       if kv.get("bootstrap", str(r)) is None]
            if missing:
                hvd_logging.error(
                    "host slot(s) %s never reached the driver control "
                    "plane — check ssh/network from those hosts to the "
                    "driver (KV port)", sorted(missing))
            return 1
        if harvest is not None:
            harvest(kv)
        return 0
    finally:
        # Reap surviving workers on ANY exit path: an exception propagating
        # out of the wait (driver-side timeout/interrupt) must not leave
        # orphaned worker processes running — possibly blocked inside a
        # device collective that outlives the KV store (reference:
        # gloo_run terminates the job on driver exit). No-op for workers
        # that already exited.
        try:
            for w in workers:
                try:
                    w.terminate()
                except Exception:  # noqa: BLE001 — best-effort reaping
                    pass
        finally:
            kv.stop()


def _run_elastic(args):
    from horovod_tpu.runner.elastic.driver import run_elastic_driver
    return run_elastic_driver(args)


def run_commandline(argv=None):
    args = parse_args(argv)
    if args.version:
        from horovod_tpu.version import __version__
        print(__version__)
        return 0
    if args.check_build:
        from horovod_tpu.version import __version__
        print(f"Horovod-TPU v{__version__}:\n\n"
              "Available Frameworks:\n    [X] JAX/Flax\n\n"
              "Available Backends:\n    [X] XLA/ICI\n    [X] XLA/DCN\n\n"
              "Available Controllers:\n    [X] jax.distributed\n\n"
              "Available Features:\n    [X] elastic\n    [X] autotune\n"
              "    [X] timeline\n    [X] process sets\n")
        return 0
    if not args.command:
        print("error: no training command given", file=sys.stderr)
        return 2
    if args.log_level:
        os.environ["HOROVOD_LOG_LEVEL"] = args.log_level
    try:
        if args.host_discovery_script or args.min_np or args.max_np:
            return _run_elastic(args)
        launcher = _resolve_launcher(args)
        if launcher in ("mpi", "jsrun"):
            return _run_static_mpi(args, launcher)
        return _run_static(args)
    except (ValueError, TimeoutError) as e:
        print(f"hvdrun: error: {e}", file=sys.stderr)
        return 1


def main():
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
