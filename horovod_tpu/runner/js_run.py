"""Launch workers with ``jsrun`` on LSF/CSM clusters.

Reference: horovod/runner/js_run.py (jsrun resource-set construction from the
LSF allocation, :40-130). TPU-native simplification: one resource set per
host, one worker process per resource set — the worker owns the host's chips
and bootstraps ``jax.distributed``; per-core/per-GPU binding is irrelevant.
Workers learn their process index from ``OMPI_COMM_WORLD_RANK`` (jsrun is
Spectrum-MPI based) via Config.from_env fallbacks.
"""

import os
import shutil
import subprocess

from horovod_tpu.runner import lsf
from horovod_tpu.runner.mpi_run import _FORWARD_PREFIXES


def js_available(env=None):
    return shutil.which("jsrun") is not None


def build_js_command(nhosts, env, command, extra_js_args=None):
    """``jsrun`` line: one resource set per host, all cpus, one task each."""
    cmd = ["jsrun",
           "--nrs", str(nhosts),          # resource sets == hosts
           "--tasks_per_rs", "1",         # one worker per host
           "--rs_per_host", "1",
           "--cpu_per_rs", "ALL_CPUS",
           "--launch_distribution", "packed"]
    names = sorted(k for k in env if k.startswith(_FORWARD_PREFIXES))
    for n in names:
        cmd += ["-E", n]
    if extra_js_args:
        cmd += list(extra_js_args)
    cmd += list(command)
    return cmd


def js_run(hosts, env, command, extra_js_args=None, dry_run=False):
    """Run across the LSF allocation via jsrun; returns exit code."""
    if not js_available():
        raise RuntimeError("hvdrun --launcher jsrun requires jsrun on PATH "
                           "(Spectrum LSF with CSM)")
    nhosts = len(hosts) if hosts else lsf.get_num_hosts()
    full_env = {**os.environ, **env}
    cmd = build_js_command(nhosts, full_env, command,
                           extra_js_args=extra_js_args)
    if dry_run:
        return cmd
    proc = subprocess.run(cmd, env=full_env)
    return proc.returncode
