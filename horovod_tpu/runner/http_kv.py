"""HTTP key-value store: rendezvous + elastic metadata.

Reference: horovod/runner/http/http_server.py (RendezvousServer/KVStoreServer,
PUT/GET/DELETE ``/scope/key``) + http_client.py, and the C++ HTTPStore client
(horovod/common/gloo/http_store.cc) that workers use to rendezvous.

On TPU the heavy rendezvous (full-mesh TCP setup) is gone —
``jax.distributed`` does process bootstrap — but an out-of-band KV store is
still the right tool for elastic membership, dynamic-shape size exchange, and
worker notification, so the server/client pair is kept with the same
verb/scope protocol.
"""

import os
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import error as urlerror
from urllib import request as urlrequest

from horovod_tpu.chaos import injector as _chaos
from horovod_tpu.common.config import Config, _env_float, _env_int
from horovod_tpu.flight import recorder as _flight
from horovod_tpu.metrics import instruments as _metrics
from horovod_tpu.runner.secret import (SECRET_ENV, check_digest,
                                       compute_digest)

SIG_HEADER = "X-Hvd-Sig"


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence
        pass

    def _parse(self):
        parts = self.path.strip("/").split("/", 1)
        if len(parts) != 2:
            return None, None
        return parts[0], parts[1]

    def _authenticate(self, body: bytes) -> bool:
        """Fail-closed HMAC check when the server holds a job secret
        (reference: network.py:306 — unsigned/mis-signed messages rejected
        before deserialization)."""
        secret = self.server.secret
        if not secret:
            return True
        sig = self.headers.get(SIG_HEADER, "")
        if check_digest(secret, sig, self.command.encode(),
                        self.path.encode(), body):
            return True
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()
        return False

    def do_GET(self):
        if not self._authenticate(b""):
            return
        scope, key = self._parse()
        store = self.server.store
        with self.server.lock:
            value = store.get(scope, {}).get(key)
        if value is None:
            # 404 is an actionable signal (elastic workers treat a missing
            # assignment row as "removed from membership"), so it must be
            # as tamper-evident as a 200.
            self.send_response(404)
            self.send_header("Content-Length", "0")
            if self.server.secret:
                self.send_header(SIG_HEADER, compute_digest(
                    self.server.secret, b"RESP404", self.path.encode(), b""))
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        if self.server.secret:
            self.send_header(SIG_HEADER, compute_digest(
                self.server.secret, b"RESP", self.path.encode(), value))
        self.end_headers()
        self.wfile.write(value)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if not self._authenticate(value):
            return
        scope, key = self._parse()
        with self.server.lock:
            self.server.store.setdefault(scope, {})[key] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if not self._authenticate(b""):
            return
        scope, key = self._parse()
        with self.server.lock:
            if key == "*":
                self.server.store.pop(scope, None)
            else:
                self.server.store.get(scope, {}).pop(key, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class KVStoreServer:
    """reference: http_server.py KVStoreServer (threaded, scoped KV).

    ``shards`` > 0 additionally starts that many per-slice shard
    listeners (the sharded control plane: slice-local scopes — the
    ``scope@s<k>`` spelling from :mod:`horovod_tpu.common.control_plane`
    — are served by shard ``k % shards``, so no single HTTP listener
    carries O(world) traffic). The in-process accessors route through
    the same scope resolver the :class:`KVStoreClient` uses, so
    driver-side reads see one coherent store regardless of sharding."""

    def __init__(self, port=0, secret=None, shards=0, shard_port_base=0):
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), _KVHandler)
        self._httpd.store = {}
        self._httpd.lock = threading.Lock()
        self._httpd.secret = secret if secret is not None \
            else os.environ.get(SECRET_ENV)
        self._thread = None
        self._shards = [
            KVStoreServer(port=(shard_port_base + i) if shard_port_base
                          else 0, secret=secret)
            for i in range(max(int(shards), 0))]

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def shard_ports(self):
        return [s.port for s in self._shards]

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        for s in self._shards:
            s.start()
        return self.port

    def stop(self):
        # shutdown() handshakes with serve_forever and would block
        # forever if start() was never called (in-process users drive
        # get/put directly); close the listener socket either way.
        for s in self._shards:
            s.stop()
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)

    def _server_for(self, scope):
        """The shard (or root: self) whose store owns ``scope``."""
        from horovod_tpu.common.control_plane import shard_of_scope
        k = shard_of_scope(scope, len(self._shards))
        return self if k is None else self._shards[k]

    # Direct (in-process) access for the driver side — scope-routed.
    def get(self, scope, key):
        srv = self._server_for(scope)
        with srv._httpd.lock:
            return srv._httpd.store.get(scope, {}).get(key)

    def put(self, scope, key, value):
        srv = self._server_for(scope)
        with srv._httpd.lock:
            srv._httpd.store.setdefault(scope, {})[key] = value

    def delete(self, scope, key=None):
        srv = self._server_for(scope)
        with srv._httpd.lock:
            if key is None:
                srv._httpd.store.pop(scope, None)
            else:
                srv._httpd.store.get(scope, {}).pop(key, None)

    def prune_scope(self, scope, keep_prefixes):
        """Drop every key in ``scope`` — and in its slice-scoped family
        ``scope@s*`` across all shards — not starting with one of
        ``keep_prefixes`` (garbage collection for version-scoped keys).
        The family sweep keeps generation pruning correct when a scope's
        slice-local keys live on shard listeners (or, unsharded, as
        sibling scopes in the root store)."""
        family_prefix = scope + "@s"
        for srv in [self] + self._shards:
            with srv._httpd.lock:
                for sc in [s for s in srv._httpd.store
                           if s == scope or s.startswith(family_prefix)]:
                    d = srv._httpd.store.get(sc)
                    if not d:
                        continue
                    for k in [k for k in d
                              if not any(k.startswith(p)
                                         for p in keep_prefixes)]:
                        del d[k]


class KVStoreClient:
    """reference: http_client.py read_data_from_kvstore/put_data_into_kvstore,
    with per-job HMAC signing (network.py:306).

    Scope->shard resolver: when the launcher started per-slice shard
    listeners (``KVStoreServer(shards=...)``, ports propagated via
    ``HOROVOD_KV_SHARD_PORTS``), slice-local scopes (the ``scope@s<k>``
    spelling) resolve to their shard's port and job-global scopes to the
    root — every KV user (elastic rendezvous, the telemetry agent, the
    task bootstrap) routes through this one resolver."""

    def __init__(self, addr, port, timeout=30, secret=None, retries=None,
                 backoff_ms=None, backoff_max_ms=None, shard_ports=None):
        self._addr = addr
        self._base = f"http://{addr}:{port}"
        if shard_ports is None:
            raw = os.environ.get("HOROVOD_KV_SHARD_PORTS", "")
            shard_ports = [int(p) for p in raw.split(",") if p.strip()]
        self._shard_ports = list(shard_ports or [])
        self._timeout = timeout
        self._secret = secret if secret is not None \
            else os.environ.get(SECRET_ENV)
        # Read the knobs directly from the env (not Config.from_env: the
        # client is used before hvd.init, e.g. the task bootstrap probe);
        # the Config dataclass defaults stay the single source of truth.
        self._retries = retries if retries is not None \
            else max(_env_int("HOROVOD_KV_RETRIES", Config.kv_retries), 0)
        self._backoff_s = (backoff_ms if backoff_ms is not None
                           else _env_float("HOROVOD_KV_RETRY_BACKOFF_MS",
                                           Config.kv_retry_backoff_ms)
                           ) / 1000.0
        self._backoff_max_s = (
            backoff_max_ms if backoff_max_ms is not None
            else _env_float("HOROVOD_KV_RETRY_BACKOFF_MAX_MS",
                            Config.kv_retry_backoff_max_ms)) / 1000.0

    def _base_for(self, scope):
        from horovod_tpu.common.control_plane import shard_of_scope
        k = shard_of_scope(scope, len(self._shard_ports))
        if k is None:
            return self._base
        return f"http://{self._addr}:{self._shard_ports[k]}"

    def _request(self, method, path, body=None, base=None):
        req = urlrequest.Request((base or self._base) + path, data=body,
                                 method=method)
        if self._secret:
            req.add_header(SIG_HEADER, compute_digest(
                self._secret, method.encode(), path.encode(), body or b""))
        return req

    def _open(self, method, path, body=None, base=None):
        """One KV RPC with bounded retry on TRANSIENT transport faults
        (connection reset/refused mid-negotiation, HTTP 5xx) under
        jittered exponential backoff. Safe because every KV verb is
        idempotent (GET reads, PUT overwrites the same cell, DELETE of a
        gone key is a no-op). 4xx — including the 404 that ``get``
        interprets as "key absent" — are semantic answers and propagate
        immediately. A single reset used to kill the caller outright;
        now it costs one backoff sleep and a counter increment
        (``kv_client_retries_total``)."""
        delay = self._backoff_s
        for attempt in range(self._retries + 1):
            try:
                # Chaos site: each ATTEMPT is one site call, so a plan
                # dropping calls [0, 1] exercises exactly two retries.
                if _chaos.armed:
                    _chaos.fire("http_kv.request",
                                url=(base or self._base) + path)
                return urlrequest.urlopen(
                    self._request(method, path, body, base=base),
                    timeout=self._timeout)
            except urlerror.HTTPError as e:
                if e.code < 500 or attempt == self._retries:
                    # The 404 that get() maps to "absent" is a semantic
                    # answer and must NOT flood the flight ring — elastic
                    # version polls 404 constantly. Every other failure
                    # (a retries-exhausted 5xx, a 403 from a mismatched
                    # secret) is an anomaly the post-mortem needs.
                    if e.code != 404 and _flight.armed:
                        _flight.record_event("kv_error", name=path,
                                             what=f"http_{e.code}")
                    raise
            except (urlerror.URLError, ConnectionError, TimeoutError) as e:
                if attempt == self._retries:
                    if _flight.armed:
                        _flight.record_event("kv_error", name=path,
                                             what=type(e).__name__)
                    raise
            _metrics.record_kv_retry()
            if _flight.armed:
                _flight.record_event("kv_retry", name=path, seq=attempt)
            time.sleep(delay * (0.5 + random.random()))
            delay = min(delay * 2, self._backoff_max_s)

    def get(self, scope, key):
        path = f"/{scope}/{key}"
        _metrics.record_http_kv("get")
        try:
            with self._open("GET", path, base=self._base_for(scope)) as r:
                value = r.read()
                if self._secret and not check_digest(
                        self._secret, r.headers.get(SIG_HEADER, ""),
                        b"RESP", path.encode(), value):
                    raise PermissionError(
                        f"unsigned/tampered KV response for {path}")
                return value
        except urlerror.HTTPError as e:
            if e.code == 404:
                if self._secret and not check_digest(
                        self._secret, e.headers.get(SIG_HEADER, ""),
                        b"RESP404", path.encode(), b""):
                    raise PermissionError(
                        f"unsigned/tampered KV 404 for {path}") from e
                return None
            raise

    def put(self, scope, key, value: bytes):
        _metrics.record_http_kv("put", payload_bytes=len(value))
        with self._open("PUT", f"/{scope}/{key}", value,
                        base=self._base_for(scope)):
            pass

    def delete(self, scope, key="*"):
        _metrics.record_http_kv("delete")
        with self._open("DELETE", f"/{scope}/{key}",
                        base=self._base_for(scope)):
            pass

    def wait_for(self, scope, key, timeout=60, interval=0.1,
                 max_interval=2.0):
        """Poll until ``scope/key`` appears: capped exponential backoff
        with jitter from ``interval`` up to ``max_interval`` (a fixed
        0.1 s cadence used to hammer the store for the whole wait), every
        poll counted under ``control_plane_rpcs_total{http,wait_poll}``
        so a hot-wait is a visible counter, not a mystery load."""
        # Counted once as a "wait" on top of the per-iteration polls, so
        # the scrape distinguishes waits from raw get storms.
        _metrics.record_http_kv("wait")
        deadline = time.time() + timeout
        delay = max(float(interval), 0.01)
        while True:
            _metrics.record_http_kv("wait_poll")
            v = self.get(scope, key)
            if v is not None:
                return v
            remaining = deadline - time.time()
            if remaining <= 0:
                raise TimeoutError(
                    f"KV key {scope}/{key} not set within {timeout}s")
            time.sleep(min(delay * (0.5 + random.random()), remaining))
            delay = min(delay * 2, float(max_interval))
