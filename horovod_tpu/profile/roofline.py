"""Per-chip peak tables and MFU/roofline math.

Methodology (docs/performance.md): MFU is achieved model FLOP/s divided by
the chip's peak dense bf16 FLOP/s — model FLOPs come from
``Compiled.cost_analysis()`` (XLA's per-device estimate) or an explicit
``hvd.set_flops_per_step()`` (the paper-formula route, e.g. ``6·N·B·L``
for transformer training, the convention of the PaLM/MLPerf accounting).
Wire utilization is collective bytes-on-wire per second against the
interconnect roof: the ICI roof within a slice, the DCN roof across
slices/hosts.

Peak numbers are public spec-sheet figures per chip; the CPU row is an
order-of-magnitude placeholder so the CPU tier still produces ratios
(clearly labeled estimates). Override any peak with the env knobs
``HOROVOD_PEAK_TFLOPS`` / ``HOROVOD_PEAK_HBM_GBS`` /
``HOROVOD_PEAK_ICI_GBS`` / ``HOROVOD_PEAK_DCN_GBS``.
"""

import os

# chip -> peaks: dense bf16 TFLOP/s, HBM GB/s, ICI GB/s per chip
# (aggregate across links, one direction), DCN GB/s per host.
PEAKS = {
    # TPU v4: 275 TFLOP/s bf16, 32 GiB HBM2 @ 1228 GB/s, 6 ICI links
    # x 50 GB/s.
    "v4": {"bf16_tflops": 275.0, "hbm_gbs": 1228.0, "ici_gbs": 300.0,
           "dcn_gbs": 25.0},
    # TPU v5e: 197 TFLOP/s bf16, 16 GiB HBM2 @ 819 GB/s, 1600 Gbps ICI.
    "v5e": {"bf16_tflops": 197.0, "hbm_gbs": 819.0, "ici_gbs": 200.0,
            "dcn_gbs": 25.0},
    # TPU v5p: 459 TFLOP/s bf16, 95 GiB HBM @ 2765 GB/s, 4800 Gbps ICI.
    "v5p": {"bf16_tflops": 459.0, "hbm_gbs": 2765.0, "ici_gbs": 600.0,
            "dcn_gbs": 25.0},
    # CPU tier (tests, dry runs): order-of-magnitude placeholder so MFU
    # ratios stay computable — never quote these as hardware truth.
    "cpu": {"bf16_tflops": 0.2, "hbm_gbs": 50.0, "ici_gbs": 10.0,
            "dcn_gbs": 10.0, "estimate": True},
}


def detect_chip():
    """Best-effort chip generation from the local backend: one of the
    PEAKS keys. Never raises (profiling must not fail the job)."""
    try:
        import jax
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return "cpu"
        kind = (getattr(dev, "device_kind", "") or "").lower()
        if "v5p" in kind or "v5 p" in kind:
            return "v5p"
        if "v5e" in kind or "v5 lite" in kind or "v5litepod" in kind:
            return "v5e"
        if "v4" in kind:
            return "v4"
        if "v5" in kind:
            return "v5e"
        return "cpu"
    except Exception:  # noqa: BLE001
        return "cpu"


def chip_peaks(chip=None):
    """Peak table for ``chip`` (default: detected), env overrides
    applied. Returns a fresh dict: ``bf16_tflops``, ``hbm_gbs``,
    ``ici_gbs``, ``dcn_gbs``, ``chip`` (+ ``estimate`` on the CPU row)."""
    chip = chip or detect_chip()
    peaks = dict(PEAKS.get(chip, PEAKS["cpu"]))
    peaks["chip"] = chip

    def _ovr(env, key):
        v = os.environ.get(env)
        if v:
            try:
                peaks[key] = float(v)
            except ValueError:
                pass

    _ovr("HOROVOD_PEAK_TFLOPS", "bf16_tflops")
    _ovr("HOROVOD_PEAK_HBM_GBS", "hbm_gbs")
    _ovr("HOROVOD_PEAK_ICI_GBS", "ici_gbs")
    _ovr("HOROVOD_PEAK_DCN_GBS", "dcn_gbs")
    return peaks


def cost_from_compiled(compiled):
    """Per-device (FLOPs, bytes-accessed) of one compiled program from
    XLA's cost analysis (``Compiled.cost_analysis()`` is already
    per-device for SPMD programs). ``(None, None)`` when unavailable."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        nbytes = float(cost.get("bytes accessed", 0.0))
        return (flops if flops > 0 else None,
                nbytes if nbytes > 0 else None)
    except Exception:  # noqa: BLE001
        return None, None


def flops_from_compiled(compiled):
    """FLOPs half of :func:`cost_from_compiled` — callers without a
    bandwidth story (the ledger's MFU) fall back to
    ``hvd.set_flops_per_step()`` on None."""
    return cost_from_compiled(compiled)[0]


def mfu(flops_per_step, step_seconds, peaks=None):
    """Model FLOP/s utilization: achieved TFLOP/s / peak bf16 TFLOP/s.
    Returns ``(mfu_fraction, achieved_tflops)`` or ``(None, None)``."""
    if not flops_per_step or not step_seconds or step_seconds <= 0:
        return None, None
    peaks = peaks or chip_peaks()
    achieved = flops_per_step / step_seconds / 1e12
    peak = peaks.get("bf16_tflops") or 0.0
    return (achieved / peak if peak > 0 else None), achieved


def tier_time_estimate(bytes_by_tier, world_size, num_slices=1, peaks=None):
    """Roofline LOWER-BOUND time for one step's per-link-tier byte totals
    (the static cost model's ``bytes_by_tier``): the ICI total is spread
    over the ``world_size`` chips against the per-chip ICI roof, the DCN
    total over the ``num_slices`` cross-slice links against the DCN roof.
    Returns ``{"ici_s", "dcn_s", "bound", "chip", "estimate"}`` — ``bound``
    names the slower tier (the leg a hierarchical schedule must overlap or
    quantize first). Chip peaks come from :func:`chip_peaks`, so the CPU
    tier's placeholder roofs are flagged ``estimate``."""
    peaks = peaks or chip_peaks()
    world_size = max(int(world_size), 1)
    num_slices = max(int(num_slices), 1)
    ici = float(bytes_by_tier.get("ici", 0) or 0)
    dcn = float(bytes_by_tier.get("dcn", 0) or 0)
    ici_roof = (peaks.get("ici_gbs") or 0.0) * 1e9
    dcn_roof = (peaks.get("dcn_gbs") or 0.0) * 1e9
    t_ici = (ici / world_size / ici_roof) if ici_roof > 0 else None
    t_dcn = (dcn / num_slices / dcn_roof) if dcn_roof > 0 else None
    bound = "dcn" if (t_dcn or 0.0) >= (t_ici or 0.0) else "ici"
    return {"ici_s": t_ici, "dcn_s": t_dcn, "bound": bound,
            "chip": peaks.get("chip"),
            "estimate": bool(peaks.get("estimate"))}


def wire_utilization(bytes_on_wire, step_seconds, peaks=None,
                     cross_host=False):
    """Collective bytes/s against the interconnect roof (ICI within a
    slice, DCN across hosts). Returns ``(fraction, gbytes_per_s)`` or
    ``(None, None)``."""
    if not bytes_on_wire or not step_seconds or step_seconds <= 0:
        return None, None
    peaks = peaks or chip_peaks()
    gbs = bytes_on_wire / step_seconds / 1e9
    roof = peaks.get("dcn_gbs" if cross_host else "ici_gbs") or 0.0
    return (gbs / roof if roof > 0 else None), gbs
