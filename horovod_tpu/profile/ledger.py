"""The per-step performance ledger — where each training step's wall time
goes.

Hot-path contract (the same budget class as the metrics registry and the
flight recorder, guarded by ``TestStepProfilerOverhead``): instrumentation
sites read one module bool (``ledger.armed``) and, when on, pay a short
lock + a few float adds per event. No allocation beyond small dict
entries, no I/O, nothing held across RPC or flush boundaries. All I/O
(the ``HVD_STEP_REPORT_FILE`` JSONL stream, watchdog KV publishes,
capture start/stop) happens at STEP boundaries only.

Attribution model — a step window runs marker-to-marker (the step markers
the flight recorder already assigns: ``hvd.step_marker``, the torch
optimizer wrapper, elastic ``State.commit``); at the closing marker the
window's accumulators become one record:

- ``host_dispatch`` — Python dispatch-path overhead around eager
  collectives (plan lookup, staging, metrics/flight bookkeeping) — and
  any stall injected there (the chaos ``delay`` site lands here, which is
  what lets the watchdog name a straggler by its own-rank signal);
- ``collective``    — the compiled collective program call plus the
  localize wait (multi-process: where a rank blocks on its peers);
- ``fusion``        — fusion-runtime flush assembly/bookkeeping (the
  fused dispatch itself is counted under ``collective``);
- ``control_plane`` — blocking negotiation/KV exchange rounds;
- ``compute``       — the residual: wall minus everything above, clamped
  at zero (fusion flushes on the cycle thread overlap main-thread
  compute, so the categories are attribution, not a strict partition).

Records survive elastic resets (the deque is process-global, like the
flight ring); the OPEN window does not — ``reset_window()`` (wired to
``basics.shutdown``) discards in-flight accumulation and bumps the record
``epoch``, so recovery traffic is never attributed to the first
post-restore step and reports cannot double-count across a rendezvous.
"""

import collections
import json
import os
import threading
import time

from horovod_tpu.common.config import _env_bool, _env_int

CATEGORIES = ("host_dispatch", "collective", "fusion", "control_plane",
              "cross_wait")
# ``cross_wait`` — time the main thread spends awaiting a hierarchical
# bucket's in-flight CROSS-SLICE (DCN) leg at a deferred sync point
# (fence / next flush / shutdown) under the fusion runtime's cross-leg
# overlap. Booked here — OUTSIDE the flush critical path — it is the
# overlap-on A/B's measurable: with overlap collapsed the same wait lands
# inside the flush bracket (collective/fusion) instead.


def median(xs):
    """THE median of the profile subsystem (stdlib statistics.median —
    true mean-of-middle-pair for even n): the watchdog's z-scores, the
    report CLI's tables and summary() must all agree on p50 for the same
    records (the flight analyzer's upper-middle pick once hid a
    straggler — same lesson)."""
    import statistics
    return statistics.median(xs)


def robust_z(x, xs):
    """THE robust z of the profile/autopilot planes: ``x`` against
    median/MAD of ``xs``, denominator floored (5% of the median, 100us
    absolute) so microsecond-noise windows cannot fabricate infinite z.
    Returns ``(z, median)``. One definition — the watchdog's
    regression/straggler naming and the autopilot's
    revert-on-regression/drift checks must score identically."""
    med = median(xs)
    mad = median([abs(v - med) for v in xs])
    denom = max(1.4826 * mad, 0.05 * abs(med), 1e-4)
    return (x - med) / denom, med

DEFAULT_HISTORY = 512

# The one-word hot-path gate (the flight-recorder idiom).
armed = _env_bool("HOROVOD_STEP_PROFILER", True)


def enabled():
    return armed


def set_enabled(value):
    global armed
    armed = bool(value)


class StepLedger:
    """Accumulators for the open step window + the bounded record deque.
    Normally used through the module-level singleton; tests construct
    small instances directly."""

    def __init__(self, history=None):
        self._lock = threading.Lock()
        self._records = collections.deque(
            maxlen=history or _env_int("HOROVOD_PROFILE_HISTORY",
                                       DEFAULT_HISTORY))
        self._epoch = 0
        self._open = False
        self._t_open = 0.0
        self._acc = dict.fromkeys(CATEGORIES, 0.0)
        self._bytes_by_op = {}
        self._wire_bytes = {}        # wire dtype name -> bytes (fused path)
        self._n_collectives = 0
        self._n_flushes = 0
        self._fusion_defer_s = 0.0
        self._plan0 = None           # plan_cache_stats at window open
        self._kv0 = None             # negotiation stats at window open
        self._flops_per_step = None
        self._flops_source = None
        self._saw_explicit = False
        self._auto_step = 0
        self._peaks = None
        self._rank = None

    # --- hot-path recording (module wrappers gate on `armed`) ----------

    def add_dispatch(self, op, collective_s, host_s, nbytes):
        with self._lock:
            self._acc["collective"] += collective_s
            if host_s > 0.0:
                self._acc["host_dispatch"] += host_s
            self._n_collectives += 1
            if nbytes:
                self._bytes_by_op[op] = \
                    self._bytes_by_op.get(op, 0) + nbytes

    def add_fusion_flush(self, wall_s, collective_delta_s, defer_s,
                         wire_dtype=None, wire_bytes=0):
        with self._lock:
            # delta clamped: a step boundary landing mid-flush resets the
            # collective accumulator, which would otherwise make the
            # delta negative and INFLATE the fusion share.
            self._acc["fusion"] += max(
                wall_s - max(collective_delta_s, 0.0), 0.0)
            if defer_s > 0.0:
                self._fusion_defer_s += defer_s
            self._n_flushes += 1
            if wire_bytes:
                key = wire_dtype or "native"
                self._wire_bytes[key] = \
                    self._wire_bytes.get(key, 0) + wire_bytes

    def add_control_plane(self, dur_s):
        with self._lock:
            self._acc["control_plane"] += dur_s

    def add_cross_wait(self, dur_s):
        with self._lock:
            self._acc["cross_wait"] += dur_s

    def collective_total(self):
        """Current window's accumulated collective seconds — the fusion
        flush brackets snapshot this before/after so fused program time is
        not double-counted as flush overhead."""
        with self._lock:
            return self._acc["collective"]

    # --- window bookkeeping --------------------------------------------

    def _snapshot_externals(self):
        """Plan-cache and KV-traffic counters at window open, so each
        record carries the DELTAS for its step. Lazy imports: the modules
        are long loaded by the time steps run; failures leave None."""
        plan = kv = None
        try:
            from horovod_tpu.ops.collective_ops import plan_cache_stats
            plan = plan_cache_stats()
        except Exception:  # noqa: BLE001
            pass
        try:
            from horovod_tpu.common import negotiation
            kv = negotiation.stats_snapshot()
        except Exception:  # noqa: BLE001
            pass
        return plan, kv

    def _reset_acc_locked(self):
        for k in self._acc:
            self._acc[k] = 0.0
        self._bytes_by_op = {}
        self._wire_bytes = {}
        self._n_collectives = 0
        self._n_flushes = 0
        self._fusion_defer_s = 0.0

    def on_step(self, step):
        """Step-boundary marker: close the open window into a record (and
        return it), or open the first window (returns None). ``step`` of
        None is an auto mark (optimizer wrapper) — suppressed once any
        explicit step has been seen, mirroring the flight recorder."""
        now_p = time.perf_counter()
        now_w = time.time()
        with self._lock:
            if step is None:
                if self._saw_explicit:
                    return None
                self._auto_step += 1
                step_val = self._auto_step
            else:
                try:
                    step_val = int(step)
                except (TypeError, ValueError):
                    return None
                self._saw_explicit = True
            if not self._open:
                self._open = True
                self._t_open = now_p
                self._reset_acc_locked()
                window = None
            else:
                # Copy-and-reset under the lock, build OUTSIDE it: the
                # external snapshots (plan cache, negotiation stats) and
                # the roofline math take other modules' locks/imports,
                # and holding the hot-path lock across them would block
                # every cycle-thread record at each step boundary —
                # breaking this module's own short-lock contract. Events
                # recorded while we build accrue to the NEW window.
                window = {
                    "wall": now_p - self._t_open,
                    "acc": dict(self._acc),
                    "bytes_by_op": self._bytes_by_op,
                    "wire_bytes": self._wire_bytes,
                    "n_collectives": self._n_collectives,
                    "n_flushes": self._n_flushes,
                    "fusion_defer_s": self._fusion_defer_s,
                    "plan0": self._plan0, "kv0": self._kv0,
                    "epoch": self._epoch,
                }
                self._t_open = now_p
                self._reset_acc_locked()
        plan1, kv1 = self._snapshot_externals()
        rec = None
        if window is not None:
            rec = self._build_record(step_val, now_w, window, plan1, kv1)
        with self._lock:
            self._plan0, self._kv0 = plan1, kv1
            if rec is not None:
                self._records.append(rec)
        return rec

    def _build_record(self, step, now_w, window, plan1=None, kv1=None):
        """Turn one closed window snapshot into a record. Runs OUTSIDE
        the hot-path lock (the snapshot dict is ours alone)."""
        wall = window["wall"]
        acc = window["acc"]
        att = {k: round(v, 6) for k, v in acc.items()}
        att["compute"] = round(max(wall - sum(acc.values()), 0.0), 6)
        rec = {
            "step": step,
            "epoch": window["epoch"],
            "rank": self._rank if self._rank is not None
            else _env_int("HOROVOD_CROSS_RANK", 0),
            "t": round(now_w, 6),
            "wall_s": round(wall, 6),
            "attribution": att,
            "collectives": window["n_collectives"],
            "fused_flushes": window["n_flushes"],
            "fusion_defer_s": round(window["fusion_defer_s"], 6),
            "bytes_by_op": dict(window["bytes_by_op"]),
            "wire_bytes_by_dtype": dict(window["wire_bytes"]),
        }
        plan0, kv0 = window["plan0"], window["kv0"]
        if plan1 is not None and plan0 is not None:
            rec["plan"] = {
                "hits": plan1["hits"] - plan0["hits"],
                "misses": plan1["misses"] - plan0["misses"]}
        if kv1 is not None and kv0 is not None:
            rec["kv"] = {
                k: kv1[k] - kv0[k]
                for k in ("rounds", "gets", "fusion_sets", "fusion_gets")}
        self._add_roofline(rec, wall)
        return rec

    def _add_roofline(self, rec, wall):
        from horovod_tpu.profile import roofline
        if self._peaks is None:
            self._peaks = roofline.chip_peaks()
        rec["chip"] = self._peaks["chip"]
        # _build_record runs outside the hot-path lock by design (see
        # record_step); snapshot the flops pair so a concurrent
        # set_flops_per_step can't tear value/source between reads.
        with self._lock:
            flops_per_step = self._flops_per_step
            flops_source = self._flops_source
        if flops_per_step:
            frac, achieved = roofline.mfu(flops_per_step, wall,
                                          self._peaks)
            rec["flops_per_step"] = flops_per_step
            rec["flops_source"] = flops_source
            if frac is not None:
                rec["mfu"] = round(frac, 5)
            if achieved is not None:
                rec["achieved_tflops"] = round(achieved, 4)
        nbytes = sum(rec["bytes_by_op"].values())
        if nbytes:
            cross = False
            try:
                import jax
                cross = jax.process_count() > 1
            except Exception:  # noqa: BLE001
                pass
            frac, gbs = roofline.wire_utilization(nbytes, wall,
                                                  self._peaks, cross)
            if gbs is not None:
                rec["wire_gbs"] = round(gbs, 5)
            if frac is not None:
                rec["wire_util"] = round(frac, 5)
        return rec

    # --- reads ----------------------------------------------------------

    def records(self, last=None):
        with self._lock:
            out = list(self._records)
        return out if last is None else out[-last:]

    def reset_window(self):
        """Discard the OPEN window (recovery traffic must not bleed into
        the first post-restore step) and bump the record epoch; completed
        records are kept — reports survive elastic resets."""
        with self._lock:
            self._open = False
            self._epoch += 1
            self._reset_acc_locked()

    def set_flops_per_step(self, flops, source="explicit"):
        with self._lock:
            self._flops_per_step = float(flops) if flops else None
            self._flops_source = source if flops else None

    def summary(self):
        recs = self.records()
        out = {"enabled": armed, "steps": len(recs)}
        if not recs:
            return out
        walls = [r["wall_s"] for r in recs]
        n = len(walls)
        out["epoch"] = recs[-1]["epoch"]
        out["chip"] = recs[-1].get("chip")
        out["mean_wall_s"] = round(sum(walls) / n, 6)
        out["p50_wall_s"] = round(median(walls), 6)
        att = {}
        for cat in CATEGORIES + ("compute",):
            att[cat] = round(
                sum(r["attribution"].get(cat, 0.0) for r in recs) / n, 6)
        out["attribution_mean_s"] = att
        mfus = [r["mfu"] for r in recs if "mfu" in r]
        if mfus:
            out["mfu_mean"] = round(sum(mfus) / len(mfus), 5)
        return out


_ledger = StepLedger()


def get():
    return _ledger


# --- module-level hot-path API (what the instrumented sites call) ---------

def record_dispatch(op, collective_s, host_s, nbytes=0):
    if not armed:
        return
    _ledger.add_dispatch(op, collective_s, host_s, nbytes)


def record_fusion_flush(wall_s, collective_delta_s, defer_s=0.0,
                        wire_dtype=None, wire_bytes=0):
    if not armed:
        return
    _ledger.add_fusion_flush(wall_s, collective_delta_s, defer_s,
                             wire_dtype, wire_bytes)


def record_control_plane(dur_s):
    if not armed:
        return
    _ledger.add_control_plane(dur_s)


def record_cross_wait(dur_s):
    """Await of an overlapped hierarchical bucket's cross-slice leg at a
    deferred sync point (fusion cross-leg overlap)."""
    if not armed:
        return
    _ledger.add_cross_wait(dur_s)


def collective_total():
    return _ledger.collective_total() if armed else 0.0


_report_path = os.environ.get("HVD_STEP_REPORT_FILE", "")
_capture_armed = False


def on_step(step):
    """The flight recorder's step listener (``recorder.set_step_listener``
    wires it at import). Closes/opens ledger windows and performs the
    step-boundary side work: JSONL stream, metrics, watchdog, capture
    window, timeline step bracket. Never raises into the training loop."""
    if not armed:
        return
    try:
        rec = _ledger.on_step(step)
    except Exception:  # noqa: BLE001 — profiling must never fail the job
        return
    try:
        _step_side_work(step, rec)
    except Exception:  # noqa: BLE001
        pass
    # Hand the boundary to the goodput ledger (its own armed gate +
    # fail-soft wrapper): a closed window's attribution decomposes into
    # productive vs badput; the first open ends the init_compile phase.
    try:
        from horovod_tpu.goodput import ledger as _goodput
        _goodput.on_step_boundary(rec, step=step)
    except Exception:  # noqa: BLE001
        pass


def _step_side_work(step, rec):
    if rec is not None:
        if _report_path:
            try:
                with open(_report_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass
        from horovod_tpu.metrics import instruments as _metrics
        _metrics.record_step(rec["wall_s"])
        from horovod_tpu.profile import watchdog
        watchdog.observe(rec)
    if _capture_armed:
        # The window is keyed on the LEDGER's step clock: auto-marked
        # frontends (torch optimizer wrapper) pass step=None here, but
        # the closed record carries the ledger-assigned step — without
        # this the a:b capture would silently never fire for them.
        if rec is not None:
            eff_step = rec["step"]
        else:
            try:
                eff_step = int(step)
            except (TypeError, ValueError):
                eff_step = None
        if eff_step is not None:
            from horovod_tpu.profile import capture
            capture.on_step(eff_step)
    # Step bracket into the Chrome-trace timeline (aligned with the
    # flight recorder's clock via the timeline's clock_sync metadata).
    # Only for markers the ledger ACCEPTED as a window close: a
    # suppressed auto mark (torch optimizer.step alongside elastic
    # State.commit) must not paint a second STEP instant per step —
    # doubled brackets would halve every apparent step span, the defect
    # class the flight recorder's auto-suppression exists for.
    if rec is not None:
        try:
            from horovod_tpu.common import basics
            tl = basics.timeline()
            if tl is not None:
                tl.mark_step(rec["step"])
        except Exception:  # noqa: BLE001
            pass


def step_report(last=1):
    """The most recent completed step record (``last=1``, default), or the
    latest ``last`` records as a list, or every retained record
    (``last=None``). Returns None / [] before the first completed step."""
    recs = _ledger.records(last=last)
    if last == 1:
        return recs[-1] if recs else None
    return recs


def digest(last=32):
    """Compact beacon fields for the telemetry plane
    (:mod:`horovod_tpu.telemetry.digest`): current step + when it closed,
    recent wall/attribution means — the step-lag and stall inputs of the
    job health model. Bounded to the last ``last`` records."""
    recs = _ledger.records(last=last)
    out = {"enabled": armed, "steps": len(recs)}
    if not recs:
        return out
    latest = recs[-1]
    out["step"] = latest["step"]
    out["step_t"] = latest["t"]
    out["epoch"] = latest["epoch"]
    walls = [r["wall_s"] for r in recs]
    out["wall_mean_s"] = round(sum(walls) / len(walls), 6)
    att = {}
    for cat in CATEGORIES + ("compute",):
        att[cat] = round(
            sum(r["attribution"].get(cat, 0.0) for r in recs) / len(recs),
            6)
    out["attribution_mean_s"] = att
    mfus = [r["mfu"] for r in recs if "mfu" in r]
    if mfus:
        out["mfu_mean"] = round(sum(mfus) / len(mfus), 5)
    return out


def step_report_summary():
    """Aggregate over the retained records: mean/p50 wall, per-category
    attribution means, mean MFU — the bench.py ride-along field."""
    return _ledger.summary()


def set_flops_per_step(flops, source="explicit"):
    """Model FLOPs per training step for the MFU/roofline fields —
    explicit (``hvd.set_flops_per_step(6*N*B*L)``) or from
    ``roofline.flops_from_compiled(compiled)``."""
    _ledger.set_flops_per_step(flops, source)


def reset_window():
    if _ledger is not None:
        _ledger.reset_window()
    try:
        from horovod_tpu.profile import watchdog
        watchdog.reset()
    except Exception:  # noqa: BLE001
        pass


def configure(config):
    """Apply a Config's step-profiler knobs (called by ``basics.init``)."""
    global _report_path, _capture_armed
    set_enabled(config.step_profiler)
    if config.step_report_file:
        _report_path = config.step_report_file
    _ledger._rank = _env_int("HOROVOD_CROSS_RANK", 0)
    hist = _env_int("HOROVOD_PROFILE_HISTORY", DEFAULT_HISTORY)
    if hist != _ledger._records.maxlen:
        with _ledger._lock:
            _ledger._records = collections.deque(_ledger._records,
                                                 maxlen=max(hist, 8))
    if config.profile_steps:
        from horovod_tpu.profile import capture
        if capture.configure_window(config.profile_steps,
                                    config.profile_dir):
            _capture_armed = True
    from horovod_tpu.profile import watchdog
    watchdog.configure(config)


# Feed step markers into the ledger regardless of the flight recorder's
# own arming (the profiler and the forensics ring have independent
# switches; the marker call sites are shared).
from horovod_tpu.flight import recorder as _flight_recorder  # noqa: E402

_flight_recorder.set_step_listener(on_step)
