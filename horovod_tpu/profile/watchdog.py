"""Online straggler/regression watchdog over the step ledger.

Two detectors, both robust-z-score based (median/MAD — a single outlier
must not poison its own baseline), both emitting evidence WHILE THE JOB
RUNS (a metrics counter + a flight-ring event + a bounded findings list)
instead of waiting for the post-mortem analyzer:

- **regression** (local): the just-closed step's wall time against the
  rolling window of this rank's recent steps. Catches "the job got slower
  at step N" — thermal throttling, a noisy neighbor, a leak.
- **straggler** (cross-rank): every ``HOROVOD_PROFILE_PUBLISH_STEPS``
  steps each rank publishes its recent median wall AND median
  host-dispatch time over the jax.distributed KV (the existing control
  plane — no new transport), then reads its peers' row for the same
  round. The rank whose HOST-DISPATCH median is a robust-z outlier high
  is named: a straggler stalls in its own dispatch path (that is where
  the chaos ``delay`` site lands), while its peers show the wait under
  ``collective`` — so the signal separates the culprit from the victims.

Publish cadence is low (default every 16 steps) and reads are bounded
(``HOROVOD_PROFILE_PUBLISH_TIMEOUT_MS`` per peer, failures swallowed), so
the watchdog can never wedge a training loop; a peer too slow to publish
within the window is simply absent from that round (and will usually name
ITSELF when it arrives and reads everyone else's rows).
"""

import collections
import json
import threading
import time

from horovod_tpu.common.config import _env_float, _env_int
from horovod_tpu.profile.ledger import median as _median
from horovod_tpu.profile.ledger import robust_z as _robust_z

_MAX_FINDINGS = 64

_lock = threading.Lock()
_walls = collections.deque(maxlen=64)
_hosts = collections.deque(maxlen=64)
_steps_seen = 0
_round = 0
_gen = 0                     # bumped by reset(): keys must not collide
_findings = collections.deque(maxlen=_MAX_FINDINGS)

# knobs (configure() re-reads from Config/env)
_publish_every = _env_int("HOROVOD_PROFILE_PUBLISH_STEPS", 16)
_read_timeout_ms = _env_int("HOROVOD_PROFILE_PUBLISH_TIMEOUT_MS", 250)
_z_threshold = _env_float("HOROVOD_PROFILE_Z_THRESHOLD", 4.0)
_min_excess_s = _env_float("HOROVOD_PROFILE_STRAGGLER_MIN_MS", 5.0) / 1e3
_min_window = 8


def configure(config):
    global _publish_every, _read_timeout_ms, _z_threshold, _min_excess_s
    _publish_every = int(config.profile_publish_steps)
    _read_timeout_ms = _env_int("HOROVOD_PROFILE_PUBLISH_TIMEOUT_MS",
                                _read_timeout_ms)
    _z_threshold = _env_float("HOROVOD_PROFILE_Z_THRESHOLD", _z_threshold)
    _min_excess_s = _env_float("HOROVOD_PROFILE_STRAGGLER_MIN_MS",
                               _min_excess_s * 1e3) / 1e3


def reset():
    """Elastic reset: history and rounds restart (step times across a
    membership change are not comparable), and the key generation bumps
    so a replayed round number can never read a stale row."""
    global _steps_seen, _round, _gen
    with _lock:
        _walls.clear()
        _hosts.clear()
        _steps_seen = 0
        _round = 0
        _gen += 1


def findings(last=None):
    """Bounded list of watchdog findings, oldest first. Each is a dict:
    ``kind`` (straggler|regression), ``step``, plus kind-specific fields
    (``rank``/``z``/``value_s``/``median_s``)."""
    with _lock:
        out = list(_findings)
    return out if last is None else out[-last:]


def _emit(finding):
    with _lock:
        _findings.append(finding)
    try:
        from horovod_tpu.metrics import instruments as _metrics
        _metrics.record_profiler_event(finding["kind"])
    except Exception:  # noqa: BLE001
        pass
    if finding["kind"] == "straggler":
        # The goodput report's victim naming: this is the cross-rank
        # COMPARATIVE verdict (a straggler stalls in its own dispatch
        # path), stronger evidence than each rank's self-relative
        # straggler_wait excess — under a synchronous collective every
        # rank books wait, but only the victim is named here.
        try:
            from horovod_tpu.goodput import ledger as _goodput
            _goodput.note_straggler(finding.get("rank"))
        except Exception:  # noqa: BLE001
            pass
    try:
        from horovod_tpu.flight import recorder as _flight
        if _flight.armed:
            _flight.record_event(
                "profiler", what=finding["kind"],
                name=f"rank{finding['rank']}"
                if "rank" in finding else None,
                seq=finding.get("step"), dur=finding.get("value_s"))
    except Exception:  # noqa: BLE001
        pass
    try:
        from horovod_tpu.common import logging as hvd_logging
        hvd_logging.warning("step profiler watchdog: %s", finding)
    except Exception:  # noqa: BLE001
        pass


def observe(rec):
    """Feed one closed step record (called by the ledger at every step
    boundary). Never raises."""
    global _steps_seen, _round
    try:
        wall = rec["wall_s"]
        host = rec["attribution"].get("host_dispatch", 0.0)
        with _lock:
            window = list(_walls)
            _walls.append(wall)
            _hosts.append(host)
            _steps_seen += 1
            steps_seen = _steps_seen
        if len(window) >= _min_window:
            z, med = _robust_z(wall, window)
            if z >= _z_threshold and wall - med >= _min_excess_s:
                _emit({"kind": "regression", "step": rec.get("step"),
                       "rank": rec.get("rank"), "z": round(z, 2),
                       "value_s": round(wall, 6),
                       "median_s": round(med, 6)})
        if _publish_every > 0 and steps_seen % _publish_every == 0:
            _publish_round(rec)
    except Exception:  # noqa: BLE001 — the watchdog must never fail a step
        pass


def _kv_client():
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001
        return None


def _publish_round(rec):
    """One cross-rank publish/read round. SPMD guarantees every rank hits
    the same round at the same step count, so the round number is the
    rendezvous key; reads are bounded per peer and failures (a dead or
    very-slow peer) leave that rank out of this round's comparison."""
    global _round
    import jax
    if jax.process_count() <= 1:
        return
    client = _kv_client()
    if client is None:
        return
    me = jax.process_index()
    with _lock:
        my_round = _round
        _round += 1
        med_wall = _median(list(_walls)) if _walls else 0.0
        med_host = _median(list(_hosts)) if _hosts else 0.0
    try:
        from horovod_tpu.common import negotiation
        epoch = negotiation._epoch
    except Exception:  # noqa: BLE001
        epoch = 0
    base = f"hvd/prof/e{epoch}/g{_gen}/r{my_round}"
    row = {"rank": me, "wall": round(med_wall, 6),
           "host": round(med_host, 6), "step": rec.get("step")}
    try:
        client.key_value_set(f"{base}/{me}", json.dumps(row))
        try:
            from horovod_tpu.metrics import instruments as _metrics
            _metrics.record_profiler_kv(sets=1)
        except Exception:  # noqa: BLE001
            pass
        if my_round >= 2:
            try:
                client.key_value_delete(
                    f"hvd/prof/e{epoch}/g{_gen}/r{my_round - 2}/{me}")
            except Exception:  # noqa: BLE001
                pass
    except Exception:  # noqa: BLE001 — publish is best-effort
        return
    rows = {me: row}
    # ONE shared deadline across all peer reads (not per-peer): with dead
    # or wedged peers the whole round is bounded by ~2x the read timeout
    # instead of (world-1) x timeout of serial stalls in the training
    # loop — absent peers just miss this round's comparison.
    deadline = time.monotonic() + 2.0 * _read_timeout_ms / 1e3
    for p in range(jax.process_count()):
        if p == me:
            continue
        budget_ms = int((deadline - time.monotonic()) * 1e3)
        if budget_ms <= 0:
            break
        try:
            raw = client.blocking_key_value_get(
                f"{base}/{p}", min(budget_ms, _read_timeout_ms))
            rows[p] = json.loads(raw)
            try:
                from horovod_tpu.metrics import instruments as _metrics
                _metrics.record_profiler_kv(gets=1)
            except Exception:  # noqa: BLE001
                pass
        except Exception:  # noqa: BLE001 — absent peer: skip this round
            continue
    if len(rows) < 3:
        return                    # outlier math needs a real quorum
    _name_stragglers(rows, rec, me)


def _name_stragglers(rows, rec, me):
    hosts = {r: row.get("host", 0.0) for r, row in rows.items()}
    values = list(hosts.values())
    for r, x in sorted(hosts.items()):
        others = [v for rr, v in hosts.items() if rr != r]
        z, med = _robust_z(x, others or values)
        if z >= _z_threshold and x - med >= _min_excess_s:
            _emit({"kind": "straggler", "rank": r,
                   "step": rec.get("step"), "z": round(z, 2),
                   "value_s": round(x, 6), "median_s": round(med, 6),
                   "observer": me})
