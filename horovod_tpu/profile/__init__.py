"""Step profiler: per-step time attribution, MFU/roofline accounting, and
on-demand trace capture.

The Horovod-timeline idea (PAPERS.md: arxiv 1802.05799) rebuilt as
structured accounting with the efficiency discipline of the MLPerf TPU-pod
scaling study (arxiv 1909.09756): instead of a trace you read by eye, every
training step closes into ONE structured record attributing its wall time
to compute vs collective vs host-dispatch vs fusion vs control-plane, plus
roofline context (achieved TFLOP/s vs the chip peak, bytes/s vs the
ICI/DCN roof).

Modules:

- :mod:`horovod_tpu.profile.ledger`   — the per-step performance ledger
  (the hot-path instrumentation target; ``hvd.step_report()``)
- :mod:`horovod_tpu.profile.roofline` — per-chip peak tables + MFU math
- :mod:`horovod_tpu.profile.watchdog` — online straggler/regression
  detection (rolling robust z-score, low-cadence cross-rank KV publish)
- :mod:`horovod_tpu.profile.capture`  — on-demand ``jax.profiler`` trace
  windows (``GET /debug/profile``, ``HOROVOD_PROFILE_STEPS=a:b``)
- :mod:`horovod_tpu.profile.report`   — ``python -m
  horovod_tpu.profile.report`` CLI over the ``HVD_STEP_REPORT_FILE`` JSONL

Knobs (docs/observability.md): ``HOROVOD_STEP_PROFILER`` (default on),
``HVD_STEP_REPORT_FILE`` (JSONL stream), ``HOROVOD_PROFILE_STEPS``,
``HOROVOD_PROFILE_DIR``, ``HOROVOD_PROFILE_PUBLISH_STEPS``.
"""

from horovod_tpu.profile.ledger import (  # noqa: F401
    step_report, step_report_summary, set_flops_per_step, reset_window,
    configure, enabled, set_enabled,
)
from horovod_tpu.profile.roofline import (  # noqa: F401
    chip_peaks, detect_chip,
)
from horovod_tpu.profile.watchdog import findings as watchdog_findings  # noqa: F401
