"""On-demand ``jax.profiler`` trace capture, aligned with the flight
recorder's clock.

Two triggers, one implementation:

- ``GET /debug/profile?ms=500`` on the metrics scrape endpoint
  (:mod:`horovod_tpu.metrics.server`) — capture a wall-clock window right
  now, from outside the process;
- ``HOROVOD_PROFILE_STEPS=a:b`` — capture a STEP window: the trace starts
  at the step-``a`` marker and stops at the step-``b`` marker (i.e. it
  covers steps ``a+1..b``), driven by the ledger's step boundaries.

Each capture directory gets a ``clock_sync.json`` recording the wall
clock (``time.time()``, the flight recorder's event time base and the
timeline's clock_sync anchor) at trace start/stop, so the XPlane trace,
the merged flight Perfetto trace, and the Chrome timeline can be rebased
onto one axis offline.

Captures are process-local and serialized by a lock — ``jax.profiler``
has one global trace session; a second concurrent request is refused
(HTTP 409 on the endpoint) instead of corrupting the first.
"""

import json
import os
import threading
import time

DEFAULT_DIR = "profile_traces"

_lock = threading.Lock()
_active_dir = None
_window = None            # (start_step, stop_step) from HOROVOD_PROFILE_STEPS
_step_capture_dir = None  # the capture the STEP WINDOW owns (on_step must
                          # never stop a /debug/profile capture it didn't
                          # start)
_base_dir = ""
_captures = 0
_MAX_STEP_CAPTURES = 4    # runaway guard for re-entered step windows


def trace_dir():
    return _base_dir or os.environ.get("HOROVOD_PROFILE_DIR") \
        or DEFAULT_DIR


def configure_window(spec, base_dir=""):
    """Parse ``HOROVOD_PROFILE_STEPS`` (``a:b``, ints, a < b). Returns
    True when a valid window is armed."""
    global _window, _base_dir
    if base_dir:
        _base_dir = base_dir
    if not spec:
        return False
    try:
        a, b = spec.split(":", 1)
        a, b = int(a), int(b)
    except ValueError:
        return False
    if b <= a:
        return False
    _window = (a, b)
    return True


def _capture_path(tag):
    rank = os.environ.get("HOROVOD_CROSS_RANK", "0")
    return os.path.join(trace_dir(), f"{tag}_r{rank}_{int(time.time())}")


def _write_clock_sync(d, extra=None):
    try:
        payload = {"wall_s": round(time.time(), 6),
                   "perf_ns": time.perf_counter_ns()}
        if extra:
            payload.update(extra)
        with open(os.path.join(d, "clock_sync.json"), "a") as f:
            f.write(json.dumps(payload) + "\n")
    except OSError:
        pass


def start(tag="ondemand"):
    """Start a trace into a fresh capture directory; returns the path or
    None (already tracing, or jax.profiler unavailable)."""
    global _active_dir
    with _lock:
        if _active_dir is not None:
            return None
        d = _capture_path(tag)
        try:
            os.makedirs(d, exist_ok=True)
            import jax
            jax.profiler.start_trace(d)
        except Exception:  # noqa: BLE001 — capture must never fail the job
            return None
        _active_dir = d
    _write_clock_sync(d, {"event": "start", "tag": tag})
    return d


def stop():
    """Stop the active trace; returns its directory or None."""
    global _active_dir
    with _lock:
        d = _active_dir
        if d is None:
            return None
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass
        _active_dir = None
    _write_clock_sync(d, {"event": "stop"})
    return d


def active():
    return _active_dir


MAX_CAPTURE_MS = 60_000


def clamp_ms(ms):
    """The capture-window clamp — shared with the ``/debug/profile``
    handler so the response reports the window that actually ran."""
    return max(1, min(int(ms), MAX_CAPTURE_MS))


def capture_ms(ms, tag="ondemand"):
    """Blocking wall-clock capture (the ``/debug/profile`` handler): start,
    sleep ``ms`` (clamped), stop. Returns the capture directory or None
    when a capture is already running."""
    ms = clamp_ms(ms)
    d = start(tag)
    if d is None:
        return None
    time.sleep(ms / 1000.0)
    stop()
    return d


def on_step(step):
    """Ledger step-boundary hook for the HOROVOD_PROFILE_STEPS window.
    Only ever stops the capture IT started: a concurrent
    ``/debug/profile`` capture occupying the (process-global) profiler
    slot is left alone — the step window then simply never fires."""
    global _window, _captures, _step_capture_dir
    w = _window
    if w is None:
        return
    a, b = w
    if _step_capture_dir is None and _active_dir is None \
            and a <= step < b and _captures < _MAX_STEP_CAPTURES:
        d = start(f"steps{a}_{b}")
        if d is not None:
            _step_capture_dir = d
            _captures += 1
    elif _step_capture_dir is not None and step >= b:
        if _active_dir == _step_capture_dir:
            stop()
        _step_capture_dir = None
        _window = None          # one-shot: the window is consumed


def shutdown():
    """Close any capture still open (called by ``basics.shutdown``): a
    job that ends — or elastically resets — before the step window's stop
    marker must still flush its trace to disk instead of leaking an open
    profiler session; ``stop()`` is a no-op when nothing is active."""
    global _step_capture_dir
    _step_capture_dir = None
    return stop()
