"""``python -m horovod_tpu.profile.report`` — render the step-report JSONL
stream (``HVD_STEP_REPORT_FILE``) as per-step attribution tables, per-rank
aggregates, and top step-time regressions.

Stdlib only in its own logic (like flight.analyze); the records are plain
JSON, so the tables need no live backend — the stream from a crashed run
renders the same as a healthy one's.
"""

import argparse
import json
import os
import sys

from horovod_tpu.profile.ledger import CATEGORIES
from horovod_tpu.profile.ledger import median as _median

_COLS = CATEGORIES + ("compute",)


def load(paths):
    """Records from one or more JSONL files, sorted by (rank, epoch,
    step). Lines that do not parse (torn writes) are skipped."""
    recs = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "wall_s" in rec \
                        and "attribution" in rec:
                    recs.append(rec)
    recs.sort(key=lambda r: (r.get("rank", 0), r.get("epoch", 0),
                             r.get("step", 0)))
    return recs


def _ms(x):
    return f"{x * 1e3:9.3f}"


def render_steps(recs, out=sys.stdout, limit=None):
    """Per-step attribution table (ms per category + share of wall)."""
    rows = recs[-limit:] if limit else recs
    head = (f"{'rank':>4} {'ep':>2} {'step':>6} {'wall_ms':>9} "
            + " ".join(f"{c:>13}" for c in _COLS)
            + f" {'coll':>5} {'mfu':>6}")
    print(head, file=out)
    print("-" * len(head), file=out)
    for r in rows:
        att = r["attribution"]
        cells = " ".join(f"{att.get(c, 0.0) * 1e3:13.3f}" for c in _COLS)
        mfu = f"{r['mfu']:6.3f}" if "mfu" in r else "     -"
        print(f"{r.get('rank', 0):>4} {r.get('epoch', 0):>2} "
              f"{r.get('step', 0):>6} {_ms(r['wall_s'])} {cells} "
              f"{r.get('collectives', 0):>5} {mfu}", file=out)


def render_summary(recs, out=sys.stdout):
    by_rank = {}
    for r in recs:
        by_rank.setdefault(r.get("rank", 0), []).append(r)
    print(f"\nper-rank summary ({len(recs)} records)", file=out)
    head = (f"{'rank':>4} {'steps':>6} {'p50_wall_ms':>12} "
            + " ".join(f"{'med_' + c[:7]:>11}" for c in _COLS))
    print(head, file=out)
    print("-" * len(head), file=out)
    for rank in sorted(by_rank):
        rows = by_rank[rank]
        p50 = _median([r["wall_s"] for r in rows])
        meds = " ".join(
            f"{_median([r['attribution'].get(c, 0.0) for r in rows]) * 1e3:11.3f}"
            for c in _COLS)
        print(f"{rank:>4} {len(rows):>6} {p50 * 1e3:12.3f} {meds}",
              file=out)


def top_regressions(recs, k=5):
    """Steps whose wall time most exceeds their rank's median (the
    offline mirror of the online watchdog's regression detector)."""
    by_rank = {}
    for r in recs:
        by_rank.setdefault(r.get("rank", 0), []).append(r)
    scored = []
    for rank, rows in by_rank.items():
        if len(rows) < 4:
            continue
        med = _median([r["wall_s"] for r in rows])
        for r in rows:
            if r["wall_s"] > med > 0:
                scored.append((r["wall_s"] / med, r))
    scored.sort(key=lambda x: -x[0])
    return scored[:k]


def render_regressions(recs, out=sys.stdout, k=5):
    top = top_regressions(recs, k)
    if not top:
        return
    print(f"\ntop step-time regressions (vs per-rank median)", file=out)
    for ratio, r in top:
        att = r["attribution"]
        dominant = max(att, key=att.get)
        print(f"  rank {r.get('rank', 0)} step {r.get('step', 0)}: "
              f"{r['wall_s'] * 1e3:.3f} ms ({ratio:.1f}x median), "
              f"dominant category: {dominant} "
              f"({att[dominant] * 1e3:.3f} ms)", file=out)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.profile.report",
        description="Render step-profiler JSONL (HVD_STEP_REPORT_FILE) as "
                    "attribution tables and top regressions.")
    p.add_argument("files", nargs="+", help="step-report JSONL file(s)")
    p.add_argument("--last", type=int, default=None,
                   help="only the last N step rows in the table")
    p.add_argument("--json", action="store_true",
                   help="machine-readable: one summary JSON object")
    args = p.parse_args(argv)
    recs = load(args.files)
    if not recs:
        print(json.dumps({"error": "no step records found"}))
        return 1
    if args.json:
        by_rank = {}
        for r in recs:
            by_rank.setdefault(r.get("rank", 0), []).append(r)
        json.dump({
            "records": len(recs),
            "ranks": sorted(by_rank),
            "p50_wall_s": _median([r["wall_s"] for r in recs]),
            "attribution_median_s": {
                c: _median([r["attribution"].get(c, 0.0) for r in recs])
                for c in _COLS},
            "regressions": [
                {"ratio": round(ratio, 3), "step": r.get("step"),
                 "rank": r.get("rank"), "wall_s": r["wall_s"]}
                for ratio, r in top_regressions(recs)],
        }, sys.stdout, indent=1)
        print()
        return 0
    render_steps(recs, limit=args.last)
    render_summary(recs)
    render_regressions(recs)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `... | head` closing the pipe is a normal way to read a table;
        # devnull keeps interpreter shutdown from re-raising on flush.
        sys.stdout = open(os.devnull, "w")
        sys.exit(0)
