"""horovod_tpu: a TPU-native distributed training framework.

Same capabilities as Horovod (the reference at yhlim5221/horovod), re-designed
for TPU: XLA collectives over the ICI mesh instead of NCCL/MPI, jit compile
caching instead of coordinator negotiation, ``jax.distributed`` bootstrap
instead of Gloo HTTP-KV rendezvous.

Typical use mirrors ``import horovod.torch as hvd``:

    import horovod_tpu as hvd
    hvd.init()
    grads = hvd.allreduce(stacked_grads)          # eager, rank-major layout
    # ... or inside your pjit'd train step:
    from horovod_tpu.ops import in_jit
    g = in_jit.allreduce(g, axis_name='hvd')
"""

from horovod_tpu.version import __version__  # noqa: F401

# HVD_LOCK_WITNESS=1: swap threading.Lock/RLock for hvdrace's recording
# proxies BEFORE any package module allocates a lock, so every
# acquisition edge lands in the witness log
# (docs/static_analysis.md#concurrency-analysis-hvdrace).
import os as _os  # noqa: E402

if _os.environ.get("HVD_LOCK_WITNESS", "").strip() in ("1", "true", "on"):
    from horovod_tpu.analysis import race as _race

    _race.maybe_install_from_env()
del _os

from horovod_tpu.common import compat as _compat  # noqa: F401  (shims first)

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, process_index, process_count, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled, gloo_built,
    nccl_built, ddl_built, ccl_built, cuda_built, rocm_built, xla_built,
    ici_built, start_timeline, stop_timeline, topology, config,
    metrics_snapshot, metrics_text, cluster_snapshot,
)
from horovod_tpu import metrics  # noqa: F401
from horovod_tpu import trace  # noqa: F401
from horovod_tpu import flight  # noqa: F401
from horovod_tpu import profile  # noqa: F401
from horovod_tpu import telemetry  # noqa: F401
from horovod_tpu.flight.recorder import step_marker  # noqa: F401
from horovod_tpu.flight.recorder import summary as flight_summary  # noqa: F401
from horovod_tpu.profile import (  # noqa: F401
    step_report, step_report_summary, set_flops_per_step,
)
from horovod_tpu.common.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt, NotInitializedError,
)
from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet, global_process_set, add_process_set, remove_process_set,
    process_set_by_id, process_sets, number_of_process_sets,
    is_process_set_included,
)
from horovod_tpu.ops.collective_ops import (  # noqa: F401
    ReduceOp, Average, Sum, Adasum, Min, Max, Product,
    allreduce, grouped_allreduce, allgather, grouped_allgather,
    allgather_ragged, broadcast, grouped_broadcast, reducescatter,
    grouped_reducescatter, alltoall, barrier, join,
    allreduce_async, grouped_allreduce_async, allgather_async,
    broadcast_async, alltoall_async, reducescatter_async,
    poll, synchronize, Handle, broadcast_object, allgather_object,
)
from horovod_tpu import callbacks  # noqa: F401
from horovod_tpu import chaos  # noqa: F401
from horovod_tpu import analysis  # noqa: F401
from horovod_tpu.analysis.program import (  # noqa: F401
    check_elastic, check_program,
)
from horovod_tpu.runner.api import run, run_elastic  # noqa: F401
from horovod_tpu import checkpoint  # noqa: F401
from horovod_tpu import elastic  # noqa: F401
from horovod_tpu.ops import in_jit  # noqa: F401
from horovod_tpu.ops import wire  # noqa: F401
from horovod_tpu.ops.wire import (set_dispatch_strategy,  # noqa: F401
                                  set_wire_dtype, wire_dtype_for,
                                  set_alltoall_strategy,
                                  set_alltoall_cross_dtype)
from horovod_tpu.ops.compression import Compression  # noqa: F401
from horovod_tpu.ops.sync_batch_norm import SyncBatchNorm  # noqa: F401
from horovod_tpu.optim import (  # noqa: F401
    DistributedOptimizer, allreduce_gradients_transform, fused_allreduce_tree,
    distributed_value_and_grad, broadcast_parameters, broadcast_object_tree,
)
