"""Durable checkpointing: stores and train-state save/restore.

Reference: the Spark estimator layer's Store abstraction
(horovod/spark/common/store.py:38-540 — LocalStore/HDFSStore with
checkpoint_path per run, `_has_checkpoint` resume in
spark/common/estimator.py:50-95) and the per-epoch checkpoints the Keras and
Lightning remote trainers write. Core Horovod itself only has in-memory
elastic commits (SURVEY.md §5.4) — this module provides both: the durable
layer that backs :mod:`horovod_tpu.elastic` across process restarts.

TPU-native design: orbax is the checkpoint engine (async, sharding-aware —
it records each array's NamedSharding and restores onto the current mesh),
wrapped in the Store API shape users of the reference know.
"""

import os

from horovod_tpu.common import logging as hvd_logging


class Store:
    """Filesystem-backed run store (reference: store.py Store/FilesystemStore
    surface: get_checkpoint_path / get_logs_path / exists)."""

    def __init__(self, prefix_path):
        self.prefix = os.path.abspath(prefix_path)

    @staticmethod
    def create(prefix_path):
        return LocalStore(prefix_path)

    def get_run_path(self, run_id):
        return os.path.join(self.prefix, "runs", run_id)

    def get_checkpoint_path(self, run_id):
        return os.path.join(self.get_run_path(run_id), "checkpoints")

    def get_logs_path(self, run_id):
        return os.path.join(self.get_run_path(run_id), "logs")

    def exists(self, path):
        return os.path.exists(path)


class LocalStore(Store):
    """reference: store.py LocalStore."""


class CheckpointManager:
    """Versioned train-state checkpoints with keep-policy and resume.

    reference behavior being matched: per-epoch checkpoint writing + best/
    latest resume of the estimator layer (spark/common/estimator.py:50-95).
    """

    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    def save(self, step, state, metrics=None, wait=False):
        """Asynchronously persist ``state`` (any pytree of arrays) at
        ``step``; sharding metadata rides along so multi-chip states restore
        onto the current mesh. Host-local leaves (step counters, replicated
        scalars) are lifted to the global mesh first — orbax cannot
        serialize process-local arrays in a multi-host setting."""
        import orbax.checkpoint as ocp
        self._mngr.save(int(step),
                        args=ocp.args.StandardSave(_globalize(state)),
                        metrics=metrics)
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, step=None, template=None, mesh=None):
        """Restore the given (default: latest) step. ``template`` — a pytree
        of like-shaped arrays — restores into matching shardings/dtypes.

        Restored leaves are re-placed onto ``mesh`` (default: the active
        global mesh when horovod_tpu is initialized) as replicated arrays
        whenever they come back on fewer devices than the mesh spans —
        without this, a checkpoint restored on one device cannot feed a
        mesh-wide train step. Leaves the template already shards across the
        mesh keep their shardings.
        """
        import jax
        import numpy as np
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding, PartitionSpec

        step = self.latest_step() if step is None else int(step)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._dir}")
        if template is not None:
            out = self._mngr.restore(
                step, args=ocp.args.StandardRestore(template))
        else:
            out = self._mngr.restore(step)

        if mesh is None:
            from horovod_tpu.common import basics
            if basics.is_initialized():
                mesh = basics.topology().mesh
        if mesh is not None and mesh.devices.size > 1:
            replicated = NamedSharding(mesh, PartitionSpec())
            multi = jax.process_count() > 1

            def place(a):
                if isinstance(a, jax.Array) and \
                        len(a.sharding.device_set) < mesh.devices.size:
                    if multi and a.is_fully_addressable:
                        # each process restored the full (identical) value
                        # locally; re-assemble
                        return _replicate_local(np.asarray(a), replicated)
                    return jax.device_put(a, replicated)
                return a

            out = jax.tree_util.tree_map(place, out)
        return out

    def latest_step(self):
        return self._mngr.latest_step()

    def all_steps(self):
        return list(self._mngr.all_steps())

    def has_checkpoint(self):
        """reference: EstimatorParams._has_checkpoint."""
        return self.latest_step() is not None

    def close(self):
        self._mngr.wait_until_finished()
        self._mngr.close()


def _replicate_local(host, replicated_sharding):
    """Re-assemble one host-local value as a replicated global array —
    every process supplies its (identical) copy, no cross-host transfer
    (the CPU/Gloo backend has none)."""
    import jax
    return jax.make_array_from_process_local_data(replicated_sharding,
                                                  host, host.shape)


def _globalize(state):
    """Lift host-local leaves onto the global mesh for multi-host saves.

    Under a multi-process launch, orbax refuses process-local arrays
    ("Cannot serialize host local jax.Array in multi-host setting") —
    exactly what a replicated step counter or optimizer scalar is. Every
    process holds the same value for such leaves (the SPMD contract), so
    they are re-assembled as REPLICATED global arrays; leaves already
    spanning processes (sharded train state) pass through untouched.
    Lifted leaves are digest-checked across processes first: a leaf that
    legitimately DIFFERS per process (a rank-folded PRNG key, a local
    metric) must fail loudly here, not be silently stamped with the
    primary's value. No-op single-process or before init."""
    import hashlib

    import jax
    import numpy as np

    from horovod_tpu.common import basics
    if jax.process_count() <= 1 or not basics.is_initialized():
        return state
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = basics.topology().mesh
    rep = NamedSharding(mesh, PartitionSpec())

    digests = []

    def lift(path, a):
        # Only host-local jax.Arrays trigger orbax's multi-host refusal;
        # plain numpy leaves are already treated as replicated (written
        # from the primary) AND lifting them through the device would
        # silently downcast 64-bit dtypes under x64-disabled JAX.
        if isinstance(a, jax.Array) and a.is_fully_addressable:
            host = np.asarray(a)
            # list, not tuple: the digest exchange is JSON and must
            # compare equal after the round-trip
            digests.append([jax.tree_util.keystr(path),
                            hashlib.md5(host.tobytes()).hexdigest()[:16]])
            return _replicate_local(host, rep)
        return a

    out = jax.tree_util.tree_map_with_path(lift, state)
    if digests:
        from horovod_tpu.common import negotiation
        peers = negotiation.exchange("ckpt_digest", digests)
        for p, other in enumerate(peers):
            if other != digests:
                if len(other) != len(digests):
                    bad = [f"{len(digests)} leaves here vs "
                           f"{len(other)} on the peer"]
                else:
                    bad = [name for (name, d), (oname, od)
                           in zip(digests, other)
                           if d != od or name != oname]
                raise ValueError(
                    f"checkpoint save: host-local leaves differ between "
                    f"process {jax.process_index()} and process {p}: "
                    f"{bad[:5]} — per-process state (rank-folded PRNG "
                    f"keys, local metrics) cannot be saved as replicated; "
                    f"shard it over the mesh or exclude it from the "
                    f"checkpointed tree")
    return out


def save_state(path, state, wait=True):
    """One-shot save of a pytree (no versioning); host-local leaves are
    lifted to the global mesh like :meth:`CheckpointManager.save`."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _globalize(state), force=True)
    if wait:
        ckptr.wait_until_finished()
    ckptr.close()
    hvd_logging.debug("saved state to %s", path)


def restore_state(path, template=None):
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(os.path.abspath(path), template)
    finally:
        ckptr.close()
