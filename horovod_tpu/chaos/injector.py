"""Process-local fault-injection runtime.

Instrumented modules guard each named site with a single module-attribute
check — ``if _chaos.armed: _chaos.fire("site.name")`` — so the disabled
path costs one bool read (guarded by the chaos-disabled leg of
``tests/test_perf_guards.py``). When a plan is armed, ``fire`` evaluates
the plan's triggers for the site (a pure function of seed × spec × call
count × step clock × rank, see :mod:`horovod_tpu.chaos.plan`), and on a
match records the injection (``chaos_injections_total{site,kind}`` +
a per-rank JSONL ledger line) before applying the effect:

- ``delay``    sleep ``delay_ms`` (straggler / stall)
- ``drop``     raise ``URLError(ConnectionResetError)`` (KV transport)
- ``http_5xx`` raise ``HTTPError(500)`` (KV server fault)
- ``crash``    ``os._exit(exit_code)`` (hard worker death, no cleanup)
- ``hang``     sleep ``hang_s`` (wedged worker; elastic heartbeat reaps it)
- ``host_remove`` applied by :func:`filter_hosts` on the elastic driver's
  discovery poll (simulated host preemption)

The ledger is the reproducibility artifact: one JSON line per injection
(role, rank, site, kind, spec index, per-spec fire index, step, call
count, timestamp). Re-running the same plan + seed against the same
workload must produce the same schedule — :func:`ledger_schedule` strips
the non-deterministic fields (timestamp, pid, raw call count for
step-keyed specs) so the soak harness can assert equality.

Installation is idempotent per process and survives ``hvd.shutdown()`` /
re-``init()`` cycles (elastic in-place re-initialization must not reset
site counters mid-plan); a CHANGED ``HOROVOD_CHAOS_PLAN`` re-installs.
"""

import json
import os
import threading
import time

from horovod_tpu.chaos.plan import ChaosPlan
from horovod_tpu.flight import recorder as _flight

# The one-word hot-path gate. Module attribute, not a function call: sites
# read ``injector.armed`` and skip everything else when False.
armed = False

_plan = None
_installed_env = None          # the env string install_from_env consumed
_lock = threading.RLock()
_site_counts = {}
_spec_fires = {}               # spec idx -> fire count
_step_fired = set()            # (spec idx, step) pairs already fired
_remove_started = set()        # host_remove spec idxs already ledgered
_step = None                   # step clock (last committed step)
_role = "worker"
_ledger_fh = None
_ledger_path = None

DEFAULT_LEDGER_DIR = "chaos_ledgers"


def plan():
    return _plan


def set_role(role):
    """Tag this process's ledger entries (``worker`` / ``driver``)."""
    global _role
    _role = role


def set_step(step):
    """Advance the step clock used by ``at_step`` triggers. Wired to
    ``State.commit`` (the elastic step boundary); callers may also set it
    explicitly from a training loop."""
    global _step
    if step is not None:
        _step = int(step)


def install(plan_obj):
    """Arm ``plan_obj`` in this process, resetting all counters/ledger
    state (a fresh schedule)."""
    global armed, _plan, _ledger_fh, _ledger_path, _step
    with _lock:
        _close_ledger()
        _plan = plan_obj
        _site_counts.clear()
        _spec_fires.clear()
        _step_fired.clear()
        _remove_started.clear()
        _step = None
        armed = _plan is not None and len(_plan) > 0


def uninstall():
    global armed, _plan, _installed_env, _role
    with _lock:
        armed = False
        _plan = None
        _installed_env = None
        # Role reverts to the default with the plan: an in-process elastic
        # driver run (tests, run_soak) set "driver", and leaving it would
        # mislabel every later same-process workload's ledger entries
        # (the test_runner → test_chaos full-suite ordering leak).
        _role = "worker"
        _close_ledger()


def install_from_env():
    """Arm the plan named by ``HOROVOD_CHAOS_PLAN``, if any. Idempotent:
    an unchanged env string is a no-op (elastic re-init calls ``hvd.init``
    again in the same process and must not reset mid-plan counters); a
    changed one re-installs. Never touches a plan armed directly via
    :func:`install`."""
    global _installed_env
    raw = os.environ.get("HOROVOD_CHAOS_PLAN", "")
    with _lock:
        if not raw:
            if _installed_env is not None:
                # A plan previously armed FROM THE ENV whose env was since
                # cleared: the operator's next run believes it is
                # chaos-free, so disarm — a stale crash spec with budget
                # left must not fire into it. Plans armed directly via
                # install() have no _installed_env and are untouched.
                uninstall()
            return
        # The ledger dir is part of the key: a worker's in-place elastic
        # re-init sees an unchanged env (no reset — counters must
        # survive), but a long-lived DRIVER process hosting several runs
        # of the same plan+seed (the soak's same-seed re-run) gets a new
        # ledger dir per run and must start a fresh schedule — otherwise
        # its site counters run on past trigger windows and its ledger
        # file handle keeps pointing at the previous run's directory.
        key = "\x00".join((raw, os.environ.get("HOROVOD_CHAOS_SEED", ""),
                           os.environ.get("HOROVOD_CHAOS_LEDGER", "")))
        if key == _installed_env:
            return
        plan_obj = ChaosPlan.from_env()
        install(plan_obj)
        _installed_env = key


def _rank():
    try:
        return int(os.environ.get("HOROVOD_CROSS_RANK", "0") or 0)
    except ValueError:
        return 0


def _decide(site, step):
    """Evaluate the site's specs for this call; returns the matched spec
    (first match wins) after recording it, or None. Runs under _lock."""
    p = _plan
    if p is None:
        return None
    n = _site_counts.get(site, 0)
    _site_counts[site] = n + 1
    eff_step = step if step is not None else _step
    rank = _rank()
    for idx, spec in p.by_site.get(site, ()):
        fires = _spec_fires.get(idx, 0)
        if spec.matches(n, eff_step, rank, p.seed, idx, fires, _step_fired):
            _spec_fires[idx] = fires + 1
            if spec.at_step is not None:
                _step_fired.add((idx, eff_step))
            _record(site, spec, idx, fires, n, eff_step, rank)
            return spec
    return None


def fire(site, step=None, url=None):
    """Evaluate and apply this site call's injection, if any. The effect
    (sleep / raise / exit) runs OUTSIDE the lock so a long stall on one
    thread never blocks another thread's trigger evaluation."""
    if step is not None:
        set_step(step)
    with _lock:
        spec = _decide(site, step)
    if spec is not None:
        _apply(spec, url=url)


def filter_hosts(site, hosts):
    """Driver-side ``host_remove`` application: every discovery poll is one
    site call; a spec whose window ``[at, at + duration)`` covers the call
    drops its victim from the returned host dict (simulated preemption —
    the elastic driver then reassigns exactly as for a real removal). The
    ledger records one entry per window, at entry."""
    if _plan is None:
        return hosts
    with _lock:
        n = _site_counts.get(site, 0)
        _site_counts[site] = n + 1
        out = hosts
        for idx, spec in _plan.by_site.get(site, ()):
            if spec.kind != "host_remove":
                continue
            start = spec.at[0] if spec.at else 0
            if not (start <= n < start + spec.duration):
                continue
            victim = spec.host
            if victim is None:
                names = sorted(hosts)
                if not (0 <= spec.host_index < len(names)):
                    continue
                victim = names[spec.host_index]
            if victim not in out:
                continue
            if out is hosts:
                out = dict(hosts)
            out.pop(victim, None)
            if idx not in _remove_started:
                _remove_started.add(idx)
                fires = _spec_fires.get(idx, 0)
                _spec_fires[idx] = fires + 1
                _record(site, spec, idx, fires, n, _step, _rank(),
                        host=victim)
        return out


def _apply(spec, url=None):
    kind = spec.kind
    if kind == "delay":
        time.sleep(spec.delay_ms / 1000.0)
    elif kind == "drop":
        from urllib import error as urlerror
        raise urlerror.URLError(ConnectionResetError(
            "chaos: injected KV connection reset"))
    elif kind == "http_5xx":
        import io
        from urllib import error as urlerror
        raise urlerror.HTTPError(url or "chaos://injected", 500,
                                 "chaos: injected server error", None,
                                 io.BytesIO(b""))
    elif kind == "crash":
        # Hard death with no interpreter cleanup — the worker vanishes the
        # way a preempted/OOM-killed process does (reference analog:
        # elastic_common.py kills workers mid-training). Last words: the
        # victim's ring (which already holds this injection — _record ran
        # under _decide) hits disk before os._exit skips every atexit.
        _flight.dump("chaos_crash", force=True)
        os._exit(spec.exit_code)
    elif kind == "hang":
        time.sleep(spec.hang_s)
    # host_remove is applied by filter_hosts, never as a call effect.


# --- ledger ---------------------------------------------------------------

def _ledger_dir():
    return os.environ.get("HOROVOD_CHAOS_LEDGER") or DEFAULT_LEDGER_DIR


def _ledger_file():
    global _ledger_fh, _ledger_path
    if _ledger_fh is not None:
        return _ledger_fh
    d = _ledger_dir()
    os.makedirs(d, exist_ok=True)
    _ledger_path = os.path.join(
        d, f"{_role}_r{_rank()}_p{os.getpid()}.jsonl")
    _ledger_fh = open(_ledger_path, "a")
    return _ledger_fh


def _close_ledger():
    global _ledger_fh, _ledger_path
    if _ledger_fh is not None:
        try:
            _ledger_fh.close()
        except OSError:
            pass
    _ledger_fh = None
    _ledger_path = None


def ledger_path():
    return _ledger_path


def _record(site, spec, idx, fire_idx, n, step, rank, **extra):
    from horovod_tpu.metrics import instruments as _metrics
    _metrics.record_chaos(site, spec.kind)
    if _flight.armed:
        # Mirrored into the flight ring so a single per-rank dump carries
        # the injection AND its downstream anomaly in one timeline (the
        # analyzer's causation pass works from either source).
        _flight.record_event("chaos", name=site, what=spec.kind, seq=step)
    entry = {"role": _role, "rank": rank, "site": site, "kind": spec.kind,
             "spec": idx, "fire": fire_idx, "n": n, "step": step,
             "ts": round(time.time(), 3)}
    entry.update(extra)
    try:
        fh = _ledger_file()
        fh.write(json.dumps(entry) + "\n")
        fh.flush()
    except OSError:
        pass                    # the ledger must never fail the workload


def read_ledger(directory=None):
    """All injection entries under ``directory`` (every process's file),
    in stable (rank, site, spec, fire) order."""
    d = directory or _ledger_dir()
    entries = []
    if not os.path.isdir(d):
        return entries
    for name in sorted(os.listdir(d)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(d, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    entries.append(json.loads(line))
    entries.sort(key=lambda e: (e.get("rank", 0), e.get("site", ""),
                                e.get("spec", 0), e.get("fire", 0)))
    return entries


def ledger_schedule(entries):
    """The deterministic projection of a ledger: what fired, where, for
    whom, at which step/fire index — with timestamps (and, for step-keyed
    specs, the raw call count, which varies with KV polling cadence)
    stripped. Two runs of the same plan + seed over the same workload must
    produce equal schedules."""
    sched = []
    for e in entries:
        sched.append((e.get("role"), e.get("rank"), e.get("site"),
                      e.get("kind"), e.get("spec"), e.get("fire"),
                      e.get("step"), e.get("host")))
    return sched


def stats():
    """Per-spec fire counts + per-site call counts (this process)."""
    with _lock:
        return {"fires": dict(_spec_fires), "sites": dict(_site_counts),
                "armed": armed}
