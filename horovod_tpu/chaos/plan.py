"""Declarative, seeded fault plans.

A :class:`ChaosPlan` is a list of :class:`FaultSpec` entries — each one a
``kind`` (what goes wrong) × ``site`` (where in the stack) × trigger (when)
— plus a seed that makes probabilistic triggers reproducible. Plans come
from YAML/JSON text, a file path, or the ``HOROVOD_CHAOS_PLAN`` /
``HOROVOD_CHAOS_SEED`` environment (the launcher's ``hvdrun --chaos-plan``
propagates them to every worker).

Determinism contract: every trigger decision is a pure function of
``(seed, spec index, site invocation count, step clock, rank)`` — never of
wall-clock time or a sequentially-drawn RNG stream. Two processes (or two
runs) that issue the same site calls therefore fire the same faults, which
is what lets the soak harness assert ledger equality across re-runs
(reference motivation: arxiv 2510.20171 — at scale, fault handling must be
testable as an invariant, not an anecdote).

Plan format (YAML; JSON is a subset)::

    seed: 42
    faults:
      - site: http_kv.request     # where (see SITES)
        kind: drop                # what (see KINDS)
        at: [0, 1]                # trigger: site-call indices
      - site: elastic.commit
        kind: crash
        rank: 5                   # only cross-rank 5
        at_step: [3]              # trigger: committed-step values
        max_fires: 1

Triggers (AND-combined; at least one required):

- ``at``:      fire when the site's call count is in the list
- ``every``:   fire when ``count % every == 0``
- ``after``:   gate — only counts >= after are eligible
- ``p``:       fire with probability p, decided by a counter-hash of
               ``(seed, spec, count)`` (deterministic, order-free)
- ``at_step``: fire when the step clock is in the list — at most once per
               (spec, step), so a step that issues many site calls (KV
               polls, dispatches) yields exactly one injection

Scoping / budget: ``rank`` (cross-rank), ``max_fires``.

Kind parameters: ``delay_ms`` (delay), ``exit_code`` (crash), ``hang_s``
(hang), ``duration``+``host``/``host_index`` (host_remove, in discovery
polls).
"""

import dataclasses
import hashlib
import os

# What can go wrong. ``drop``/``http_5xx`` model KV transport faults and
# only make sense on the HTTP-KV site; ``host_remove`` is a driver-side
# membership fault (simulated preemption); the rest apply anywhere.
KINDS = ("drop", "delay", "http_5xx", "crash", "hang", "host_remove")

# Named injection sites wired through the stack (docs/robustness.md has the
# catalogue with the code location of each).
SITES = (
    "http_kv.request",        # runner/http_kv.py KVStoreClient, per attempt
    "negotiation.exchange",   # common/negotiation.py exchange()
    "collective.dispatch",    # ops/collective_ops.py eager dispatch
    "fusion.flush",           # ops/fusion.py bucket flush
    "elastic.commit",         # elastic/state.py State.commit (step boundary)
    "elastic.rendezvous",     # elastic/worker.py scale-up barrier
    "driver.discovery",       # runner/elastic/driver.py discovery poll
    "telemetry.tick",         # telemetry/aggregator.py aggregation round
)

_SITE_ONLY = {
    "drop": ("http_kv.request",),
    "http_5xx": ("http_kv.request",),
    "host_remove": ("driver.discovery",),
}


@dataclasses.dataclass
class FaultSpec:
    site: str
    kind: str
    # --- triggers (AND-combined; at least one required) ---
    at: tuple = None            # site-call indices (0-based)
    every: int = None           # count % every == 0
    after: int = 0              # eligibility gate on the count
    p: float = None             # counter-hash probability
    at_step: tuple = None       # step-clock values (once per spec+step)
    # --- scoping / budget ---
    rank: int = None            # cross-rank scope (None = every rank)
    max_fires: int = None       # total firing budget for this spec
    # --- kind parameters ---
    delay_ms: float = 10.0
    exit_code: int = 1
    hang_s: float = 3600.0
    duration: int = 1           # host_remove: window length in polls
    host: str = None            # host_remove: victim hostname...
    host_index: int = None      # ...or index into the sorted host list
    note: str = ""

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown chaos site {self.site!r} (sites: {SITES})")
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown chaos kind {self.kind!r} (kinds: {KINDS})")
        only = _SITE_ONLY.get(self.kind)
        if only and self.site not in only:
            raise ValueError(
                f"kind {self.kind!r} applies only at site(s) {only}, "
                f"not {self.site!r}")
        if self.at is not None:
            self.at = tuple(int(x) for x in self.at)
        if self.at_step is not None:
            self.at_step = tuple(int(x) for x in self.at_step)
        if self.p is not None and not 0.0 <= float(self.p) <= 1.0:
            raise ValueError(f"p={self.p} must be a probability")
        if self.kind == "host_remove":
            if self.at is None:
                raise ValueError("host_remove needs an `at` poll index")
            if self.host is None and self.host_index is None:
                raise ValueError(
                    "host_remove needs `host` or `host_index`")
        elif self.at is None and self.every is None and self.p is None \
                and self.at_step is None:
            raise ValueError(
                f"spec {self.kind}@{self.site} has no trigger "
                "(one of at/every/p/at_step is required)")

    def matches(self, n, step, rank, seed, spec_idx, fires, step_fired):
        """Pure trigger decision for site call ``n`` (see the module
        docstring's determinism contract). ``step_fired`` is the set of
        (spec_idx, step) pairs already fired — at_step specs fire at most
        once per step."""
        if self.rank is not None and rank != self.rank:
            return False
        if self.max_fires is not None and fires >= self.max_fires:
            return False
        if n < self.after:
            return False
        if self.at_step is not None:
            if step is None or step not in self.at_step:
                return False
            if (spec_idx, step) in step_fired:
                return False
        if self.at is not None and n not in self.at:
            return False
        if self.every is not None and n % self.every != 0:
            return False
        if self.p is not None and _unit(seed, spec_idx, n) >= self.p:
            return False
        return True


def _unit(seed, spec_idx, n):
    """Deterministic uniform [0, 1) from (seed, spec, count): a keyed hash,
    not a sequential RNG draw, so the decision for call ``n`` is the same
    regardless of thread interleaving or what other sites fired."""
    h = hashlib.blake2b(f"{seed}:{spec_idx}:{n}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big") / 2.0 ** 64


class TriggerCursor:
    """The bookkeeping half of the determinism contract, reusable outside
    the process-level injector: per-(site, rank) call counters, per-spec
    firing budgets and the (spec, step) once-per-step set, driving
    :meth:`FaultSpec.matches` — which stays the single trigger decision.
    The in-process :mod:`~horovod_tpu.chaos.injector` keeps its own
    per-process counters (one process = one rank there); the scale
    digital twin (:mod:`horovod_tpu.sim`) hosts EVERY virtual rank in one
    process, so its counters must be rank-keyed — this class is that
    seam. Purely deterministic: same plan + same call sequence → same
    verdicts, no wall clock, no RNG stream."""

    def __init__(self, plan):
        self.plan = plan
        self._counts = {}       # (site, rank) -> site call count
        self._fires = {}        # spec idx -> total fires
        self._step_fired = set()
        self.log = []           # (site, rank, step, n, kind) fired log

    def decide(self, site, rank, step=None):
        """Advance the (site, rank) call counter and return the list of
        :class:`FaultSpec` entries that fire for this call."""
        if self.plan is None:
            return []
        specs = self.plan.by_site.get(site)
        n = self._counts.get((site, rank), 0)
        self._counts[(site, rank)] = n + 1
        if not specs:
            return []
        fired = []
        for idx, spec in specs:
            if not spec.matches(n, step, rank, self.plan.seed, idx,
                                self._fires.get(idx, 0),
                                self._step_fired):
                continue
            self._fires[idx] = self._fires.get(idx, 0) + 1
            if spec.at_step is not None and step is not None:
                self._step_fired.add((idx, step))
            fired.append(spec)
            self.log.append((site, rank, step, n, spec.kind))
        return fired


class ChaosPlan:
    def __init__(self, faults, seed=0, note=""):
        self.faults = list(faults)
        self.seed = int(seed)
        self.note = note
        self.by_site = {}
        for i, spec in enumerate(self.faults):
            self.by_site.setdefault(spec.site, []).append((i, spec))

    def __len__(self):
        return len(self.faults)

    def to_dict(self):
        # Omit only fields still at their DEFAULT — a plain falsy test
        # would silently strip meaningful zeros (rank: 0 would un-scope a
        # coordinator-targeted fault to every rank; host_index: 0 would
        # make a host_remove spec unparseable).
        defaults = {f.name: f.default for f in dataclasses.fields(FaultSpec)}
        return {"seed": self.seed, "note": self.note,
                "faults": [
                    {k: (list(v) if isinstance(v, tuple) else v)
                     for k, v in dataclasses.asdict(s).items()
                     if v != defaults[k] or k in ("site", "kind")}
                    for s in self.faults]}

    @classmethod
    def from_dict(cls, d):
        if not isinstance(d, dict) or "faults" not in d:
            raise ValueError(
                "chaos plan must be a mapping with a `faults` list")
        known = {f.name for f in dataclasses.fields(FaultSpec)}
        faults = []
        for entry in d["faults"] or []:
            unknown = set(entry) - known
            if unknown:
                raise ValueError(
                    f"unknown chaos spec field(s) {sorted(unknown)} "
                    f"(known: {sorted(known)})")
            faults.append(FaultSpec(**entry))
        return cls(faults, seed=d.get("seed", 0), note=d.get("note", ""))

    @classmethod
    def from_yaml(cls, text):
        import yaml
        return cls.from_dict(yaml.safe_load(text))

    @classmethod
    def from_env(cls, env=None):
        """Plan from ``HOROVOD_CHAOS_PLAN`` (a file path or inline
        YAML/JSON) with ``HOROVOD_CHAOS_SEED`` overriding the plan's seed.
        Returns None when no plan is configured."""
        env = env if env is not None else os.environ
        raw = env.get("HOROVOD_CHAOS_PLAN", "")
        if not raw:
            return None
        if os.path.exists(raw):
            with open(raw) as f:
                raw = f.read()
        plan = cls.from_yaml(raw)
        seed = env.get("HOROVOD_CHAOS_SEED")
        if seed is not None:
            plan.seed = int(seed)
        return plan
