"""Chaos: deterministic fault injection + the elastic-recovery soak harness.

Production collective stacks treat failure as the steady state — fault
handling, not raw bandwidth, dominates engineering cost at scale (arxiv
2510.20171) — yet recovery code is the least-exercised code in most
frameworks. This subsystem makes every failure mode a reproducible,
CPU-runnable experiment:

- :mod:`horovod_tpu.chaos.plan` — declarative, seeded fault plans
  (kind × site × trigger), parsed from YAML/JSON or the
  ``HOROVOD_CHAOS_PLAN`` / ``HOROVOD_CHAOS_SEED`` env
  (``hvdrun --chaos-plan`` propagates them).
- :mod:`horovod_tpu.chaos.injector` — the process-local runtime behind the
  named injection sites wired through the KV client, negotiation, eager
  dispatch, fusion flush, elastic commit/rendezvous, and the elastic
  driver's discovery loop. Disabled-by-default: each site is one bool
  check. Every firing counts into ``chaos_injections_total{site,kind}``
  and appends to a per-rank JSONL ledger.
- :mod:`horovod_tpu.chaos.soak` — drives a multi-process elastic training
  run through a scheduled failure plan and asserts the recovery
  invariants: target step reached, loss parity with a clean run, resets
  within the kill budget, ledger equal across same-seed re-runs.

See docs/robustness.md for the plan format, the sites catalogue, and the
soak runbook.
"""

from horovod_tpu.chaos.plan import (  # noqa: F401
    KINDS, SITES, ChaosPlan, FaultSpec,
)
from horovod_tpu.chaos.injector import (  # noqa: F401
    filter_hosts, fire, install, install_from_env, ledger_path,
    ledger_schedule, plan, read_ledger, set_role, set_step, stats,
    uninstall,
)
from horovod_tpu.chaos import injector  # noqa: F401


def armed():
    """Whether a plan is armed in this process (live view — the module
    attribute ``injector.armed`` is the hot-path gate)."""
    return injector.armed
