"""Elastic-recovery soak harness.

Drives a multi-process elastic training run through a seeded fault plan
(worker kill + KV drop + collective straggler by default) and asserts the
recovery invariants the elastic stack promises:

1. the run reaches the target step despite the injected failures,
2. the final weights match a clean (chaos-free) run within tolerance —
   the training contribution is world-size invariant (an ``Average`` of
   identical per-rank terms), so a correct restore/re-rendezvous sequence
   is loss-neutral by construction,
3. elastic resets stay within the plan's kill budget (no flapping),
4. every recovering worker populated the ``elastic_recovery_seconds``
   histogram, and
5. re-running the same plan + seed produces an identical injection-ledger
   schedule (the determinism contract of :mod:`horovod_tpu.chaos.plan`).

Progress streams through the same JSONL channel as ``bench.py``
(``HVD_BENCH_PROGRESS_FILE``), so a wedged soak still leaves parseable
evidence of how far it got. CLI wrapper: ``scripts/chaos_soak.py``;
runbook: docs/robustness.md.
"""

import contextlib
import json
import os
import time

_PROGRESS_PATH = os.environ.get("HVD_BENCH_PROGRESS_FILE",
                                "bench_progress.jsonl")
_T0 = time.perf_counter()


def _progress(phase, **extra):
    """One bench-channel JSONL record (same shape as bench.py's)."""
    if not _PROGRESS_PATH:
        return
    try:
        rec = {"ts": round(time.time(), 3),
               "elapsed_s": round(time.perf_counter() - _T0, 3),
               "model": "chaos_soak", "phase": phase}
        rec.update(extra)
        with open(_PROGRESS_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass                      # evidence must never fail the soak


def wait_cluster_view(timeout=12.0):
    """Post-loop telemetry convergence: poll the job view until it shows
    the FINAL membership fully healthy (or the timeout lapses — the last
    view is still returned as evidence). Immediate local view when no
    aggregation plane is armed, so non-telemetry soaks pay nothing."""
    import time

    import jax

    from horovod_tpu.telemetry import aggregator
    view = aggregator.cluster_snapshot()
    if aggregator.get_agent() is None:
        return view
    world = jax.process_count()
    deadline = time.time() + timeout
    while time.time() < deadline:
        view = aggregator.cluster_snapshot()
        if not view.get("local_only") \
                and view.get("world") == world \
                and view.get("counts", {}).get("healthy") == world:
            break
        time.sleep(0.25)
    return view


def soak_train(total_steps):
    """The per-worker training loop (importable by name — spawned workers
    resolve it from the installed package). World-size-invariant updates:
    each step adds ``Average(step + 1)`` of identical per-rank
    contributions, so the final weights are independent of membership
    changes — any deviation from the clean run is a recovery bug, not a
    modeling artifact."""
    import os

    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    hvd.init()
    state = elastic.TpuState(trees={"w": jnp.zeros((4,))},
                             step=0, worlds=[])
    elastic.attach_listener(state)

    @elastic.run
    def loop(state):
        while state.step < total_steps:
            contrib = jnp.ones((1, 4)) * float(state.step + 1)
            g = hvd.allreduce(contrib, op=hvd.Average)
            state.w = state.w + g[0]
            state.step += 1
            state.worlds.append(hvd.process_count())
            state.commit()
        snap = hvd.metrics_snapshot()

        def _count(name, labels=None):
            total = 0
            for s in snap.get(name, {}).get("series", ()):
                if labels is None or all(
                        s["labels"].get(k) == v for k, v in labels.items()):
                    total += s.get("count", s.get("value", 0))
            return total

        # Per-rank goodput decomposition: the wall-clock evidence the
        # driver's conservation / bracket assertions read.
        from horovod_tpu.goodput import ledger as goodput_ledger
        return {
            "steps": state.step,
            "w": np.asarray(state.w).tolist(),
            "worlds": list(state.worlds),
            "final_world": hvd.process_count(),
            "cross_rank": hvd.cross_rank(),
            "pid": os.getpid(),
            "resets": _count("elastic_events_total", {"event": "reset"}),
            "recoveries": _count("elastic_recovery_seconds"),
            "kv_retries": _count("kv_client_retries_total"),
            "injections": _count("chaos_injections_total"),
            # Telemetry-plane evidence: the job view after the final
            # membership converged (local-only when the plane is off).
            "cluster": wait_cluster_view(),
            "goodput": goodput_ledger.snapshot(),
        }

    return loop(state)


def default_plan(procs=8, seed=123, kill_rank=None, kill_step=3,
                 straggler_rank=2, drop_step=None):
    """The acceptance plan: one hard worker kill at a step boundary, one
    KV-RPC drop per rank at a later step (absorbed by the client's retry),
    and a collective-dispatch straggler on one rank. All triggers are
    step-keyed, so the ledger schedule is re-run deterministic."""
    if kill_rank is None:
        # A mid-fleet rank for real fleets; never rank 0 on tiny worlds
        # (killing the coordination-service host is legal but makes the
        # small validation runs needlessly noisy).
        kill_rank = procs - 3 if procs > 3 else procs - 1
    drop_step = kill_step + 3 if drop_step is None else drop_step
    return {
        "seed": seed,
        "note": f"soak: kill r{kill_rank}@s{kill_step}, kv drop @s"
                f"{drop_step}, straggler r{straggler_rank}",
        "faults": [
            {"site": "elastic.commit", "kind": "crash", "rank": kill_rank,
             "at_step": [kill_step], "max_fires": 1},
            {"site": "http_kv.request", "kind": "drop",
             "at_step": [drop_step]},
            {"site": "collective.dispatch", "kind": "delay",
             "delay_ms": 30, "rank": straggler_rank,
             "at_step": [1, drop_step]},
        ],
    }


def plan_kill_budget(plan_dict):
    """Total process-fatal firings the plan allows (crash + host_remove
    budgets; an unbounded fatal spec counts as its trigger-list length)."""
    budget = 0
    for f in plan_dict.get("faults", ()):
        if f.get("kind") in ("crash", "hang", "host_remove"):
            budget += f.get("max_fires") or \
                len(f.get("at_step") or f.get("at") or (1,))
    return budget


def _assert_flight_forensics(flight_dir, ledger_dir, kills, procs):
    """Merge the chaos leg's flight dumps and assert the analyzer localizes
    the injected kill: the victim's rank, the first unmatched collective
    sequence number, and the causing injection site. Returns a compact
    report for the evidence dict."""
    from horovod_tpu.flight import analyze as flight_analyze

    kill_ranks = sorted({k["rank"] for k in kills})
    events, metas, marks = flight_analyze.load_dir(flight_dir,
                                                   ledger_dir=ledger_dir)
    assert events, f"chaos leg left no flight dumps under {flight_dir}"
    report = flight_analyze.analyze(events, metas, marks)
    # Each victim's last act was dumping its ring (chaos crash hook).
    for r in kill_ranks:
        assert r in report["crash_dump_ranks"], report["dumps"]
    assert report["killed_ranks"] == kill_ranks, report["killed_ranks"]
    # Every rank left a dump: each victim's chaos_crash plus each
    # survivor's internal-error / membership-abort / atexit dump.
    # Ledger-synthesized events (from_ledger) are NOT dump evidence — a
    # rank whose dump write failed still has ledger entries, and counting
    # those would hide exactly the missing-dump regression this catches.
    worker_ranks = {e["rank"] for e in events
                    if e.get("role") != "driver"
                    and not e.get("from_ledger")}
    missing = set(range(procs)) - worker_ranks
    assert not missing, \
        f"ranks {sorted(missing)} left no flight dump: {report['dumps']}"
    causes = []
    for k in kills:
        # Cross-rank desync: each victim lags, and the first unmatched
        # seq names the collective it never dispatched.
        lagging = [d for d in report["desync"].values()
                   if d["desynced"] and k["rank"] in d["lagging_ranks"]]
        assert lagging, \
            f"killed rank {k['rank']} not localized: {report['desync']}"
        assert isinstance(lagging[0]["first_unmatched_seq"], int)
        # Causation: each crash injection is correlated with the first
        # downstream anomaly some rank recorded.
        cause = next((c for c in report["chaos"]
                      if c["what"] == "crash" and c["rank"] == k["rank"]
                      and c["site"] == k["site"]), None)
        assert cause is not None, (k, report["chaos"])
        assert cause.get("first_anomaly"), \
            f"crash injection has no downstream anomaly: {cause}"
        causes.append(cause)
    # The driver tied the dumps to the membership changes that removed the
    # victims' hosts.
    assert report["driver_disruptions"], "driver wrote no disruption marker"
    return {
        "dumps": report["dumps"],
        "killed_ranks": report["killed_ranks"],
        "desync": report["desync"],
        "cause": causes[0],
        "causes": causes,
        "driver_disruptions": report["driver_disruptions"],
    }


@contextlib.contextmanager
def _scoped_env(overrides):
    saved = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _write_discovery(path, procs):
    """N distinct loopback 'hosts' (127.0.0.0/8 is local to WorkerProcess),
    one slot each."""
    hosts = ["localhost"] + [f"127.0.0.{i}" for i in range(2, procs + 1)]
    with open(path, "w") as f:
        f.write("#!/bin/sh\n")
        for h in hosts:
            f.write(f"echo {h}:1\n")
    os.chmod(path, 0o755)
    return hosts


def _elastic_run(steps, procs, min_np, workdir, chaos_env):
    from horovod_tpu.runner import run_elastic

    script = os.path.join(workdir, "discover.sh")
    _write_discovery(script, procs)
    env = {
        # The killed host must STAY out (determinism: exactly one shrink,
        # no timing-dependent re-add mid-run).
        "HOROVOD_BLACKLIST_COOLDOWN_RANGE": "600,600",
        # Fast failure detection keeps the soak's wall clock bounded.
        "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT": "5",
    }
    env.update(chaos_env)
    with _scoped_env(env):
        return run_elastic(soak_train, args=(steps,), min_np=min_np,
                           host_discovery_script=script)


def leader_kill_plan(procs, slices, seed, kill_step=3):
    """One hard kill of a TELEMETRY SLICE LEADER at a step boundary — the
    aggregation plane's own failure drill: its slice must re-elect, and
    the job view must record the lost host."""
    from horovod_tpu.telemetry.aggregator import slice_members
    victim = slice_members(1, procs, slices)[0] if slices > 1 \
        else procs - 1
    return victim, {
        "seed": seed,
        "note": f"telemetry soak: kill slice-1 leader r{victim}"
                f"@s{kill_step} ({slices} slices over {procs} procs)",
        "faults": [
            {"site": "elastic.commit", "kind": "crash", "rank": victim,
             "at_step": [kill_step], "max_fires": 1},
        ],
    }


def run_leader_kill_soak(procs=8, slices=2, steps=8, seed=321,
                         workdir=None, kill_step=3):
    """Kill a telemetry slice leader mid-elastic-run and assert the
    aggregation plane's recovery invariants on top of the elastic ones:

    1. the run still reaches the target step at the shrunk world,
    2. the post-recovery job view is FRESH, covers the new membership,
       and reports every surviving rank healthy,
    3. re-election converged: every slice (including the victim's) has a
       live leader among the survivors and a full digest count,
    4. the job view's event log names the killed host as dead
       (``membership_removed`` — the generation diff), and
    5. no surviving worker's aggregator crashed (they all produced the
       converged view — the "never a crashed aggregator" contract).
    """
    import tempfile
    workdir = workdir or tempfile.mkdtemp(prefix="hvd_leader_kill_")
    os.makedirs(workdir, exist_ok=True)
    victim, plan_dict = leader_kill_plan(procs, slices, seed,
                                         kill_step=kill_step)
    plan_path = os.path.join(workdir, "plan.yaml")
    with open(plan_path, "w") as f:
        json.dump(plan_dict, f)
    # Host naming mirrors _write_discovery: rank r lives on
    # localhost/127.0.0.<r+1>.
    victim_host = "localhost" if victim == 0 else f"127.0.0.{victim + 1}"
    _progress("leader-kill soak start", procs=procs, slices=slices,
              victim=victim)
    try:
        results = _elastic_run(steps, procs, procs - 1, workdir, {
            "HOROVOD_CHAOS_PLAN": plan_path,
            "HOROVOD_CHAOS_SEED": str(seed),
            "HOROVOD_CHAOS_LEDGER": os.path.join(workdir, "ledger"),
            "HOROVOD_FLIGHT_DIR": os.path.join(workdir, "flight"),
            "HOROVOD_MESH_SLICES": str(slices),
            # The HIERARCHICAL control plane rides the same leader-kill
            # transition: the victim is a slice's lowest rank — its
            # negotiation leadership and fusion-boundary re-publish role
            # die with it, and the post-shrink world (procs-1, usually
            # undivisible) must degrade to the flat strategy on every
            # survivor identically. A short boundary lease keeps the
            # takeover window inside the kill-to-rendezvous gap.
            "HOROVOD_CONTROL_PLANE": "hier",
            "HOROVOD_CONTROL_LEASE_MS": "500",
            # Tight beacon cadence: the old generation's job view must
            # exist before the kill, and the new generation must converge
            # within the post-loop wait.
            "HOROVOD_TELEMETRY_INTERVAL": "0.1",
        })
    finally:
        # The driver armed the plan in THIS process from the scoped env.
        from horovod_tpu import chaos
        chaos.uninstall()
    survivors = procs - 1
    # (1) elastic recovery held.
    assert all(r["steps"] == steps for r in results), \
        f"leader-kill run fell short of {steps} steps: {results}"
    assert all(r["final_world"] == survivors for r in results), results
    views = [r["cluster"] for r in results]
    # (5) every survivor's plane produced a real (non-fallback) view.
    assert all(v and not v.get("local_only") for v in views), views
    view = views[0]
    # (2) fresh, full coverage, all healthy.
    assert view["world"] == survivors, view
    assert view["counts"]["healthy"] == survivors, view["health"]
    assert view["num_slices"] == slices, view
    # (3) re-election: every slice has a leader among the survivors and
    # saw every member's digest.
    for sid, meta in view["slices"].items():
        assert meta["leader"] is not None, (sid, meta)
        assert meta["digests"] == len(meta["members"]), (sid, meta)
    # (4) the lost host is named dead in the event log.
    removed = [e for e in view.get("events", ())
               if e.get("why") == "membership_removed"]
    assert removed, f"no membership_removed event: {view.get('events')}"
    assert any(e.get("host") == victim_host for e in removed), \
        (victim_host, removed)
    _progress("leader-kill soak done", ok=True)
    return {"procs": procs, "slices": slices, "victim": victim,
            "victim_host": victim_host, "view": view,
            "results": results, "workdir": workdir}


def autopilot_straggler_plan(procs, seed, delay_ms=120):
    """A PERMANENT straggler: every collective dispatch on the LAST rank
    is delayed. The last rank so that after the autopilot removes its
    host and the survivors renumber 0..procs-2, no process inherits the
    victim's rank and the fault dies with the host."""
    victim = procs - 1
    return victim, {
        "seed": seed,
        "note": f"autopilot soak: permanent {delay_ms}ms straggler "
                f"r{victim}",
        "faults": [
            {"site": "collective.dispatch", "kind": "delay",
             "delay_ms": delay_ms, "rank": victim, "every": 1},
        ],
    }


def run_autopilot_soak(procs=8, steps=56, seed=777, workdir=None,
                       delay_ms=120):
    """ROADMAP item 4's acceptance soak: an elastic run with a seeded
    permanent straggler is recovered by the AUTOPILOT — the step-profiler
    watchdog names the delayed rank online, the controller's remediation
    policy passes hysteresis/rate/floor and publishes the removal, the
    driver arm blacklists the host through the cooldown path, and the
    job re-rendezvouses and reaches the target step — with zero human or
    harness intervention (this harness only starts the run). Asserted:

    1. every survivor reaches the target step at world ``procs - 1``;
    2. the removal really was controller-initiated: the flight analyzer
       (`report["autopilot"]`) names the removed rank and the causing
       decision (cause ``straggler``), correlated with the driver's
       disruption marker;
    3. the straggler is actually GONE: the post-shrink worlds in every
       survivor's step log are ``procs - 1``.
    """
    import tempfile
    workdir = workdir or tempfile.mkdtemp(prefix="hvd_autopilot_soak_")
    os.makedirs(workdir, exist_ok=True)
    victim, plan_dict = autopilot_straggler_plan(procs, seed,
                                                 delay_ms=delay_ms)
    plan_path = os.path.join(workdir, "plan.yaml")
    with open(plan_path, "w") as f:
        json.dump(plan_dict, f)
    flight_dir = os.path.join(workdir, "flight")
    ledger_dir = os.path.join(workdir, "ledger")
    victim_host = "localhost" if victim == 0 else f"127.0.0.{victim + 1}"
    _progress("autopilot soak start", procs=procs, steps=steps,
              victim=victim)
    try:
        results = _elastic_run(steps, procs, procs - 1, workdir, {
            "HOROVOD_CHAOS_PLAN": plan_path,
            "HOROVOD_CHAOS_SEED": str(seed),
            "HOROVOD_CHAOS_LEDGER": ledger_dir,
            "HOROVOD_FLIGHT_DIR": flight_dir,
            # The autopilot, tuned for a short soak: 1 s decision epochs,
            # 2-epoch hysteresis, one removal, floor at the survivor
            # count (the job must never shrink past one straggler).
            "HOROVOD_AUTOPILOT": "1",
            "HOROVOD_AUTOPILOT_INTERVAL": "1.0",
            "HOROVOD_AUTOPILOT_HYSTERESIS": "2",
            "HOROVOD_AUTOPILOT_MAX_REMOVALS": "1",
            "HOROVOD_AUTOPILOT_MIN_WORLD": str(procs - 1),
            # Fast naming + fresh host mapping: watchdog publish round
            # every 2 steps, telemetry beacons at 0.5 s.
            "HOROVOD_PROFILE_PUBLISH_STEPS": "2",
            "HOROVOD_TELEMETRY_INTERVAL": "0.5",
        })
    finally:
        from horovod_tpu import chaos
        chaos.uninstall()
    survivors = procs - 1
    # (1) recovered with no human/harness help.
    assert all(r["steps"] == steps for r in results), \
        f"autopilot soak fell short of {steps} steps: {results}"
    assert all(r["final_world"] == survivors for r in results), results
    # (3) the straggler's host really left: the tail of every step log
    # ran at the shrunk world.
    assert all(r["worlds"][-1] == survivors for r in results), \
        [r["worlds"][-5:] for r in results]
    # (2) the forensics name the removal and its cause.
    from horovod_tpu.flight import analyze as flight_analyze
    events, metas, marks = flight_analyze.load_dir(flight_dir,
                                                   ledger_dir=ledger_dir)
    assert events, f"soak left no flight dumps under {flight_dir}"
    report = flight_analyze.analyze(events, metas, marks)
    ap = report["autopilot"]
    rem = [r for r in ap["remediations"] if r.get("cause") == "straggler"]
    assert rem, f"no straggler remediation in the flight trail: {ap}"
    assert any(r.get("rank") == victim for r in rem), (victim, rem)
    assert any(r.get("host") == victim_host for r in rem), \
        (victim_host, rem)
    # ...and the driver executed it: a disruption marker removed the host.
    assert report["driver_disruptions"], "driver left no disruption marker"
    assert any(victim_host in (m.get("removed") or ())
               for m in report["driver_disruptions"]), \
        (victim_host, report["driver_disruptions"])
    _progress("autopilot soak done", ok=True,
              remediation=rem[0])
    return {"procs": procs, "steps": steps, "victim": victim,
            "victim_host": victim_host, "remediations": rem,
            "report_autopilot": ap, "results": results,
            "workdir": workdir}


def goodput_badput_plan(procs, seed, steps, kill_step=3,
                        straggler_rank=2, delay_ms=120,
                        straggler_from=12):
    """Seeded badput schedule for the goodput acceptance soak: one hard
    kill early (rendezvous_recovery badput on every survivor) plus a
    WINDOWED collective-dispatch straggler on a rank that survives the
    kill. The straggler window starts only after the survivors have
    rebuilt a clean >= 8-step comm baseline post-reset — a delay injected
    from step 0 is absorbed into the victim's own rolling median and
    books no excess — and runs to the end so the watchdog's published
    median actually goes outlier-high."""
    kill_rank = procs - 3 if procs > 3 else procs - 1
    assert straggler_rank != kill_rank
    return kill_rank, {
        "seed": seed,
        "note": f"goodput soak: kill r{kill_rank}@s{kill_step}, "
                f"{delay_ms}ms straggler r{straggler_rank}"
                f"@s{straggler_from}..{steps - 1}",
        "faults": [
            {"site": "elastic.commit", "kind": "crash", "rank": kill_rank,
             "at_step": [kill_step], "max_fires": 1},
            {"site": "collective.dispatch", "kind": "delay",
             "delay_ms": delay_ms, "rank": straggler_rank,
             "at_step": list(range(straggler_from, steps))},
        ],
    }


def run_goodput_soak(procs=8, steps=32, seed=555, workdir=None,
                     delay_ms=120, straggler_rank=2):
    """The goodput ledger's acceptance soak: an elastic run with a seeded
    kill and a windowed straggler must come back with a decomposition
    that (a) CONSERVES wall time on every rank, (b) BRACKETS the injected
    badput — ``rendezvous_recovery`` on every reset rank,
    ``straggler_wait`` on the victim against the chaos ledger's exact
    fire count — and (c) leaves a durable journal from which the report
    CLI names the victim rank. Asserted:

    1. every survivor reaches the target step at world ``procs - 1``;
    2. ``conservation_error <= 1%`` on EVERY rank's decomposition;
    3. every rank that reset booked ``rendezvous_recovery`` in
       ``(0, wall)``;
    4. the victim's ``straggler_wait`` brackets the injected delay total
       (loose CPU-box bounds; the exact total comes from the injection
       ledger, not the plan);
    5. the step watchdog's cross-rank naming reached the goodput ledger:
       some survivor's snapshot carries ``straggler_named == victim``;
    6. the run journal is durable and complete (``run_end`` present) and
       ``python -m horovod_tpu.goodput.report`` renders it, naming
       ``victim: rank <straggler_rank>``.
    """
    import io
    import tempfile
    workdir = workdir or tempfile.mkdtemp(prefix="hvd_goodput_soak_")
    os.makedirs(workdir, exist_ok=True)
    kill_rank, plan_dict = goodput_badput_plan(
        procs, seed, steps, straggler_rank=straggler_rank,
        delay_ms=delay_ms)
    plan_path = os.path.join(workdir, "plan.yaml")
    with open(plan_path, "w") as f:
        json.dump(plan_dict, f)
    ledger_dir = os.path.join(workdir, "ledger")
    history_dir = os.path.join(workdir, "run_history")
    goodput_dir = os.path.join(workdir, "goodput")
    _progress("goodput soak start", procs=procs, steps=steps,
              kill_rank=kill_rank, straggler_rank=straggler_rank)
    try:
        results = _elastic_run(steps, procs, procs - 1, workdir, {
            "HOROVOD_CHAOS_PLAN": plan_path,
            "HOROVOD_CHAOS_SEED": str(seed),
            "HOROVOD_CHAOS_LEDGER": ledger_dir,
            "HOROVOD_FLIGHT_DIR": os.path.join(workdir, "flight"),
            "HOROVOD_GOODPUT": "1",
            "HOROVOD_GOODPUT_DIR": goodput_dir,
            "HOROVOD_RUN_HISTORY_DIR": history_dir,
            "HOROVOD_GOODPUT_JOURNAL_S": "2",
            # Watchdog publish rounds every 2 steps (cross-rank straggler
            # naming) + live telemetry beacons (per-rank goodput rows in
            # the journaled cluster view).
            "HOROVOD_PROFILE_PUBLISH_STEPS": "2",
            "HOROVOD_TELEMETRY_INTERVAL": "0.5",
        })
    finally:
        from horovod_tpu import chaos
        chaos.uninstall()
    survivors = procs - 1
    # (1) elastic recovery held.
    assert all(r["steps"] == steps for r in results), \
        f"goodput soak fell short of {steps} steps: {results}"
    assert all(r["final_world"] == survivors for r in results), results
    by_rank = {r["cross_rank"]: r for r in results}
    # The injected straggler total from the injection ledger — the exact
    # count of delay fires, not the plan's intent (a fire suppressed by
    # the recovery window would silently shrink the bracket's target).
    from horovod_tpu.chaos import injector
    entries = injector.read_ledger(ledger_dir)
    delays = [e for e in entries if e["kind"] == "delay"]
    assert delays, f"straggler never fired: {entries}"
    injected_s = len(delays) * delay_ms / 1e3
    # (2) conservation on every rank.
    for r in results:
        gp = r["goodput"]
        assert gp.get("enabled"), f"goodput off on r{r['cross_rank']}"
        assert gp["conservation_error"] <= 0.01, \
            f"conservation violated on r{r['cross_rank']}: {gp}"
    # (3) recovery badput on every reset rank.
    for r in results:
        if r["resets"]:
            rr = r["goodput"]["categories"]["rendezvous_recovery"]
            assert 0.0 < rr < r["goodput"]["wall_s"], \
                (r["cross_rank"], r["goodput"])
    # (4) the victim's straggler_wait brackets the injected total. Lower
    # bound: at least 3 full-delay steps booked before the victim's own
    # rolling median adapts to the elevated window. Upper: generous
    # CPU-contention slack — the wait must still be the same order as
    # the injection, not the whole run.
    wait = by_rank[straggler_rank]["goodput"]["categories"][
        "straggler_wait"]
    lower = 3 * delay_ms / 1e3
    upper = 3.0 * injected_s + 2.0
    assert lower <= wait <= upper, \
        f"victim straggler_wait {wait:.3f}s outside " \
        f"[{lower:.3f}, {upper:.3f}] for {injected_s:.3f}s injected"
    # (5) the comparative (watchdog) naming reached the ledger.
    named = {r["cross_rank"]: r["goodput"].get("straggler_named")
             for r in results}
    assert any(v == straggler_rank for v in named.values()), \
        f"no survivor's watchdog named r{straggler_rank}: {named}"
    # (6) durable journal + report CLI naming.
    from horovod_tpu.goodput import report as goodput_report
    from horovod_tpu.goodput.history import read_runs
    runs = read_runs(history_dir)
    assert runs, f"no run journal under {history_dir}"
    rid = sorted(runs, key=lambda r: runs[r].get("t0") or 0)[-1]
    summary = runs[rid]
    assert summary.get("ended"), \
        f"journal {rid} has no run_end marker: {summary['records']} recs"
    victim = goodput_report.find_victim(summary)
    assert victim is not None and int(victim[0]) == straggler_rank, \
        f"report blamed {victim}, expected rank {straggler_rank}"
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = goodput_report.main(["--dir", history_dir])
    rendered = buf.getvalue()
    assert rc == 0 and f"victim: rank {straggler_rank}" in rendered, \
        rendered
    _progress("goodput soak done", ok=True, injected_s=injected_s,
              straggler_wait=round(wait, 3), named=named)
    return {"procs": procs, "steps": steps, "kill_rank": kill_rank,
            "straggler_rank": straggler_rank, "injected_s": injected_s,
            "straggler_wait_s": wait, "named": named, "run_id": rid,
            "report": rendered, "results": results, "workdir": workdir}


def run_soak(procs=8, steps=8, seed=123, workdir=None, plan_dict=None,
             loss_tol=1e-5, reruns=1):
    """Run clean + chaos (+ ``reruns`` same-seed repeats), assert the
    invariants, and return the evidence dict. Raises AssertionError with
    the failing invariant."""
    import tempfile
    workdir = workdir or tempfile.mkdtemp(prefix="hvd_chaos_soak_")
    os.makedirs(workdir, exist_ok=True)
    plan_dict = plan_dict or default_plan(procs=procs, seed=seed)
    plan_dict["seed"] = seed
    plan_path = os.path.join(workdir, "plan.yaml")
    with open(plan_path, "w") as f:
        json.dump(plan_dict, f)    # JSON is valid YAML
    budget = plan_kill_budget(plan_dict)
    evidence = {"procs": procs, "steps": steps, "seed": seed,
                "plan": plan_dict, "kill_budget": budget,
                "workdir": workdir}

    min_np = max(procs - budget, 1)

    try:
        return _run_soak_inner(procs, steps, seed, workdir, plan_dict,
                               plan_path, budget, min_np, loss_tol,
                               reruns, evidence)
    finally:
        # The elastic DRIVER runs in this process and armed the plan from
        # the scoped env — the caller (a pytest process, a notebook) must
        # not inherit a live injector.
        from horovod_tpu import chaos
        chaos.uninstall()


def _run_soak_inner(procs, steps, seed, workdir, plan_dict, plan_path,
                    budget, min_np, loss_tol, reruns, evidence):
    _progress("soak clean run start", procs=procs, steps=steps)
    clean = _elastic_run(steps, procs, min_np, workdir, {})
    _progress("soak clean run done", hosts=len(clean))
    assert all(r["steps"] == steps for r in clean), \
        f"clean run fell short of {steps} steps: {clean}"
    clean_w = clean[0]["w"]
    evidence["clean_w"] = clean_w

    schedules = []
    for attempt in range(1 + reruns):
        ledger_dir = os.path.join(workdir, f"ledger_{attempt}")
        flight_dir = os.path.join(workdir, f"flight_{attempt}")
        _progress("soak chaos run start", attempt=attempt)
        results = _elastic_run(steps, procs, min_np, workdir, {
            "HOROVOD_CHAOS_PLAN": plan_path,
            "HOROVOD_CHAOS_SEED": str(seed),
            "HOROVOD_CHAOS_LEDGER": ledger_dir,
            # Archive the chaos leg's flight dumps under the workdir: the
            # victim's chaos_crash dump, every survivor's internal-error /
            # membership-abort dump, and the driver's disruption marker
            # land in one analyzable directory.
            "HOROVOD_FLIGHT_DIR": flight_dir,
        })
        from horovod_tpu.chaos import injector
        entries = injector.read_ledger(ledger_dir)
        schedules.append(injector.ledger_schedule(entries))
        _progress("soak chaos run done", attempt=attempt,
                  hosts=len(results), injections=len(entries))
        if attempt == 0:
            evidence["chaos_results"] = results
            evidence["ledger"] = entries
            # (1) the run survived to the target step
            assert all(r["steps"] == steps for r in results), \
                f"chaos run fell short of {steps} steps: {results}"
            # (2) loss/weight parity with the clean run
            import numpy as np
            np.testing.assert_allclose(
                [r["w"] for r in results],
                [clean_w] * len(results), atol=loss_tol,
                err_msg="recovery was not loss-neutral vs the clean run")
            # (3) resets within the kill budget, and the membership
            # actually shrank by the killed workers
            for r in results:
                assert r["resets"] <= budget, \
                    f"worker r{r['cross_rank']} reset {r['resets']}x " \
                    f"(> kill budget {budget}): flapping recovery"
            assert all(r["final_world"] == procs - budget
                       for r in results), results
            # (4) recovering workers populated the recovery histogram
            recovered = [r for r in results if r["resets"]]
            assert recovered and all(r["recoveries"] >= 1
                                     for r in recovered), \
                f"elastic_recovery_seconds not populated: {results}"
            # (4b) goodput conservation on every rank, clean AND chaos
            # legs — the decomposition must account the full wall within
            # 1% no matter how the run was disrupted — and every
            # recovering worker booked rendezvous_recovery badput.
            for r in clean + results:
                gp = r.get("goodput") or {}
                if not gp.get("enabled"):
                    continue
                assert gp["conservation_error"] <= 0.01, \
                    f"goodput conservation violated on " \
                    f"r{r['cross_rank']}: {gp}"
            for r in recovered:
                gp = r.get("goodput") or {}
                if not gp.get("enabled"):
                    continue
                assert gp["categories"]["rendezvous_recovery"] > 0.0, \
                    f"r{r['cross_rank']} reset {r['resets']}x but booked " \
                    f"no rendezvous_recovery: {gp['categories']}"
            # the injected kill actually fired (exactly once)
            kills = [e for e in entries if e["kind"] == "crash"]
            assert len(kills) == budget, entries
            # (6) the flight forensics localize the kill: merge the per-
            # rank dumps the failure left behind and check the analyzer
            # names the killed rank, the first unmatched collective seq,
            # and the injection that caused it — "it recovered" AND "the
            # forensics say why".
            evidence["flight_report"] = _assert_flight_forensics(
                flight_dir, ledger_dir, kills, procs)
    # (5) same seed ⇒ identical ledger schedule
    for i, sched in enumerate(schedules[1:], 1):
        assert sched == schedules[0], (
            f"ledger schedule diverged between same-seed runs 0 and {i}:\n"
            f"{schedules[0]}\nvs\n{sched}")
    evidence["ledger_deterministic"] = len(schedules) > 1
    _progress("soak done", ok=True)
    return evidence
