"""Finding model shared by the program analyzer and the codebase linter.

Every program-analysis finding carries the same ``(op, ps, seq, sig)``
identity the flight recorder stamps on each dispatch event
(:mod:`horovod_tpu.flight.recorder`), so a runtime desync from
``flight.analyze`` can be matched against the static prediction (and vice
versa) without any joining heuristics.

Program-analysis codes (``HVP1xx``):

- ``HVP101`` rank_gated_collective — a collective dispatched by a strict
  subset of ranks (count mismatch per process set); the deadlock class.
- ``HVP102`` order_mismatch — same collectives, different cross-rank order.
- ``HVP103`` signature_mismatch — same op position, differing shape/dtype.
- ``HVP104`` degenerate_collective — collective over a 1-member process
  set / mesh axis (all cost, no exchange).
- ``HVP105`` fusion_fill — advisory: estimated bytes-on-wire vs the fusion
  threshold fill ratio (tiny sync collectives that would fuse, or tensors
  that overflow every bucket).
- ``HVP106`` wire_dtype — advisory: fp32 on the wire inside jit while a
  compressed wire dtype is configured. Suppressed when the jaxpr shows
  the block-scaled quantized exchange (int8/float8 collectives from
  ops/wire.py) — that program is already quantizing in jit.
- ``HVP107`` buffer_reuse — advisory: one input buffer dispatched to more
  than one collective (a hazard when eager donation is armed, a missed
  donation opportunity otherwise).
- ``HVP108`` cond_collective — advisory: collective under a ``lax.cond``
  branch (subset participation deadlocks the rendezvous if the predicate
  varies across the mesh).
- ``HVP109`` stale_residual — advisory: wire error feedback is configured
  but the program runs in-jit quantized exchanges outside the runtime
  residual store — residuals threaded through optimizer state must be
  zeroed on elastic reset (a resized mesh must not replay stale
  residuals), and residual-less in-jit exchanges get no feedback at all.
- ``HVP110`` world_dependent_signature — error (``check_elastic``): a
  collective whose stream position, order, dtype, repeat count or payload
  is a function of WORLD SIZE, so a resized mesh replays the step against
  mismatched peers. Payloads that are an even reshard of one logical
  buffer (ZeRO ``ceil(B/n)`` shards) or fully replicated pass clean.
- ``HVP111`` tier_budget_exceeded — error (``analysis/cost.py``): the
  predicted per-step DCN bytes exceed the declared budget
  (``HOROVOD_DCN_BYTES_BUDGET`` / ``dcn_budget_bytes=``).
- ``HVP112`` unbounded_repeat — advisory: a collective under a ``while``
  whose trip count the walker cannot bound — cost totals and the elastic
  generation diff are LOWER BOUNDS for it, not exact.
- ``HVP113`` hierarchical_one_slice — advisory: a hierarchical 2-level
  allreduce (local RS -> cross -> local AG, ``hier_triads``) — or the
  armed ``HOROVOD_HIERARCHICAL_DISPATCH`` tier — over a 1-slice layout:
  two extra ICI legs for no DCN saving.

Lint codes (``HVL0xx``) are documented in :mod:`horovod_tpu.analysis.lint`.
"""

import dataclasses

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer result. ``rank``/``op``/``ps``/``seq``/``sig`` are the
    flight-recorder-aligned identity fields (None where not applicable)."""

    code: str
    severity: str
    message: str
    rank: int = None
    op: str = None
    ps: str = None
    seq: int = None
    sig: str = None

    def identity(self):
        """The flight-recorder event identity this finding points at."""
        return (self.op, self.ps, self.seq, self.sig)

    def render(self):
        where = ""
        if self.rank is not None:
            where += f" rank={self.rank}"
        if self.op is not None:
            where += f" op={self.op}"
        if self.ps is not None:
            where += f" ps={self.ps}"
        if self.seq is not None:
            where += f" seq={self.seq}"
        if self.sig is not None:
            where += f" sig={self.sig}"
        return f"{self.code} [{self.severity}]{where}: {self.message}"

    def to_dict(self):
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


def sort_findings(findings):
    """Errors first, then warnings, then advisories; stable within a
    severity."""
    return sorted(findings,
                  key=lambda f: (_SEVERITY_ORDER.get(f.severity, 3),))
