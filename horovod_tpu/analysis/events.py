"""Collective-event model for the static analyzer.

A :class:`CollectiveEvent` is the static twin of one flight-recorder
``dispatch`` event: same ``op`` label (``collective_ops`` op_kind,
lowercased), same process-set label (``_ps_label``), same per-process-set
monotonic ``seq`` (1-based), and — for eager ops — the same signature hash
(``flight.recorder.signature`` over the staged GLOBAL stacked tensors).
That alignment is what makes :func:`horovod_tpu.analysis.program.cross_check`
a tuple comparison instead of a joining heuristic.

In-jit collectives (``lax.psum``/``ppermute``/... inside ``shard_map``/
``pjit``) are never seen by the flight recorder — they live inside the
user's compiled program — so their events are static-only, labelled
``ps="axis:<name>"`` with ``origin="jit"``.
"""

import dataclasses
import zlib


class _Aval:
    """shape/dtype stand-in accepted by ``flight.recorder.signature`` (it
    reads only ``.shape`` and ``.dtype``)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = dtype


def signature_of(shapes, dtypes):
    """The flight recorder's signature hash for a (shapes, dtypes) pair —
    one source of truth (delegates to :func:`flight.recorder.signature`)."""
    from horovod_tpu.flight.recorder import signature
    return signature([_Aval(s, d) for s, d in zip(shapes, dtypes)])


@dataclasses.dataclass(frozen=True)
class CollectiveEvent:
    """One predicted collective dispatch on one simulated rank."""

    op: str            # flight op label: "allreduce", "allgather", ... or
    #                    the jit primitive: "psum", "ppermute", ...
    ps: str            # "global" / "set<N>" / "unregistered" / "axis:<ax>"
    seq: int           # per-ps monotonic sequence number (1-based)
    shapes: tuple      # wire tensor shapes (global stacked, eager ops)
    dtypes: tuple      # matching dtype strings
    origin: str        # "eager" | "fused" | "jit"
    name: str = None   # user-supplied op name, if any
    nbytes: int = 0
    # Static trip count when inside a lax.scan; 0 = UNKNOWN (the event
    # sits in a while-loop body whose trip count is data-dependent —
    # diffed for presence across ranks, excluded from sequence hashes).
    repeat: int = 1
    # Cost-model context (analysis/cost.py). Eager ops: the reduce-op
    # name ("Sum"/"Average"/...; None for non-reductions) and, for a
    # non-global process set, its member ranks — tier classification
    # needs to know WHICH ranks exchange, not just how many. Jit ops:
    # the named-axis sizes the collective communicates over (None =
    # unknown axis). None of these enter key()/identity(), so cross-rank
    # diffing and the flight cross-check are unchanged.
    red_op: str = None
    ps_ranks: tuple = None
    axis_sizes: tuple = ()

    @property
    def sig(self):
        return signature_of(self.shapes, self.dtypes)

    def identity(self):
        """The flight-recorder identity tuple ``(op, ps, seq, sig)``."""
        return (self.op, self.ps, self.seq, self.sig)

    def key(self):
        """Identity *without* seq — what cross-rank diffing compares at
        each position."""
        return (self.op, self.ps, self.sig, self.repeat)

    def per_rank_elems(self):
        """Elements ONE participant contributes: eager shapes are global
        rank-major stacks (leading axis = set size), jit shapes are the
        per-device view already."""
        total = 0
        for s in self.shapes:
            dims = s[1:] if self.origin != "jit" else s
            cnt = 1
            for d in dims:
                cnt *= int(d)
            total += cnt
        return total

    def group_size(self, world_size=None):
        """Number of exchange participants: the leading stacked dim for
        eager ops, the product of known axis sizes for jit ops (falling
        back to ``world_size`` when the walker couldn't size the axis)."""
        if self.origin != "jit":
            if self.shapes and self.shapes[0]:
                return int(self.shapes[0][0])
            return len(self.ps_ranks) if self.ps_ranks else world_size
        sizes = [s for s in self.axis_sizes if s]
        if not sizes:
            return world_size
        p = 1
        for s in sizes:
            p *= int(s)
        return p

    def describe(self):
        shp = ",".join(f"{tuple(s)}:{d}"
                       for s, d in zip(self.shapes, self.dtypes))
        rep = "" if self.repeat == 1 \
            else (" x?" if self.repeat == 0 else f" x{self.repeat}")
        return (f"{self.op}[{self.ps}] seq={self.seq} sig={self.sig}"
                f" {shp}{rep} ({self.origin})")


def assign_seqs(events):
    """Stamp per-process-set monotonic 1-based seqs (the flight recorder's
    numbering) onto an ordered event list; ``repeat`` advances the counter
    by its trip count, matching what the recorder would log across loop
    iterations. Returns a new list."""
    counters = {}
    out = []
    for e in events:
        seq = counters.get(e.ps, 0) + 1
        # repeat 0 (unknown while-loop count) still advances by one: the
        # later numbering is approximate either way, and the hash skips
        # these events.
        counters[e.ps] = seq + (max(e.repeat, 1) - 1)
        out.append(dataclasses.replace(e, seq=seq))
    return out


def sequence_hash(events, ps=None):
    """Stable (cross-process) hash of an ordered collective sequence:
    crc32 over each event's ``op|ps|sig|repeat``. Accepts
    :class:`CollectiveEvent` lists or flight-recorder event dicts
    (``kind=="dispatch"`` rows; others are skipped). ``ps`` filters to one
    process set. Events with ``repeat=0`` (unknown while-loop trip count)
    are excluded — their contribution is inherently non-static."""
    parts = []
    for e in events:
        if isinstance(e, dict):
            if e.get("kind") != "dispatch":
                continue
            if ps is not None and e.get("ps") != ps:
                continue
            parts.append(f"{e.get('op')}|{e.get('ps')}|{e.get('sig')}|1")
        else:
            if ps is not None and e.ps != ps:
                continue
            if e.repeat == 0:
                continue
            parts.append(f"{e.op}|{e.ps}|{e.sig}|{e.repeat}")
    return format(zlib.crc32(";".join(parts).encode()), "08x")
