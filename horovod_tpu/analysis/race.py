"""hvdrace -- whole-package lock-graph analyzer with a runtime acquisition witness.

Static side
-----------
Two AST passes over every module in the package:

* pass A collects lock *definitions* (``threading.Lock/RLock/Condition``
  allocations bound to module globals or ``self`` attributes), class layouts,
  import aliases (including function-local imports, which ``basics`` uses
  heavily), and enough type breadcrumbs (``self.x = ClassName(...)``,
  ``g = ClassName(...)``) to resolve attribute calls across modules.
* pass B walks every function body tracking which locks are held at each
  statement (``with lock:`` scopes plus *sticky* ``lock.acquire()`` calls that
  stay held until a matching ``.release()``), and records call sites, blocking
  calls, guarded-field accesses, ``Thread(...)`` creations, ``signal.signal``
  registrations and stop/join evidence.

Holds are then propagated over the resolvable call graph to a fixed point, so
"function f runs while lock L is held" is known even when the ``with`` sits
three frames up in another module.  The propagated graph feeds the rules:

========  ====================================================================
HVR201    lock-order inversion: a cycle in the may-hold-before graph; the
          finding carries a witness path for both directions.
HVR202    blocking call (KV/HTTP RPC, sockets, subprocess, ``Thread.join``,
          ``sleep``, unbounded ``wait``, trace/flight ``dump``) reachable
          while an unbounded lock is held.  Subsumes and retires
          hvdlint HVL001/HVL006.
HVR203    guarded-field escape: an attribute written under its class's lock in
          some methods and read or written without it in others (lockset
          style).  Also applied to module-level mutable-container globals.
HVR204    signal-handler-unsafe call: a handler registered via
          ``signal.signal`` reaches an *unbounded* lock acquire.
HVR205    thread-lifecycle leak: a ``Thread`` started from an init/arm path
          whose owner has no stop/join evidence reachable from
          ``basics.shutdown`` (or an ``atexit`` root).
HVR200    bare ``# hvdrace: disable=...`` without a ``-- reason``.
HVR210    (cross-check only) runtime acquisition edge not predicted
          statically -- an analyzer gap, never suppressible.
HVR211    (cross-check only) runtime witness saw a package lock the static
          pass never resolved.
HVR999    file does not parse.
==========================================================================

Suppression follows hvdlint conventions exactly: ``# hvdrace:
disable=HVR203 -- reason`` on the offending line or on an enclosing
``def``/``with`` line, and ``# hvdrace: skip-file`` in the first two lines.

Runtime witness
---------------
``install_witness()`` (or ``HVD_LOCK_WITNESS=1`` at import) swaps
``threading.Lock``/``threading.RLock`` for factories that hand instrumented
proxies to *package* allocation sites only (callers outside the package --
including ``threading.py`` itself building a ``Condition`` -- get real locks),
and sweeps already-imported ``horovod_tpu`` modules for module-global locks,
wrapping the existing object so mutual exclusion is preserved.  Each proxy
records, per thread, the edge (held-lock -> acquired-lock) on every successful
acquisition.  ``cross_check(report, edges)`` then asserts every observed edge
exists in the static may-hold-before graph -- a missed edge is a bug in *this
analyzer*, reported as HVR210.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(r"#\s*hvdrace:\s*disable=([A-Z0-9, ]+?)\s*(?:--\s*(.*))?$")
_SKIP_FILE_RE = re.compile(r"#\s*hvdrace:\s*skip-file")

ALL_RULES = frozenset({"HVR201", "HVR202", "HVR203", "HVR204", "HVR205"})

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

# Calls that can block indefinitely.  Grouped for the finding message.
_BLOCKING_RPC = {
    "urlopen", "kv_get", "kv_put", "kv_delete", "wait_for_key", "negotiate",
    "getresponse", "request", "read_response",
}
_BLOCKING_SOCKET = {"connect", "accept", "recv", "recvfrom", "sendall", "makefile"}
_BLOCKING_SUBPROC = {"Popen", "check_call", "check_output", "run", "communicate"}
_BLOCKING_COLLECTIVE = {
    "allreduce", "allgather", "broadcast", "alltoall", "reducescatter",
    "barrier", "grouped_allreduce",
}
_BLOCKING_MISC = {"sleep", "dump", "join", "wait", "get", "put", "select"}

# Receiver-name hints: `join` only blocks interestingly on threads/processes/
# queues; `get`/`put` only on queues; `wait` on events/conditions.
_JOIN_RECV_HINT = ("thread", "proc", "worker", "agent")
_QUEUE_RECV_HINT = ("queue", "q", "inbox", "outbox")
_WAIT_RECV_HINT = ("event", "cond", "cv", "stop", "done", "ready", "_stop")

_STOP_EVIDENCE_CALLS = {"join", "set", "stop", "close", "shutdown", "terminate", "cancel"}

_INIT_PATH_NAMES = ("init", "arm", "start", "configure", "install", "__init__")


# --------------------------------------------------------------------------
# findings
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class RaceFinding:
    code: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_dict(self) -> dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "message": self.message}


# --------------------------------------------------------------------------
# small AST helpers
# --------------------------------------------------------------------------


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _recv_name(node: ast.Call) -> str:
    """Best-effort textual receiver of an attribute call: `a.b.c()` -> 'a.b'."""
    fn = node.func
    if not isinstance(fn, ast.Attribute):
        return ""
    parts: List[str] = []
    cur = fn.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call) and not parts:
        return "()"      # method on a call result: f(...).m()
    return ".".join(reversed(parts))


def _is_lock_ctor(node: ast.Call) -> Optional[str]:
    """Return the ctor name if `node` allocates a threading lock."""
    fn = node.func
    name = None
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        name = fn.id
    elif isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTORS:
        base = fn.value
        if isinstance(base, ast.Name) and base.id in ("threading", "_threading"):
            name = fn.attr
        elif isinstance(base, ast.Name):
            # `t.Lock()` style aliases are rare; accept module-ish names.
            name = fn.attr if base.id.islower() else None
    return name


def _has_timeout_arg(node: ast.Call) -> bool:
    if node.args:
        return True
    return any(kw.arg == "timeout" and not (
        isinstance(kw.value, ast.Constant) and kw.value.value is None)
        for kw in node.keywords)


def _lockish_name(text: str) -> bool:
    low = text.lower()
    return "lock" in low or low.endswith(("_cv", "_cond")) or "condition" in low


# --------------------------------------------------------------------------
# pass A: module-level collection
# --------------------------------------------------------------------------


@dataclass
class _ClassInfo:
    name: str
    line: int
    # attr -> lock ident for self.<attr> = threading.Lock()
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    # attr -> class name for self.<attr> = SomeClass(...)
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, "_FuncInfo"] = field(default_factory=dict)


@dataclass
class _FuncInfo:
    qual: str                      # "mod:func" or "mod:Class.func"
    module: str
    cls: Optional[str]
    name: str
    line: int
    # locks acquired locally: ident -> (line, bounded)
    acquires: List[Tuple[str, int, bool]] = field(default_factory=list)
    # call sites: (callee-token, receiver-text, line, frozenset(held idents), node)
    calls: List[Tuple[str, str, int, FrozenSet[str]]] = field(default_factory=list)
    # blocking calls noticed locally: (kind, name, line, frozenset(held))
    blocking: List[Tuple[str, str, int, FrozenSet[str]]] = field(default_factory=list)
    # self-field accesses: (attr, line, is_write, frozenset(held))
    fields: List[Tuple[str, int, bool, FrozenSet[str]]] = field(default_factory=list)
    # module-global accesses: (name, line, is_write, frozenset(held))
    globals_acc: List[Tuple[str, int, bool, FrozenSet[str]]] = field(default_factory=list)
    # Thread(...) creations: (line, target-name-if-known)
    thread_creates: List[Tuple[int, str]] = field(default_factory=list)
    # stop evidence (calls like x.join()/stop()/set())
    has_stop_evidence: bool = False
    # ordered pairs (held -> acquired) with the acquire line, for HVR201
    order_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    # propagated entry holds: ident -> chain of "qual@line" strings
    entry_holds: Dict[str, Tuple[str, ...]] = field(default_factory=dict)


@dataclass
class _ModuleInfo:
    rel: str                       # repo-relative path
    modname: str                   # dotted module name within the package
    tree: Optional[ast.AST]
    source_lines: List[str]
    suppressions: Dict[int, Tuple[Set[str], str]] = field(default_factory=dict)
    def_lines: Dict[int, List[int]] = field(default_factory=dict)  # line -> enclosing def/with lines
    # module-global lock idents: varname -> ident
    locks: Dict[str, str] = field(default_factory=dict)
    # lock allocation sites: (line) -> ident, for witness site mapping
    lock_sites: Dict[int, str] = field(default_factory=dict)
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    funcs: Dict[str, _FuncInfo] = field(default_factory=dict)  # qual -> info
    # import aliases: local name -> dotted module (package-internal only)
    imports: Dict[str, str] = field(default_factory=dict)
    # from-import symbols: local name -> (module, symbol)
    symbol_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # module globals holding class instances: name -> class token
    global_types: Dict[str, str] = field(default_factory=dict)
    # module-level mutable container globals (dict/list/set literals or ctors)
    mutable_globals: Set[str] = field(default_factory=set)
    signal_handlers: List[Tuple[str, int]] = field(default_factory=list)  # (handler token, line)
    atexit_roots: List[str] = field(default_factory=list)  # handler tokens


class Report:
    """Result of a static analysis run."""

    def __init__(self) -> None:
        self.findings: List[RaceFinding] = []
        self.modules: Dict[str, _ModuleInfo] = {}
        self.funcs: Dict[str, _FuncInfo] = {}
        self.edges: Set[Tuple[str, str]] = set()      # may-hold-before
        # edge -> (rel, line, holder-function qual): the witness site
        self.edge_witness: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.lock_table: Dict[Tuple[str, int], str] = {}  # (rel, line) -> ident
        self.lock_idents: Set[str] = set()
        self.n_files = 0
        self.seconds = 0.0

    def to_dict(self) -> dict:
        return {
            "files": self.n_files,
            "seconds": round(self.seconds, 3),
            "locks": sorted(self.lock_idents),
            "edges": sorted(["%s->%s" % e for e in self.edges]),
            "findings": [f.to_dict() for f in self.findings],
        }


def _collect_suppressions(source_lines: List[str], mi: _ModuleInfo) -> List[RaceFinding]:
    bare: List[RaceFinding] = []
    for i, line in enumerate(source_lines, start=1):
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            bare.append(RaceFinding(
                "HVR200", mi.rel, i,
                "bare 'hvdrace: disable' without '-- reason'; suppressions "
                "must explain themselves"))
            continue
        mi.suppressions[i] = (codes, reason)
    return bare


def _index_def_lines(mi: _ModuleInfo) -> None:
    """Map every line to the def/with header lines that enclose it."""
    if mi.tree is None:
        return

    stack: List[int] = []

    def walk(node: ast.AST) -> None:
        push = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.With))
        if push:
            stack.append(node.lineno)
        for child in ast.iter_child_nodes(node):
            walk(child)
        if push:
            stack.pop()
        if hasattr(node, "lineno"):
            lineno = node.lineno
            if stack and lineno not in mi.def_lines:
                mi.def_lines[lineno] = list(stack)
            elif stack:
                mi.def_lines[lineno] = list(stack)

    walk(mi.tree)


def _suppressed(mi: _ModuleInfo, code: str, line: int) -> bool:
    candidates = [line] + mi.def_lines.get(line, [])
    for ln in candidates:
        entry = mi.suppressions.get(ln)
        if entry and code in entry[0]:
            return True
    return False


def _pass_a(mi: _ModuleInfo) -> None:
    """Collect locks, classes, imports, type breadcrumbs."""
    tree = mi.tree
    assert tree is not None
    mod = mi.modname

    def record_import(node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("horovod_tpu"):
                    mi.imports[alias.asname or alias.name.split(".")[-1]] = alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                # relative import: resolve against this module's package
                parts = ("horovod_tpu." + mod).split(".")
                parts = parts[: len(parts) - node.level]
                base = ".".join(parts + ([node.module] if node.module else []))
            if not base.startswith("horovod_tpu"):
                return
            for alias in node.names:
                local = alias.asname or alias.name
                mi.symbol_imports[local] = (base, alias.name)
                # `from pkg import mod` -- also usable as a module alias
                mi.imports.setdefault(local, base + "." + alias.name)

    for node in ast.walk(tree):
        record_import(node)

    def lock_ident_module(var: str) -> str:
        return f"{mod}:{var}"

    # module-level statements
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and isinstance(node.value, ast.Call):
                ctor = _is_lock_ctor(node.value)
                if ctor:
                    ident = lock_ident_module(tgt.id)
                    mi.locks[tgt.id] = ident
                    mi.lock_sites[node.value.lineno] = ident
                else:
                    cn = _call_name(node.value)
                    if cn and cn[:1].isupper():
                        mi.global_types[tgt.id] = cn
                    if cn in ("dict", "list", "set", "deque", "defaultdict",
                              "OrderedDict", "Counter"):
                        mi.mutable_globals.add(tgt.id)
            elif isinstance(tgt, ast.Name) and isinstance(
                    node.value, (ast.Dict, ast.List, ast.Set)):
                mi.mutable_globals.add(tgt.id)

    # classes
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        ci = _ClassInfo(name=node.name, line=node.lineno)
        mi.classes[node.name] = ci
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(item):
                if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Attribute)
                        and isinstance(sub.targets[0].value, ast.Name)
                        and sub.targets[0].value.id == "self"
                        and isinstance(sub.value, ast.Call)):
                    attr = sub.targets[0].attr
                    ctor = _is_lock_ctor(sub.value)
                    if ctor:
                        ident = f"{mod}:{node.name}.{attr}"
                        ci.lock_attrs[attr] = ident
                        mi.lock_sites[sub.value.lineno] = ident
                    else:
                        cn = _call_name(sub.value)
                        if cn and cn[:1].isupper():
                            ci.attr_types[attr] = cn

    # anonymous / dict-valued lock allocations anywhere else in the module
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            ctor = _is_lock_ctor(node)
            if ctor and node.lineno not in mi.lock_sites:
                ident = f"{mod}:L{node.lineno}"
                mi.lock_sites[node.lineno] = ident

    # signal handlers + atexit roots
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            recv = _recv_name(node)
            if name == "signal" and (recv == "signal" or recv.endswith("signal")):
                if len(node.args) >= 2:
                    h = node.args[1]
                    tok = h.id if isinstance(h, ast.Name) else (
                        h.attr if isinstance(h, ast.Attribute) else "")
                    if tok:
                        mi.signal_handlers.append((tok, node.lineno))
            elif name == "register" and recv == "atexit" and node.args:
                h = node.args[0]
                tok = h.id if isinstance(h, ast.Name) else (
                    h.attr if isinstance(h, ast.Attribute) else "")
                if tok:
                    mi.atexit_roots.append(tok)


# --------------------------------------------------------------------------
# pass B: per-function walker
# --------------------------------------------------------------------------


class _FuncWalker(ast.NodeVisitor):
    """Track held locks through a single function body."""

    def __init__(self, mi: _ModuleInfo, fi: _FuncInfo, ci: Optional[_ClassInfo],
                 local_imports: Dict[str, str],
                 local_symbols: Dict[str, Tuple[str, str]]) -> None:
        self.mi = mi
        self.fi = fi
        self.ci = ci
        self.local_imports = local_imports
        self.local_symbols = local_symbols
        self.with_stack: List[str] = []
        self.sticky: List[str] = []
        self.local_types: Dict[str, str] = {}
        # local names bound to locks (e.g. `lk = self._lock`)
        self.local_locks: Dict[str, str] = {}

    # -- lock expression resolution ------------------------------------

    def _resolve_lock_expr(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            if node.id in self.local_locks:
                return self.local_locks[node.id]
            if node.id in self.mi.locks:
                return self.mi.locks[node.id]
            if _lockish_name(node.id):
                return f"{self.fi.module}:~{node.id}"
            return None
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" and self.ci:
                if node.attr in self.ci.lock_attrs:
                    return self.ci.lock_attrs[node.attr]
                if _lockish_name(node.attr):
                    return f"{self.fi.module}:{self.ci.name}.{node.attr}"
                return None
            if isinstance(base, ast.Name):
                # module alias: other_mod._lock — but only lock-ish attrs;
                # `with mod.scoped_thing():` is a contextmanager, not an
                # acquisition
                target_mod = self.local_imports.get(base.id) or self.mi.imports.get(base.id)
                if target_mod:
                    if _lockish_name(node.attr):
                        return f"{_strip_pkg(target_mod)}:{node.attr}"
                    return None
                if _lockish_name(node.attr):
                    return f"{self.fi.module}:~{base.id}.{node.attr}"
            if isinstance(base, ast.Subscript) and _lockish_name(
                    getattr(node, "attr", "")):
                return f"{self.fi.module}:~subscript.{node.attr}"
            return None
        if isinstance(node, ast.Subscript):
            # state["lock"] style
            if isinstance(node.slice, ast.Constant) and isinstance(
                    node.slice.value, str) and _lockish_name(node.slice.value):
                return f"{self.fi.module}:~[{node.slice.value}]"
        return None

    def _held(self) -> FrozenSet[str]:
        return frozenset(self.with_stack + self.sticky)

    def _note_acquire(self, ident: str, line: int, bounded: bool) -> None:
        for h in self._held():
            if h != ident:
                self.fi.order_edges.append((h, ident, line))
        self.fi.acquires.append((ident, line, bounded))

    # -- nested defs ---------------------------------------------------
    # A nested def's body does NOT run where it is defined — it runs when
    # the closure is called, usually on another thread (Thread targets)
    # or after the enclosing locks are released (deferred emissions).
    # _pass_b analyzes each nested def as its own _FuncInfo; attributing
    # its calls/acquires to the parent would fabricate held-lock chains.

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    # -- with ----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            ctx = item.context_expr
            target = ctx
            if isinstance(ctx, ast.Call):
                # `with lock.acquire_timeout(...)` etc -- treat receiver
                target = ctx.func if not isinstance(ctx.func, ast.Attribute) else ctx.func.value
                if isinstance(ctx.func, ast.Attribute) and ctx.func.attr in (
                        "acquire", "acquire_timeout"):
                    target = ctx.func.value
                else:
                    target = ctx
            ident = self._resolve_lock_expr(target if not isinstance(target, ast.Call) else target.func if isinstance(target, ast.Call) else target)
            if ident is None and not isinstance(ctx, ast.Call):
                ident = self._resolve_lock_expr(ctx)
            if ident:
                self._note_acquire(ident, node.lineno, bounded=False)
                self.with_stack.append(ident)
                pushed += 1
            if item.optional_vars is not None and isinstance(
                    item.optional_vars, ast.Name) and ident:
                self.local_locks[item.optional_vars.id] = ident
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.with_stack.pop()

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    # -- assignments ---------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1:
            tgt = node.targets[0]
            val = node.value
            if isinstance(tgt, ast.Name):
                ident = self._resolve_lock_expr(val) if isinstance(
                    val, (ast.Name, ast.Attribute)) else None
                if ident:
                    self.local_locks[tgt.id] = ident
                elif isinstance(val, ast.Call):
                    cn = _call_name(val)
                    if cn and cn[:1].isupper():
                        self.local_types[tgt.id] = cn
            if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                self.fi.fields.append((tgt.attr, node.lineno, True, self._held()))
            elif isinstance(tgt, ast.Name) and tgt.id in self.mi.mutable_globals:
                self.fi.globals_acc.append((tgt.id, node.lineno, True, self._held()))
            elif isinstance(tgt, ast.Subscript):
                base = tgt.value
                if (isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"):
                    self.fi.fields.append((base.attr, node.lineno, True, self._held()))
                elif isinstance(base, ast.Name) and base.id in self.mi.mutable_globals:
                    self.fi.globals_acc.append((base.id, node.lineno, True, self._held()))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        tgt = node.target
        if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            self.fi.fields.append((tgt.attr, node.lineno, True, self._held()))
        elif isinstance(tgt, ast.Name) and tgt.id in self.mi.mutable_globals:
            self.fi.globals_acc.append((tgt.id, node.lineno, True, self._held()))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)):
            self.fi.fields.append((node.attr, node.lineno, False, self._held()))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if (isinstance(node.ctx, ast.Load)
                and node.id in self.mi.mutable_globals):
            self.fi.globals_acc.append((node.id, node.lineno, False, self._held()))

    def visit_Global(self, node: ast.Global) -> None:
        pass

    # -- function-local imports ----------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.startswith("horovod_tpu"):
                local = alias.asname or alias.name.split(".")[-1]
                self.local_imports[local] = alias.name
                # call resolution runs after all walks; make the alias
                # visible module-wide (distinctive local names in practice)
                self.mi.imports.setdefault(local, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            anchor = ("horovod_tpu." + self.fi.module).split(".")
            anchor = anchor[: len(anchor) - node.level]
            base = ".".join(anchor + ([node.module] if node.module else []))
        if base.startswith("horovod_tpu"):
            for alias in node.names:
                local = alias.asname or alias.name
                self.local_symbols[local] = (base, alias.name)
                # `from x import mod` where mod is a submodule
                self.local_imports.setdefault(local, base + "." + alias.name)
                self.mi.imports.setdefault(local, base + "." + alias.name)
                self.mi.symbol_imports.setdefault(local, (base, alias.name))

    # -- calls ---------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        recv = _recv_name(node)
        held = self._held()
        line = node.lineno

        # lock protocol calls
        if name == "acquire":
            ident = self._resolve_lock_expr(node.func.value) if isinstance(
                node.func, ast.Attribute) else None
            if ident:
                bounded = _has_timeout_arg(node)
                self._note_acquire(ident, line, bounded)
                # held is held regardless of boundedness; the bounded flag
                # only matters for HVR204 (handler deadlock) analysis
                self.sticky.append(ident)
            self.generic_visit(node)
            return
        if name == "release":
            ident = self._resolve_lock_expr(node.func.value) if isinstance(
                node.func, ast.Attribute) else None
            if ident and ident in self.sticky:
                self.sticky.remove(ident)
            self.generic_visit(node)
            return

        # Thread creation
        if name == "Thread":
            target = ""
            for kw in node.keywords:
                if kw.arg == "target":
                    v = kw.value
                    target = v.id if isinstance(v, ast.Name) else (
                        v.attr if isinstance(v, ast.Attribute) else "")
            self.fi.thread_creates.append((line, target))

        # stop evidence
        if name in _STOP_EVIDENCE_CALLS and isinstance(node.func, ast.Attribute):
            if name == "set":
                low = recv.lower()
                if any(h in low for h in _WAIT_RECV_HINT):
                    self.fi.has_stop_evidence = True
            else:
                self.fi.has_stop_evidence = True

        # blocking classification
        kind = self._blocking_kind(name, recv, node)
        if kind:
            self.fi.blocking.append((kind, name, line, held))

        # record call site for propagation
        if name:
            self.fi.calls.append((name, recv, line, held))
        self.generic_visit(node)

    def _blocking_kind(self, name: str, recv: str, node: ast.Call) -> Optional[str]:
        low_recv = recv.lower()
        if name in _BLOCKING_RPC:
            return "rpc"
        if name in _BLOCKING_SUBPROC and (recv in ("subprocess", "sp") or name == "Popen"):
            return "subprocess"
        if name in _BLOCKING_SOCKET and ("sock" in low_recv or "conn" in low_recv
                                         or recv == "s"):
            return "socket"
        if name in _BLOCKING_COLLECTIVE:
            return "collective"
        if name == "sleep":
            return "sleep"
        if name == "dump" and ("trace" in low_recv or "flight" in low_recv
                               or "recorder" in low_recv or "json" not in low_recv
                               and "pickle" not in low_recv and recv != ""):
            # json.dump/pickle.dump to an open file is fast; trace/flight dump
            # does real I/O + snapshotting.
            if "json" in low_recv or "pickle" in low_recv or "yaml" in low_recv:
                return None
            return "dump"
        if name == "join" and any(h in low_recv for h in _JOIN_RECV_HINT):
            if not _has_timeout_arg(node):
                return "join"
        if name == "wait" and not _has_timeout_arg(node):
            if any(h in low_recv for h in _WAIT_RECV_HINT) or self._resolve_lock_expr(
                    node.func.value if isinstance(node.func, ast.Attribute) else node):
                return "wait"
        if name in ("get", "put") and any(h == low_recv or low_recv.endswith("." + h)
                                          for h in _QUEUE_RECV_HINT):
            if not _has_timeout_arg(node):
                return "queue"
        return None


def _strip_pkg(dotted: str) -> str:
    return dotted[len("horovod_tpu."):] if dotted.startswith("horovod_tpu.") else dotted


def _pass_b(mi: _ModuleInfo) -> None:
    assert mi.tree is not None
    mod = mi.modname

    def walk_func(fn: ast.AST, cls: Optional[_ClassInfo]) -> None:
        assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        qual = f"{mod}:{cls.name + '.' if cls else ''}{fn.name}"
        fi = _FuncInfo(qual=qual, module=mod, cls=cls.name if cls else None,
                       name=fn.name, line=fn.lineno)
        mi.funcs[qual] = fi
        if cls:
            cls.methods[fn.name] = fi
        walker = _FuncWalker(mi, fi, cls, dict(), dict())
        for stmt in fn.body:
            walker.visit(stmt)
        # nested defs: analyze as separate anonymous-ish functions under the
        # same qual namespace so thread targets like closures get coverage.
        for stmt in ast.walk(fn):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and stmt is not fn:
                nested_qual = f"{mod}:{cls.name + '.' if cls else ''}{fn.name}.{stmt.name}"
                if nested_qual in mi.funcs:
                    continue
                nfi = _FuncInfo(qual=nested_qual, module=mod,
                                cls=cls.name if cls else None,
                                name=stmt.name, line=stmt.lineno)
                mi.funcs[nested_qual] = nfi
                nwalker = _FuncWalker(mi, nfi, cls, dict(walker.local_imports),
                                      dict(walker.local_symbols))
                for s in stmt.body:
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        continue
                    nwalker.visit(s)

    for node in mi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_func(node, None)
        elif isinstance(node, ast.ClassDef):
            ci = mi.classes.get(node.name)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk_func(item, ci)


# --------------------------------------------------------------------------
# call resolution + hold propagation
# --------------------------------------------------------------------------


def _resolve_call(report: Report, mi: _ModuleInfo, fi: _FuncInfo,
                  name: str, recv: str) -> List[str]:
    """Return candidate callee quals for a call site."""
    out: List[str] = []
    mod = mi.modname

    if not recv:
        # bare call: same module function, from-imported symbol, or class ctor
        q = f"{mod}:{name}"
        if q in report.funcs:
            out.append(q)
        sym = mi.symbol_imports.get(name)
        if sym:
            target_mod = _strip_pkg(sym[0])
            q2 = f"{target_mod}:{sym[1]}"
            if q2 in report.funcs:
                out.append(q2)
            # from-imported class: route to its __init__
            q3 = f"{target_mod}:{sym[1]}.__init__"
            if q3 in report.funcs:
                out.append(q3)
            if not out:
                out.extend(_follow_reexport(report, target_mod, sym[1]))
        if name in mi.classes:
            q4 = f"{mod}:{name}.__init__"
            if q4 in report.funcs:
                out.append(q4)
        return out

    if recv == "()":
        # method on a call result (e.g. FAMILY.labels(...).inc()): the
        # result type is unknown — resolve generously to same-named
        # methods of classes in this module and its direct imports.
        mods = {mod}
        mods.update(_strip_pkg(v) for v in mi.imports.values())
        mods.update(_strip_pkg(s[0]) for s in mi.symbol_imports.values())
        for tm in sorted(mods):
            tmi = report.modules.get(tm)
            if tmi is None:
                continue
            for cls in tmi.classes:
                q = f"{tm}:{cls}.{name}"
                if q in report.funcs:
                    out.append(q)
        return out

    head = recv.split(".")[0]

    if head == "self" and fi.cls:
        ci = mi.classes.get(fi.cls)
        if recv == "self":
            q = f"{mod}:{fi.cls}.{name}"
            if q in report.funcs:
                out.append(q)
            return out
        # self.attr.method() via attr type inference
        if ci and "." in recv:
            attr = recv.split(".")[1]
            tcls = ci.attr_types.get(attr)
            if tcls:
                out.extend(_methods_named(report, tcls, name))
        return out

    # module alias call: mod_alias.func()
    target = mi.imports.get(head)
    if target:
        tmod = _strip_pkg(target)
        if "." in recv:
            # alias.sub.attr unsupported beyond one hop
            pass
        q = f"{tmod}:{name}"
        if q in report.funcs:
            out.append(q)
        # alias.Class() ctor
        q2 = f"{tmod}:{name}.__init__"
        if q2 in report.funcs:
            out.append(q2)
        if not out:
            # package __init__ re-export: alias.func defined elsewhere
            out.extend(_follow_reexport(report, tmod, name))
        return out

    # local/global variable of known class type
    tcls = mi.global_types.get(head)
    if tcls:
        out.extend(_methods_named(report, tcls, name))
        return out

    return out


def _follow_reexport(report: Report, tmod: str, name: str) -> List[str]:
    """One extra hop through a module's own `from X import name`:
    resolves `pkg/__init__.py` re-exports to the defining module."""
    out: List[str] = []
    tmi = report.modules.get(tmod)
    sym = tmi.symbol_imports.get(name) if tmi else None
    if sym:
        smod = _strip_pkg(sym[0])
        for cand in (f"{smod}:{sym[1]}", f"{smod}:{sym[1]}.__init__"):
            if cand in report.funcs:
                out.append(cand)
    return out


def _methods_named(report: Report, cls_token: str, method: str) -> List[str]:
    out = []
    for mi in report.modules.values():
        if cls_token in mi.classes:
            q = f"{mi.modname}:{cls_token}.{method}"
            if q in report.funcs:
                out.append(q)
    return out


def _propagate_holds(report: Report) -> None:
    """Fixed-point: push held-lock sets across resolvable call edges."""
    # Pre-resolve call edges once.
    call_edges: List[Tuple[_FuncInfo, FrozenSet[str], int, _FuncInfo]] = []
    for mi in report.modules.values():
        for fi in mi.funcs.values():
            for name, recv, line, held in fi.calls:
                for callee_q in _resolve_call(report, mi, fi, name, recv):
                    callee = report.funcs.get(callee_q)
                    if callee is not None and callee is not fi:
                        call_edges.append((fi, held, line, callee))

    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for caller, held_at_site, line, callee in call_edges:
            # effective holds at the call site = local holds + caller entry holds
            eff: Dict[str, Tuple[str, ...]] = {}
            for h in held_at_site:
                eff[h] = (f"{caller.qual}@{line}",)
            for h, chain in caller.entry_holds.items():
                if h not in eff:
                    eff[h] = chain + (f"{caller.qual}@{line}",)
            for h, chain in eff.items():
                if h not in callee.entry_holds:
                    if len(chain) <= 12:
                        callee.entry_holds[h] = chain
                        changed = True


def _build_order_graph(report: Report) -> None:
    for mi in report.modules.values():
        for fi in mi.funcs.values():
            entry = set(fi.entry_holds)
            for a, b, line in fi.order_edges:
                _add_edge(report, a, b, mi.rel, line, fi.qual)
            for ident, line, bounded in fi.acquires:
                for h in entry:
                    if h != ident:
                        _add_edge(report, h, ident, mi.rel, line, fi.qual)


def _add_edge(report: Report, a: str, b: str, rel: str, line: int,
              qual: str) -> None:
    if a == b:
        return
    key = (a, b)
    if key not in report.edges:
        report.edges.add(key)
        report.edge_witness[key] = (rel, line, qual)


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------


def _real_lock(ident: str) -> bool:
    """Pseudo idents (`mod:~x`) come from unresolvable lock-ish names."""
    return ":~" not in ident


def _rule_hvr201(report: Report) -> List[RaceFinding]:
    findings = []
    edges = {e for e in report.edges if _real_lock(e[0]) and _real_lock(e[1])}
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    seen_pairs: Set[FrozenSet[str]] = set()
    for a, b in sorted(edges):
        if (b, a) in edges:
            pair = frozenset((a, b))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            w1 = report.edge_witness[(a, b)]
            w2 = report.edge_witness[(b, a)]
            findings.append(RaceFinding(
                "HVR201", w1[0], w1[1],
                f"lock-order inversion: {a} -> {b} in {w1[2]} here, but "
                f"{b} -> {a} in {w2[2]} at {w2[0]}:{w2[1]}; one consistent "
                f"order is required"))
    # longer cycles via DFS (rare; bounded)
    return findings


def _rule_hvr202(report: Report) -> List[RaceFinding]:
    findings = []
    for mi in report.modules.values():
        for fi in mi.funcs.values():
            entry = {h: c for h, c in fi.entry_holds.items() if _real_lock(h)}
            for kind, name, line, held in fi.blocking:
                local = {h for h in held if _real_lock(h)}
                if local:
                    locks = ", ".join(sorted(local))
                    findings.append(RaceFinding(
                        "HVR202", mi.rel, line,
                        f"blocking {kind} call '{name}()' while holding "
                        f"{locks}; move it outside the critical section"))
                elif entry and not held:
                    # blame the chain root: the function that acquired the lock
                    h, chain = sorted(entry.items())[0]
                    root = chain[0]
                    root_qual, _, root_line = root.partition("@")
                    root_fi = report.funcs.get(root_qual)
                    root_rel = mi.rel
                    for m2 in report.modules.values():
                        if root_fi and root_fi.module == m2.modname:
                            root_rel = m2.rel
                            break
                    findings.append(RaceFinding(
                        "HVR202", root_rel, int(root_line or 0),
                        f"call chain reaches blocking {kind} call '{name}()' "
                        f"({mi.rel}:{line}) while {h} is held "
                        f"(via {' -> '.join(c.split('@')[0] for c in chain)})"))
    return findings


def _rule_hvr203(report: Report) -> List[RaceFinding]:
    findings = []
    skip_methods = {"__init__", "__new__", "__repr__", "__str__", "__del__",
                    "__enter__", "__exit__"}
    for mi in report.modules.values():
        for cname, ci in mi.classes.items():
            own_locks = set(ci.lock_attrs.values())
            if not own_locks:
                continue
            # field -> [(line, write, locked)]
            acc: Dict[str, List[Tuple[int, bool, bool]]] = {}
            for mname, fi in ci.methods.items():
                if mname in skip_methods:
                    continue
                entry = set(fi.entry_holds)
                for attr, line, write, held in fi.fields:
                    if attr in ci.lock_attrs:
                        continue
                    locked = bool((set(held) | entry) & own_locks)
                    acc.setdefault(attr, []).append((line, write, locked))
            for attr, uses in acc.items():
                locked_write = any(w and l for _, w, l in uses)
                locked_any = any(l for _, _, l in uses)
                unlocked_write = any(w and not l for _, w, l in uses)
                unlocked_any = any(not l for _, _, l in uses)
                if (locked_write and unlocked_any) or (locked_any and unlocked_write):
                    anchor = min(line for line, _, l in uses if not l)
                    lock_names = ", ".join(sorted(own_locks))
                    findings.append(RaceFinding(
                        "HVR203", mi.rel, anchor,
                        f"field '{cname}.{attr}' is guarded by {lock_names} in "
                        f"some methods but accessed without it here"))
        # module-global mutable containers vs module locks
        mod_locks = set(mi.locks.values())
        if mod_locks:
            gacc: Dict[str, List[Tuple[int, bool, bool]]] = {}
            for fi in mi.funcs.values():
                entry = set(fi.entry_holds)
                for gname, line, write, held in fi.globals_acc:
                    locked = bool((set(held) | entry) & mod_locks)
                    gacc.setdefault(gname, []).append((line, write, locked))
            for gname, uses in gacc.items():
                locked_write = any(w and l for _, w, l in uses)
                locked_any = any(l for _, _, l in uses)
                unlocked_write = any(w and not l for _, w, l in uses)
                unlocked_any = any(not l for _, _, l in uses)
                if (locked_write and unlocked_any) or (locked_any and unlocked_write):
                    anchor = min(line for line, _, l in uses if not l)
                    findings.append(RaceFinding(
                        "HVR203", mi.rel, anchor,
                        f"module global '{gname}' is mutated under "
                        f"{', '.join(sorted(mod_locks))} elsewhere but accessed "
                        f"without it here"))
    return findings


def _rule_hvr204(report: Report) -> List[RaceFinding]:
    findings = []
    # BFS from each signal handler through resolvable calls looking for an
    # unbounded acquire of a real lock.
    for mi in report.modules.values():
        for tok, reg_line in mi.signal_handlers:
            roots = _toks_to_funcs(report, mi, tok)
            seen: Set[str] = set()
            frontier = [(fi, [fi.qual]) for fi in roots]
            while frontier:
                fi, path = frontier.pop()
                if fi.qual in seen or len(path) > 10:
                    continue
                seen.add(fi.qual)
                fmi = report.modules.get(fi.module)
                if fmi is None:
                    continue
                for ident, line, bounded in fi.acquires:
                    if not bounded and _real_lock(ident):
                        findings.append(RaceFinding(
                            "HVR204", mi.rel, reg_line,
                            f"signal handler '{tok}' reaches unbounded acquire "
                            f"of {ident} at {fmi.rel}:{line} "
                            f"(via {' -> '.join(path)}); use "
                            f"acquire(timeout=...) on every handler path"))
                        break
                else:
                    for name, recv, line, held in fi.calls:
                        for q in _resolve_call(report, fmi, fi, name, recv):
                            callee = report.funcs.get(q)
                            if callee and callee.qual not in seen:
                                frontier.append((callee, path + [callee.qual]))
    return findings


def _toks_to_funcs(report: Report, mi: _ModuleInfo, tok: str) -> List[_FuncInfo]:
    out = []
    q = f"{mi.modname}:{tok}"
    if q in report.funcs:
        out.append(report.funcs[q])
    else:
        for fq, fi in report.funcs.items():
            if fi.module == mi.modname and fi.name == tok:
                out.append(fi)
    return out


def _rule_hvr205(report: Report) -> List[RaceFinding]:
    findings = []
    # 1. compute the shutdown-reachable closure by terminal call name,
    #    starting from basics.shutdown + all atexit roots.
    reachable_names: Set[str] = set()
    frontier: List[_FuncInfo] = []
    for mi in report.modules.values():
        for tok in mi.atexit_roots:
            frontier.extend(_toks_to_funcs(report, mi, tok))
        q = f"{mi.modname}:shutdown"
        if mi.modname.endswith("basics") and q in report.funcs:
            frontier.append(report.funcs[q])
    seen_q: Set[str] = set()
    while frontier:
        fi = frontier.pop()
        if fi.qual in seen_q:
            continue
        seen_q.add(fi.qual)
        for name, recv, line, held in fi.calls:
            if name in reachable_names:
                continue
            reachable_names.add(name)
            # generous closure: any package function with this terminal name
            for fq, cand in report.funcs.items():
                if cand.name == name and cand.qual not in seen_q:
                    frontier.append(cand)

    # 2. every Thread created in an init/arm path must have stop evidence in
    #    its owner scope reachable from shutdown.
    for mi in report.modules.values():
        for fi in mi.funcs.values():
            if not fi.thread_creates:
                continue
            if not any(p in fi.name.lower() for p in _INIT_PATH_NAMES):
                continue
            # owner scope: the class's methods, or the module's functions
            if fi.cls:
                ci = mi.classes.get(fi.cls)
                owner_funcs = list(ci.methods.values()) if ci else []
            else:
                owner_funcs = [f for f in mi.funcs.values() if f.cls is None]
            stoppers = [f for f in owner_funcs if f.has_stop_evidence]
            ok = any(
                f.name in reachable_names or f.qual in seen_q for f in stoppers)
            if not ok:
                for line, target in fi.thread_creates:
                    findings.append(RaceFinding(
                        "HVR205", mi.rel, line,
                        f"Thread created in '{fi.name}' has no stop/join "
                        f"reachable from basics.shutdown; register a stop "
                        f"path or join it on the shutdown path"))
    return findings


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------


def _rel_to_modname(rel: str) -> str:
    p = rel
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    parts = p.split("/")
    if parts and parts[0] == "horovod_tpu":
        parts = parts[1:]
    return ".".join(parts) if parts else "__root__"


def analyze_sources(sources: Dict[str, str],
                    rules: Optional[FrozenSet[str]] = None) -> Report:
    """Analyze a mapping of repo-relative path -> source text."""
    t0 = time.monotonic()
    rules = rules or ALL_RULES
    report = Report()
    bare: List[RaceFinding] = []

    for rel, source in sorted(sources.items()):
        lines = source.splitlines()
        head = "\n".join(lines[:2])
        if _SKIP_FILE_RE.search(head):
            continue
        report.n_files += 1
        mi = _ModuleInfo(rel=rel, modname=_rel_to_modname(rel), tree=None,
                         source_lines=lines)
        try:
            mi.tree = ast.parse(source)
        except SyntaxError as exc:
            report.findings.append(RaceFinding(
                "HVR999", rel, exc.lineno or 1, f"syntax error: {exc.msg}"))
            continue
        bare.extend(_collect_suppressions(lines, mi))
        _index_def_lines(mi)
        _pass_a(mi)
        report.modules[mi.modname] = mi

    for mi in report.modules.values():
        _pass_b(mi)
        for ident in mi.locks.values():
            report.lock_idents.add(ident)
        for ci in mi.classes.values():
            report.lock_idents.update(ci.lock_attrs.values())
        for line, ident in mi.lock_sites.items():
            report.lock_table[(mi.rel, line)] = ident
            report.lock_idents.add(ident)
        report.funcs.update(mi.funcs)

    _propagate_holds(report)
    _build_order_graph(report)

    raw: List[RaceFinding] = []
    if "HVR201" in rules:
        raw.extend(_rule_hvr201(report))
    if "HVR202" in rules:
        raw.extend(_rule_hvr202(report))
    if "HVR203" in rules:
        raw.extend(_rule_hvr203(report))
    if "HVR204" in rules:
        raw.extend(_rule_hvr204(report))
    if "HVR205" in rules:
        raw.extend(_rule_hvr205(report))

    # apply suppressions + dedupe
    by_mod = {mi.rel: mi for mi in report.modules.values()}
    seen: Set[Tuple[str, str, int, str]] = set()
    for f in raw:
        mi = by_mod.get(f.path)
        if mi is not None and _suppressed(mi, f.code, f.line):
            continue
        key = (f.code, f.path, f.line, f.message)
        if key in seen:
            continue
        seen.add(key)
        report.findings.append(f)

    report.findings.extend(bare)
    report.findings.sort(key=lambda f: (f.path, f.line, f.code))
    report.seconds = time.monotonic() - t0
    return report


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.append(os.path.join(root, fn))
    return out


def analyze_paths(paths: Sequence[str],
                  rules: Optional[FrozenSet[str]] = None,
                  base: Optional[str] = None) -> Report:
    base = base or os.getcwd()
    sources: Dict[str, str] = {}
    for fp in iter_py_files(paths):
        rel = os.path.relpath(fp, base)
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                sources[rel] = fh.read()
        except OSError:
            continue
    return analyze_sources(sources, rules=rules)


# --------------------------------------------------------------------------
# runtime witness
# --------------------------------------------------------------------------

_witness_installed = False
_witness_edges: Dict[Tuple[str, str], int] = {}
_witness_locks: Dict[int, str] = {}          # id(proxy) -> ident
_witness_guard = threading.Lock()            # real lock, captured pre-swap
_witness_tls = threading.local()
_orig_lock = None
_orig_rlock = None
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _WitnessProxy:
    """Wraps a real lock; records per-thread acquisition edges."""

    __slots__ = ("_inner", "_ident")

    def __init__(self, inner, ident: str) -> None:
        self._inner = inner
        self._ident = ident

    def _record(self) -> None:
        stack = getattr(_witness_tls, "stack", None)
        if stack is None:
            stack = _witness_tls.stack = []
        with _witness_guard:
            for held in stack:
                if held != self._ident:
                    key = (held, self._ident)
                    _witness_edges[key] = _witness_edges.get(key, 0) + 1
        stack.append(self._ident)

    def _unrecord(self) -> None:
        stack = getattr(_witness_tls, "stack", None)
        if stack and self._ident in stack:
            # remove last occurrence (RLock re-entry safe)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self._ident:
                    del stack[i]
                    break

    def acquire(self, *args, **kwargs):
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            self._record()
        return ok

    def release(self):
        self._inner.release()
        self._unrecord()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") else False

    # Condition() interop if someone wraps us
    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):     # RLock
            return self._inner._is_owned()
        # plain Lock: the stdlib Condition fallback (acquire(0) probe)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<WitnessProxy {self._ident} {self._inner!r}>"


def _site_ident() -> str:
    """Allocation-site ident for factory-created locks: '<rel>.py:<line>'."""
    f = sys._getframe(2)
    rel = os.path.relpath(f.f_code.co_filename, os.path.dirname(_PKG_DIR))
    return f"{rel}:{f.f_lineno}"


def _caller_in_package() -> bool:
    f = sys._getframe(2)
    try:
        return os.path.abspath(f.f_code.co_filename).startswith(_PKG_DIR + os.sep)
    except Exception:
        return False


def install_witness() -> None:
    """Swap threading.Lock/RLock for witness factories and wrap existing
    package module-global locks in proxies."""
    global _witness_installed, _orig_lock, _orig_rlock
    if _witness_installed:
        return
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock

    def lock_factory(*a, **kw):
        inner = _orig_lock(*a, **kw)
        if _caller_in_package():
            proxy = _WitnessProxy(inner, _site_ident())
            _witness_locks[id(proxy)] = proxy._ident
            return proxy
        return inner

    def rlock_factory(*a, **kw):
        inner = _orig_rlock(*a, **kw)
        if _caller_in_package():
            proxy = _WitnessProxy(inner, _site_ident())
            _witness_locks[id(proxy)] = proxy._ident
            return proxy
        return inner

    threading.Lock = lock_factory          # type: ignore[assignment]
    threading.RLock = rlock_factory        # type: ignore[assignment]

    # sweep already-imported package modules for module-global locks
    lock_types = (type(_orig_lock()), type(_orig_rlock()))
    for modname, mod in list(sys.modules.items()):
        if not modname.startswith("horovod_tpu") or mod is None:
            continue
        if modname.startswith("horovod_tpu.analysis"):
            # never wrap the analyzer's own guard (_witness_guard must
            # stay a real lock or _record would re-enter itself)
            continue
        short = _strip_pkg(modname)
        for attr in list(vars(mod)):
            obj = getattr(mod, attr, None)
            if isinstance(obj, lock_types):
                ident = f"{short}:{attr}"
                proxy = _WitnessProxy(obj, ident)
                _witness_locks[id(proxy)] = ident
                setattr(mod, attr, proxy)
    _witness_installed = True


def uninstall_witness() -> None:
    """Restore threading factories.  Swapped module globals keep their
    proxies (they still delegate to the original lock, so behaviour is
    unchanged); edges stop accumulating once factories are restored."""
    global _witness_installed
    if not _witness_installed:
        return
    threading.Lock = _orig_lock            # type: ignore[assignment]
    threading.RLock = _orig_rlock          # type: ignore[assignment]
    _witness_installed = False


def witness_edges() -> Dict[Tuple[str, str], int]:
    with _witness_guard:
        return dict(_witness_edges)


def reset_witness_edges() -> None:
    with _witness_guard:
        _witness_edges.clear()


def dump_witness(path: str) -> None:
    with _witness_guard:
        rows = [{"held": a, "acquired": b, "count": n}
                for (a, b), n in sorted(_witness_edges.items())]
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    os.replace(tmp, path)


def load_witness(path: str) -> Dict[Tuple[str, str], int]:
    out: Dict[Tuple[str, str], int] = {}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            out[(row["held"], row["acquired"])] = int(row.get("count", 1))
    return out


def maybe_install_from_env() -> None:
    if os.environ.get("HVD_LOCK_WITNESS", "").strip() in ("1", "true", "on"):
        install_witness()


def _canonical(report: Report, ident: str) -> Optional[str]:
    """Map a witness ident to a static lock ident.

    Witness idents are either already canonical (`module:attr` from the
    module-global sweep) or allocation sites (`horovod_tpu/x/y.py:LINE`).
    """
    if ident in report.lock_idents:
        return ident
    if ":" in ident and ident.rsplit(":", 1)[1].isdigit():
        rel, _, line = ident.rpartition(":")
        return report.lock_table.get((rel, int(line)))
    return None


def cross_check(report: Report,
                edges: Dict[Tuple[str, str], int]) -> List[RaceFinding]:
    """Assert every runtime acquisition edge is predicted statically.

    Returns findings (empty list == green).  HVR210 = edge observed at
    runtime but absent from the static may-hold-before graph (an analyzer
    gap).  HVR211 = runtime lock that static analysis never resolved.
    """
    findings: List[RaceFinding] = []
    for (held, acquired), count in sorted(edges.items()):
        ch = _canonical(report, held)
        ca = _canonical(report, acquired)
        if ch is None:
            findings.append(RaceFinding(
                "HVR211", "witness", 0,
                f"runtime lock '{held}' unknown to static analysis"))
            continue
        if ca is None:
            findings.append(RaceFinding(
                "HVR211", "witness", 0,
                f"runtime lock '{acquired}' unknown to static analysis"))
            continue
        if ch == ca:
            continue
        if (ch, ca) not in report.edges:
            findings.append(RaceFinding(
                "HVR210", "witness", 0,
                f"runtime edge {ch} -> {ca} (observed {count}x) not in the "
                f"static may-hold-before graph; the analyzer missed a hold "
                f"propagation path"))
    return findings


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdrace",
        description="whole-package lock-graph analyzer (HVR201-HVR205)")
    parser.add_argument("paths", nargs="*", default=["horovod_tpu"])
    parser.add_argument("--rules", default=",".join(sorted(ALL_RULES)),
                        help="comma-separated rule ids to run")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--witness", metavar="JSONL",
                        help="cross-check a witness log against the static graph")
    args = parser.parse_args(argv)

    rules = frozenset(r.strip() for r in args.rules.split(",") if r.strip())
    report = analyze_paths(args.paths, rules=rules)

    findings = list(report.findings)
    if args.witness:
        findings.extend(cross_check(report, load_witness(args.witness)))

    if args.format == "json":
        doc = report.to_dict()
        doc["findings"] = [f.to_dict() for f in findings]
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        print(f"hvdrace: {report.n_files} files, {len(report.lock_idents)} locks, "
              f"{len(report.edges)} edges, {len(findings)} findings "
              f"({report.seconds:.2f}s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
