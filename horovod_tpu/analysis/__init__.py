"""Static collective-correctness analysis + runtime lint (hvdlint).

Two halves (ISSUE 9 / docs/static_analysis.md):

- :func:`check_program` (exported as ``hvd.check_program``) — abstract-eval
  a step function per simulated rank and diff the collective sequences for
  desync hazards before the run, each finding carrying the flight
  recorder's ``(op, ps, seq, sig)`` identity;
- :mod:`horovod_tpu.analysis.lint` — AST-based codebase lint
  (``python -m horovod_tpu.analysis.lint``, ``scripts/lint.py``) for the
  bug classes previous PRs fixed by hand.
"""

from horovod_tpu.analysis.events import (  # noqa: F401
    CollectiveEvent, sequence_hash,
)
from horovod_tpu.analysis.findings import Finding  # noqa: F401
from horovod_tpu.analysis.program import (  # noqa: F401
    CheckReport, check_program, cross_check,
)

_LINT_EXPORTS = ("LintFinding", "declared_knobs", "lint_paths",
                 "lint_source")


def __getattr__(name):
    # Lazy: `python -m horovod_tpu.analysis.lint` imports this package
    # first, and an eager `from .lint import ...` would double-import the
    # module it is about to execute (runpy RuntimeWarning).
    if name in _LINT_EXPORTS:
        from horovod_tpu.analysis import lint
        return getattr(lint, name)
    raise AttributeError(name)
