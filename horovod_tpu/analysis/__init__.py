"""Static collective-correctness analysis + runtime lint (hvdlint).

Four pieces (ISSUEs 9/11, docs/static_analysis.md):

- :func:`check_program` (exported as ``hvd.check_program``) — abstract-eval
  a step function per simulated rank and diff the collective sequences for
  desync hazards before the run, each finding carrying the flight
  recorder's ``(op, ps, seq, sig)`` identity;
- :func:`check_elastic` — the elastic world-transition model checker:
  re-run the per-rank abstract eval across a resize ladder and diff the
  streams ACROSS generations (HVP110 world_dependent_signature);
- :mod:`horovod_tpu.analysis.cost` (hvdcost) — the static per-link-tier
  communication cost model: per-step ``bytes_by_tier{ici,dcn}``, the
  HVP111 DCN budget gate, and :func:`cross_check_bytes` against the
  runtime ``wire_bytes_total`` counters
  (``python -m horovod_tpu.analysis.cost``);
- :mod:`horovod_tpu.analysis.lint` — AST-based codebase lint
  (``python -m horovod_tpu.analysis.lint``, ``scripts/lint.py``) for the
  bug classes previous PRs fixed by hand.
"""

from horovod_tpu.analysis.events import (  # noqa: F401
    CollectiveEvent, sequence_hash,
)
from horovod_tpu.analysis.findings import Finding  # noqa: F401
from horovod_tpu.analysis.program import (  # noqa: F401
    CheckReport, ElasticReport, check_elastic, check_program, cross_check,
)

_LINT_EXPORTS = ("LintFinding", "declared_knobs", "lint_paths",
                 "lint_source")
_COST_EXPORTS = ("CostReport", "check_cost", "cost_report",
                 "cross_check_bytes", "resolve_slices")


def __getattr__(name):
    # Lazy: `python -m horovod_tpu.analysis.lint` / `.cost` imports this
    # package first, and an eager `from .lint import ...` would
    # double-import the module it is about to execute (runpy
    # RuntimeWarning).
    if name in _LINT_EXPORTS:
        from horovod_tpu.analysis import lint
        return getattr(lint, name)
    if name in _COST_EXPORTS:
        from horovod_tpu.analysis import cost
        return getattr(cost, name)
    raise AttributeError(name)
