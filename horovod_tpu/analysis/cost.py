"""hvdcost: static per-link-tier communication cost model.

Builds on :func:`horovod_tpu.analysis.program.check_program`'s per-rank
abstract eval: every predicted collective event is priced in BYTES ON THE
WIRE — with the exact formulas the runtime meters (``wire_bytes_total``:
``ops/wire.py`` quantized-exchange accounting incl. scales and padding,
the RS+AG double-crossing for full-precision allreduces) — and every leg
is classified against the slice hierarchy from ``common/topology.py``
(``num_slices`` / ``mesh_dcn`` / forced ``HOROVOD_MESH_SLICES``) as
``ici`` (in-slice interconnect) or ``dcn`` (the scarce cross-slice tier,
where arXiv 2510.20171 locates the bandwidth cliff at 100k-GPU scale).

Tier classification rules (rank-major layout, ``slice = rank //
slice_size`` — the same reshape ``_build_dcn_mesh`` materializes):

- **ring-scheduled legs** (allreduce RS+AG, allgather, broadcast,
  reducescatter; jit ``psum``/``all_gather``/``reduce_scatter``/
  ``ppermute``): the DCN share is the fraction of ring hops that cross a
  slice boundary — ``S/n`` for a world-spanning set over ``S`` slices.
- **all-to-all-scheduled legs** (eager ``alltoall``, jit ``all_to_all``,
  and the FIRST leg of the block-scaled quantized exchange): the DCN
  share is the fraction of destination rows living in a foreign slice —
  ``1 - slice_size/n`` for a world-spanning set.
- jit collectives over the ``cross`` axis of the DCN mesh are pure DCN;
  over ``local`` pure ICI; unknown axes are assumed rank-major contiguous
  groups (in-slice when the group fits inside one slice).

Totals are CONSERVED across the split (the tier fractions of each leg sum
to 1), so ``sum(bytes_by_tier.values())`` equals the flat-schedule wire
estimate the runtime counters accumulate — which is what makes
:func:`cross_check_bytes` a tight (<5 %) comparison instead of a
hand-wave. The 2-level **hierarchical what-if** (local RS -> cross-slice
-> local AG, the fork's ``NCCLTorusAllreduce`` shape, ROADMAP item 3) is
reported alongside: same ICI volume, DCN divided by the slice width —
the target the future hierarchical allreduce will be validated against.

``python -m horovod_tpu.analysis.cost`` renders the per-tier table and
the HVP111 budget verdict with lint-style exit codes (see
``docs/static_analysis.md``); ``scripts/lint.py --cost`` runs it behind
the self-lint gate.
"""

import dataclasses
import json

from horovod_tpu.analysis import jaxpr_walk
from horovod_tpu.analysis.findings import (ERROR, INFO, Finding,
                                           sort_findings)
from horovod_tpu.common.topology import (CROSS_AXIS, LOCAL_AXIS,
                                         slice_layout, slice_of_rank)
from horovod_tpu.ops import wire as _wire

# Leg schedules per op label (flight labels + canonical jit primitives).
_A2A_OPS = {"alltoall", "all_to_all"}
_GATHER_LIKE = {"allgather", "broadcast", "reducescatter", "all_gather",
                "reduce_scatter", "ppermute", "pgather"}


def _is_float_name(dtype_str):
    s = str(dtype_str)
    return "float" in s or "bfloat" in s


def resolve_slices(world_size, num_slices=None):
    """``(num_slices, slice_size)`` for the cost model: an explicit
    ``num_slices`` wins, then the live topology's DCN hierarchy (when
    initialized at this world size), then the forced
    ``HOROVOD_MESH_SLICES`` knob — each subject to the mesh construction's
    own divisibility rules (:func:`topology.slice_layout`)."""
    if num_slices:
        return slice_layout(world_size, num_slices)
    try:
        from horovod_tpu.common import basics
        if basics.is_initialized() and basics._sim_world is None:
            topo = basics.topology()
            if topo.size == int(world_size) and topo.num_slices > 1:
                return slice_layout(world_size, topo.num_slices)
    except Exception:  # noqa: BLE001 — fall through to the env layout
        pass
    return slice_layout(world_size)


def _member_ranks(event, world_size, num_slices, slice_size):
    """Representative member-rank list for one event's exchange group —
    what the tier fractions are computed over. Eager sets use their real
    ranks; jit axes map onto the DCN mesh's (cross, local) structure, and
    unknown user axes are assumed rank-major contiguous."""
    if event.origin == "jit":
        axes = event.ps[len("axis:"):].split(",") \
            if event.ps.startswith("axis:") else []
        if axes == [CROSS_AXIS]:
            return [i * slice_size for i in range(num_slices)]
        if axes == [LOCAL_AXIS]:
            p = event.group_size(None) or slice_size
            return list(range(min(int(p), slice_size)))
        p = event.group_size(world_size) or world_size
        return list(range(min(int(p), world_size)))
    if event.ps_ranks:
        return [r for r in event.ps_ranks if r < world_size] \
            or list(event.ps_ranks)
    n = event.group_size(world_size) or world_size
    return list(range(min(int(n), world_size)))


# The ring / all-to-all slice-boundary fractions live in the WIRE tier now
# (one definition for this static classifier AND the runtime counters'
# default split — metrics.record_wire); re-exported here for the existing
# call sites.
_ring_dcn_fraction = _wire.ring_dcn_fraction
_a2a_dcn_fraction = _wire.a2a_dcn_fraction


def _hier_for_event(event, config, num_slices, use_registry=True):
    """Mirror of the runtime's hierarchical-dispatch verdict
    (``collective_ops._eager_hier_for``) for one predicted eager/fused
    allreduce: the effective cross-leg wire string when the dispatch
    layer would decompose this event (local RS -> cross-slice -> local
    AG), else None for the flat path. Shares the strategy registry /
    ``HOROVOD_HIERARCHICAL_DISPATCH`` chain and the float-Sum/Average
    single-dtype gates, so the analyzer can never predict a schedule the
    dispatch layer would refuse."""
    if event.op != "allreduce" or event.origin == "jit" \
            or event.ps != "global" or num_slices <= 1:
        return None
    default = "hier_qcross" \
        if getattr(config, "hierarchical_dispatch", False) else ""
    strategy = _wire.dispatch_strategy_for(event.ps, default) \
        if use_registry else default
    if strategy not in ("hier", "hier_qcross"):
        return None
    if event.red_op not in (None, "Sum", "Average"):
        return None
    dtypes = set(event.dtypes)
    if len(dtypes) != 1 or not all(_is_float_name(d) for d in dtypes):
        return None
    if strategy != "hier_qcross":
        return ""
    if use_registry:
        return _wire.cross_wire_for(event.ps, config)
    return _wire.resolve_wire_dtype(
        getattr(config, "wire_dtype_dcn", "")
        or getattr(config, "wire_dtype", ""))


def _a2a_hier_for_event(event, config, num_slices, use_registry=True):
    """Mirror of the runtime's hierarchical-ALLTOALL verdict
    (``collective_ops._eager_a2a_hier_for``) for one predicted eager
    alltoall: the effective cross-leg wire string when the dispatch layer
    would decompose this event (slice-local a2a -> cross-slice a2a), else
    None for the flat path. Shares the a2a strategy registry /
    ``HOROVOD_HIERARCHICAL_ALLTOALL`` chain and the single-tensor
    equal-splits gates — and, deliberately, the no-inherit cross-wire
    policy: ``alltoall_cross_dtype`` only, never the allreduce wire
    knobs."""
    if event.op != "alltoall" or event.origin == "jit" \
            or event.ps != "global" or num_slices <= 1:
        return None
    default = "hier_qcross" \
        if getattr(config, "hierarchical_alltoall", False) else ""
    strategy = _wire.alltoall_strategy_for(event.ps, default) \
        if use_registry else default
    if strategy not in ("hier", "hier_qcross"):
        return None
    if len(event.shapes) != 1:
        return None
    shape = event.shapes[0]
    n = int(shape[0]) if shape else 0
    if len(shape) < 2 or n < 2 or int(shape[1]) % n != 0:
        return None
    if strategy != "hier_qcross":
        return ""
    if use_registry:
        return _wire.alltoall_cross_wire_for(event.ps, config)
    return _wire.resolve_wire_dtype(
        getattr(config, "alltoall_cross_dtype", ""))


def _event_legs(event, world_size, config, use_registry=True,
                num_slices=1):
    """Transfer legs for one predicted event: a list of ``(bytes,
    schedule, dtype_label)`` with schedule in ``{"ring", "a2a", "ici",
    "dcn"}`` — the SAME byte totals the runtime's
    ``wire_bytes_total{dtype,tier}`` counter would accumulate for this
    dispatch (``_timeline_op`` / ``_DispatchPlan`` / the fused flush),
    split per leg so the tier classifier can price each leg's schedule
    separately. ``ici``/``dcn`` legs are tier-EXPLICIT (the hierarchical
    decomposition's local and cross legs — no fraction applied);
    ``ring``/``a2a`` legs are classified by the slice-boundary fractions.
    ``use_registry=False`` prices against the config knobs alone
    (counterfactual "as if" pricing), ignoring live registry entries.
    Returns ``[]`` for zero-byte events (barrier)."""
    if event.op == "barrier" or not event.shapes:
        return []
    dtypes = event.dtypes
    width = jaxpr_walk.dtype_width(dtypes[0]) if dtypes else 4
    if event.origin == "jit":
        p = int(event.group_size(world_size) or world_size)
        e = event.per_rank_elems()
        if event.op in ("psum", "pmin", "pmax"):
            # participants x payload x both internal legs — the global-
            # payload convention the eager allreduce accounting uses.
            return [(2 * p * e * width, "ring", str(dtypes[0]))]
        sched = "a2a" if event.op in _A2A_OPS else "ring"
        return [(p * e * width, sched, str(dtypes[0]))]
    n = int(event.group_size(world_size) or world_size)
    if event.op == "allreduce":
        flat_len = event.per_rank_elems()
        cfg_wire = getattr(config, "wire_dtype", "")
        req = _wire.wire_dtype_for(event.ps, cfg_wire) if use_registry \
            else _wire.resolve_wire_dtype(cfg_wire)
        all_float = all(_is_float_name(d) for d in dtypes)
        hier_cross = _hier_for_event(event, config, num_slices,
                                     use_registry)
        if hier_cross is not None:
            # The hierarchical dispatch tier: local RS + AG at the
            # payload dtype (explicit ici), the cross-slice allreduce at
            # the per-tier wire (explicit dcn) — the same
            # wire.hierarchical_wire_bytes integers the runtime records,
            # which is what makes cross_check_bytes exact. One fused
            # wrinkle mirrored from _fused_program's cast_wire: a 16-bit
            # cast wire applies to the EXACT-cross strategy ("torus") —
            # every leg then moves the cast dtype — while torus_qcross
            # keeps the payload dtype on its ICI legs by design.
            label, w = str(dtypes[0]), width
            if event.origin == "fused" and not hier_cross and all_float \
                    and req in ("float16", "bfloat16"):
                label, w = req, 2
            h = _wire.hierarchical_wire_bytes(flat_len, n, num_slices,
                                              w,
                                              cross_wire=hier_cross)
            return [(h["ici"], "ici", label),
                    (h["dcn"], "dcn", h["cross_label"] or label)]
        quant = _wire.quantized_label(req)
        sum_avg = event.red_op in (None, "Sum", "Average")
        if quant and _wire.quantized_eligible(flat_len, n, all_float,
                                              sum_avg):
            leg = _wire.exchange_leg_bytes(flat_len, n)
            # First leg: AllToAll of the 1-byte shards (+ scales);
            # second: AllGather of the reduced shards (+ scales).
            return [(leg, "a2a", quant), (leg, "ring", quant)]
        if event.origin == "fused" and req in ("float16", "bfloat16") \
                and all_float:
            # The fusion runtime casts float buckets to the 16-bit wire;
            # sync eager dispatches never cast (they record the payload
            # dtype), matching the runtime's accounting exactly.
            return [(2 * n * flat_len * 2, "ring", req)]
        return [(2 * event.nbytes, "ring", str(dtypes[0]))]
    if event.op == "alltoall":
        hier_cross = _a2a_hier_for_event(event, config, num_slices,
                                         use_registry)
        if hier_cross is not None:
            # The hierarchical a2a tier: slice-local exchange at the
            # payload dtype (explicit ici), cross-slice exchange on the
            # expert cross wire split by its own (S-1)/S foreign-slice
            # fraction — the same wire.hierarchical_a2a_bytes integers
            # _HierAlltoallPlan records, which is what makes
            # cross_check_bytes exact. Non-float payloads keep the cross
            # leg exact (the runtime verdict's float gate).
            all_float = all(_is_float_name(d) for d in dtypes)
            h = _wire.hierarchical_a2a_bytes(
                event.per_rank_elems(), n, num_slices, width,
                cross_wire=hier_cross if all_float else "")
            label = str(dtypes[0])
            cross_label = h["cross_label"] or label
            ct = h["cross_tiers"]
            return [(h["local"], "ici", label),
                    (ct["ici"], "ici", cross_label),
                    (ct["dcn"], "dcn", cross_label)]
    sched = "a2a" if event.op in _A2A_OPS else "ring"
    return [(event.nbytes, sched, str(dtypes[0]))]


@dataclasses.dataclass
class EventCost:
    """One predicted event priced and tier-classified."""

    op: str
    ps: str
    seq: int
    origin: str
    dtype: str          # effective wire label (the runtime counter label)
    wire_bytes: int     # one dispatch, both legs
    ici_bytes: int      # total across repeats
    dcn_bytes: int      # total across repeats
    repeat: int         # 0 = unknown trip count (totals are lower bounds)

    @property
    def total_bytes(self):
        return self.ici_bytes + self.dcn_bytes

    @property
    def exact(self):
        return self.repeat != 0

    def describe(self):
        rep = "" if self.repeat == 1 \
            else (" x? (lower bound)" if self.repeat == 0
                  else f" x{self.repeat}")
        return (f"{self.op}[{self.ps}] seq={self.seq} dtype={self.dtype} "
                f"wire={self.wire_bytes}B ici={self.ici_bytes}B "
                f"dcn={self.dcn_bytes}B{rep} ({self.origin})")


@dataclasses.dataclass
class CostReport:
    """Result of :func:`cost_report`."""

    world_size: int
    num_slices: int
    slice_size: int
    rows: list                     # [EventCost]
    bytes_by_tier: dict            # {"ici": B, "dcn": B} — all rows
    bytes_by_dtype: dict           # eager+fused rows (runtime-metered)
    jit_bytes_by_dtype: dict       # static-only jit rows
    hierarchical: dict             # 2-level what-if {"ici","dcn",...}
    time_estimate: dict            # roofline.tier_time_estimate(...)
    findings: list
    exact: bool                    # False when any repeat is unbounded
    dcn_budget_bytes: int = 0
    # Runtime-metered (eager+fused) subset of bytes_by_tier — what the
    # live wire_bytes_total{tier} counters accumulate per step; the
    # per-tier side of cross_check_bytes diffs against THIS.
    runtime_bytes_by_tier: dict = dataclasses.field(default_factory=dict)
    # Control-plane load prediction: negotiation rounds per step and the
    # per-role KV RPC counts of one round under the resolved strategy
    # (control_plane.exchange_plan — the same layout math the runtime
    # decomposes with), so the static model predicts host-side fan-out
    # alongside wire bytes.
    control_plane: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self):
        return not any(f.severity == ERROR for f in self.findings)

    def errors(self):
        return [f for f in self.findings if f.severity == ERROR]

    def to_dict(self):
        return {
            "world_size": self.world_size,
            "num_slices": self.num_slices,
            "slice_size": self.slice_size,
            "bytes_by_tier": dict(self.bytes_by_tier),
            "runtime_bytes_by_tier": dict(self.runtime_bytes_by_tier),
            "bytes_by_dtype": dict(self.bytes_by_dtype),
            "jit_bytes_by_dtype": dict(self.jit_bytes_by_dtype),
            "hierarchical": dict(self.hierarchical),
            "time_estimate": dict(self.time_estimate),
            "control_plane": dict(self.control_plane),
            "exact": self.exact,
            "dcn_budget_bytes": self.dcn_budget_bytes,
            "rows": [dataclasses.asdict(r) for r in self.rows],
            "findings": [f.to_dict() for f in self.findings],
            "ok": self.ok,
        }

    def render(self):
        bound = "" if self.exact else " (lower bound: unbounded repeats)"
        lines = [f"hvdcost: world={self.world_size} "
                 f"slices={self.num_slices} "
                 f"(slice_size={self.slice_size})"]
        lines.append(f"  predicted wire traffic per step (rank 0), "
                     f"{len(self.rows)} event(s):")
        for r in self.rows[:32]:
            lines.append(f"    {r.describe()}")
        if len(self.rows) > 32:
            lines.append(f"    ... {len(self.rows) - 32} more")
        lines.append(f"  bytes_by_tier: ici={self.bytes_by_tier['ici']} "
                     f"dcn={self.bytes_by_tier['dcn']}{bound}")
        if self.runtime_bytes_by_tier:
            lines.append(
                "  runtime bytes_by_tier (cross-checkable vs "
                f"wire_bytes_total{{tier}}): "
                f"ici={self.runtime_bytes_by_tier.get('ici', 0)} "
                f"dcn={self.runtime_bytes_by_tier.get('dcn', 0)}")
        if self.bytes_by_dtype:
            lines.append("  bytes_by_dtype (cross-checkable vs "
                         "wire_bytes_total): "
                         + " ".join(f"{k}={v}" for k, v in
                                    sorted(self.bytes_by_dtype.items())))
        if self.jit_bytes_by_dtype:
            lines.append("  jit bytes_by_dtype (static-only estimate): "
                         + " ".join(f"{k}={v}" for k, v in
                                    sorted(self.jit_bytes_by_dtype.items())))
        if self.num_slices > 1:
            h = self.hierarchical
            lines.append(
                f"  hierarchical what-if (local RS -> cross-slice -> "
                f"local AG): ici={h['ici']} dcn={h['dcn']} "
                f"(DCN x{h['dcn_vs_flat']:.3f} of the flat schedule)")
        cp = self.control_plane
        if cp:
            per = cp.get("per_round", {})
            if cp.get("strategy") == "hier":
                detail = (f"member gets {cp['member_gets']} "
                          f"(flat would be {cp['flat_gets']}), leader "
                          f"gets {cp['leader_gets']} (local "
                          f"{per.get('leader_local_gets', 0)} + cross "
                          f"{per.get('leader_cross_gets', 0)} per round)")
            else:
                detail = f"per-rank gets {cp['member_gets']}"
            lines.append(
                f"  control plane ({cp.get('strategy')}): "
                f"{cp.get('rounds_per_step', 0)} negotiation round(s)/"
                f"step — {detail}")
        t = self.time_estimate
        if t.get("ici_s") is not None or t.get("dcn_s") is not None:
            est = " [placeholder peaks]" if t.get("estimate") else ""
            lines.append(
                "  roofline lower bound: "
                f"ici {1e6 * (t.get('ici_s') or 0.0):.1f}us, "
                f"dcn {1e6 * (t.get('dcn_s') or 0.0):.1f}us -> "
                f"{t.get('bound')}-bound (chip={t.get('chip')}){est}")
        if self.dcn_budget_bytes:
            verdict = "EXCEEDED" \
                if self.bytes_by_tier["dcn"] > self.dcn_budget_bytes \
                else "OK"
            lines.append(f"  dcn budget: {self.bytes_by_tier['dcn']} B "
                         f"vs {self.dcn_budget_bytes} B -> {verdict}")
        if not self.findings:
            lines.append("  findings: none")
        else:
            lines.append(f"  findings: {len(self.findings)}")
            for f in sort_findings(self.findings):
                lines.append(f"    {f.render()}")
        return "\n".join(lines)


def cost_report(report, *, config=None, num_slices=None,
                dcn_budget_bytes=None, use_registry=True):
    """Price a :class:`~horovod_tpu.analysis.program.CheckReport`'s
    predicted collective stream per link tier. ``num_slices`` defaults to
    the live topology / forced ``HOROVOD_MESH_SLICES`` hierarchy;
    ``dcn_budget_bytes`` defaults to the ``HOROVOD_DCN_BYTES_BUDGET``
    knob (0 = no budget); ``use_registry=False`` prices against
    ``config.wire_dtype`` alone, ignoring live per-process-set wire pins
    (counterfactual what-if pricing — the bench's ``static_cost`` record
    uses it). Returns a :class:`CostReport` whose per-dtype totals use
    the runtime's own wire-byte formulas, so :func:`cross_check_bytes`
    can diff them against the real ``wire_bytes_total{dtype}``
    counters."""
    if config is None:
        from horovod_tpu.common import basics
        from horovod_tpu.common.config import Config
        try:
            config = basics.config()
        except Exception:  # noqa: BLE001 — uninitialized analysis is fine
            config = Config()
    world = report.world_size
    n_slices, slice_size = resolve_slices(world, num_slices)
    if dcn_budget_bytes is None:
        dcn_budget_bytes = int(getattr(config, "dcn_bytes_budget", 0) or 0)
    events = report.sequences[report.ranks[0]]
    rows = []
    tier = {"ici": 0, "dcn": 0}
    runtime_tier = {"ici": 0, "dcn": 0}
    by_dtype, jit_by_dtype = {}, {}
    hier = {"ici": 0, "dcn": 0}
    findings = []
    seen_unbounded = set()
    for e in events:
        legs = _event_legs(e, world, config, use_registry, n_slices)
        if not legs:
            continue
        members = _member_ranks(e, world, n_slices, slice_size)
        ring_f = _ring_dcn_fraction(members, slice_size) \
            if n_slices > 1 else 0.0
        a2a_f = _a2a_dcn_fraction(members, slice_size) \
            if n_slices > 1 else 0.0
        occurrences = max(e.repeat, 1)
        wire_bytes = sum(b for b, _, _ in legs)
        ici = dcn = 0
        for leg_bytes, sched, leg_dtype in legs:
            # Tier-explicit legs (the hierarchical decomposition) book
            # whole; ring/a2a legs split on the slice-boundary fraction.
            frac = {"ici": 0.0, "dcn": 1.0}.get(
                sched, a2a_f if sched == "a2a" else ring_f)
            leg_total = leg_bytes * occurrences
            leg_dcn = int(round(leg_total * frac))
            dcn += leg_dcn
            ici += leg_total - leg_dcn
            target = jit_by_dtype if e.origin == "jit" else by_dtype
            target[leg_dtype] = target.get(leg_dtype, 0) + leg_total
        label = "+".join(dict.fromkeys(d for _, _, d in legs))
        rows.append(EventCost(
            op=e.op, ps=e.ps, seq=e.seq, origin=e.origin, dtype=label,
            wire_bytes=wire_bytes, ici_bytes=ici, dcn_bytes=dcn,
            repeat=e.repeat))
        tier["ici"] += ici
        tier["dcn"] += dcn
        if e.origin != "jit":
            # The runtime-metered subset: what wire_bytes_total{tier}
            # accumulates per step (jit rows record at trace time only).
            runtime_tier["ici"] += ici
            runtime_tier["dcn"] += dcn
        # 2-level what-if: an allreduce over a multi-slice group priced
        # AS IF dispatched hierarchically — local RS + local AG on ICI
        # (the full flat volume), only the slice-reduced shards (on the
        # per-tier cross wire) over DCN: flat total divided by the slice
        # width, via the SAME wire.hierarchical_wire_bytes integers the
        # runtime records, so the what-if is exactly the counters the
        # hierarchical dispatch tier produces. Non-allreduce exchanges
        # keep their flat split (their hierarchical decompositions are
        # workload-specific).
        slices_spanned = len({slice_of_rank(r, slice_size)
                              for r in members}) if members else 1
        if e.op in ("allreduce", "psum") and slices_spanned > 1:
            width = jaxpr_walk.dtype_width(e.dtypes[0]) if e.dtypes else 4
            cross = ""
            if e.origin != "jit":
                cross = _wire.cross_wire_for(e.ps, config) if use_registry \
                    else _wire.resolve_wire_dtype(
                        getattr(config, "wire_dtype_dcn", "")
                        or getattr(config, "wire_dtype", ""))
            hh = _wire.hierarchical_wire_bytes(
                e.per_rank_elems(), len(members), slices_spanned, width,
                cross_wire=cross)
            hier["ici"] += hh["ici"] * occurrences
            hier["dcn"] += hh["dcn"] * occurrences
        elif e.op == "alltoall" and e.origin != "jit" \
                and slices_spanned > 1:
            # The a2a twin: an eager alltoall priced AS IF dispatched
            # hierarchically (slice-local leg all-ICI, cross leg on the
            # expert cross wire split (S-1)/S) — the same
            # wire.hierarchical_a2a_bytes integers _HierAlltoallPlan
            # records, so when the tier is armed the what-if IS the
            # as-dispatched prediction. Cross dtype resolves through the
            # a2a chain only (alltoall_cross_dtype — activations never
            # inherit the allreduce wire knobs).
            width = jaxpr_walk.dtype_width(e.dtypes[0]) if e.dtypes else 4
            all_float = all(_is_float_name(d) for d in e.dtypes)
            cross = ""
            if all_float:
                cross = _wire.alltoall_cross_wire_for(e.ps, config) \
                    if use_registry else _wire.resolve_wire_dtype(
                        getattr(config, "alltoall_cross_dtype", ""))
            hh = _wire.hierarchical_a2a_bytes(
                e.per_rank_elems(), len(members), slices_spanned, width,
                cross_wire=cross)
            hier["ici"] += hh["ici"] * occurrences
            hier["dcn"] += hh["dcn"] * occurrences
        else:
            hier["ici"] += ici
            hier["dcn"] += dcn
        if e.repeat == 0 and (e.op, e.ps) not in seen_unbounded:
            seen_unbounded.add((e.op, e.ps))
            findings.append(Finding(
                code="HVP112", severity=INFO,
                message=(f"{e.op} on {e.ps} sits under a while loop with "
                         "no static trip count — its bytes are counted "
                         "ONCE, so per-tier totals are lower bounds, not "
                         "exact"),
                op=e.op, ps=e.ps, seq=e.seq))
    exact = not seen_unbounded
    hier["dcn_vs_flat"] = (hier["dcn"] / tier["dcn"]) if tier["dcn"] else 1.0
    if dcn_budget_bytes and tier["dcn"] > dcn_budget_bytes:
        findings.append(Finding(
            code="HVP111", severity=ERROR,
            message=(f"tier budget exceeded: predicted per-step DCN "
                     f"traffic {tier['dcn']} B > declared budget "
                     f"{dcn_budget_bytes} B (HOROVOD_DCN_BYTES_BUDGET) — "
                     "quantize the cross-slice leg (wire tier), shrink "
                     "the payload, or raise the budget"
                     + ("" if exact else "; note the prediction is "
                        "itself a LOWER bound (unbounded repeats)")),
            ps="dcn"))
    from horovod_tpu.profile import roofline
    t = roofline.tier_time_estimate(tier, world, n_slices)
    return CostReport(
        world_size=world, num_slices=n_slices, slice_size=slice_size,
        rows=rows, bytes_by_tier=tier, bytes_by_dtype=by_dtype,
        jit_bytes_by_dtype=jit_by_dtype, hierarchical=hier,
        time_estimate=t, findings=sort_findings(findings), exact=exact,
        dcn_budget_bytes=dcn_budget_bytes,
        runtime_bytes_by_tier=runtime_tier,
        control_plane=_control_plane_cost(events, world, n_slices,
                                          config))


def _control_plane_cost(events, world, num_slices, config):
    """Predicted control-plane load of one step: negotiation rounds (the
    dynamic-shape exchanges — ragged allgather / uneven alltoall — plus
    the per-dispatch join/order-check rounds when those modes are armed)
    priced with :func:`control_plane.exchange_plan` under the resolved
    strategy. Each rank is priced as one process — the launcher's
    worst-case (1 chip per process) and exactly the CPU-tier test
    layout, so the guard's measured counters are directly comparable."""
    from horovod_tpu.common import control_plane as _cp
    negotiated = sum(max(e.repeat, 1) for e in events
                     if e.origin != "jit"
                     and e.op in ("alltoall", "allgather_ragged"))
    extra = (1 if getattr(config, "join_mode", False) else 0) \
        + (1 if getattr(config, "order_check", False) else 0)
    if extra:
        negotiated += extra * sum(
            max(e.repeat, 1) for e in events if e.origin != "jit")
    strategy = "flat" if _cp.configured() == "flat" or num_slices <= 1 \
        else "hier"
    plan = _cp.exchange_plan(world, num_slices if strategy == "hier"
                             else 1)
    return {
        "strategy": plan["strategy"], "rounds_per_step": negotiated,
        "per_round": plan,
        # Per-step blocking gets by role, vs the flat fan-out — the
        # O(slice_size + num_slices) vs O(world) claim as numbers.
        "member_gets": negotiated * plan["member_gets"],
        "leader_gets": negotiated * plan["leader_gets"],
        "flat_gets": negotiated * (world - 1),
    }


def collective_step_tiers(per_rank_elems, world, num_slices, *,
                          strategy="flat", width=4, cross_wire=""):
    """Per-tier wire bytes of ONE allreduce of ``per_rank_elems``
    elements under ``strategy`` — the callable per-event pricing seam
    the scale digital twin's step model uses (:mod:`horovod_tpu.sim.
    autopilot`): same ``wire.hierarchical_wire_bytes`` /
    ``ring_dcn_fraction`` integers :func:`cost_report` books, so the
    twin and the static model can never disagree on a step's bytes.

    Returns ``{"ici": int, "dcn": int}``. Hierarchical strategies book
    tier-explicit legs (local RS+AG on ICI, slice-reduced shards on the
    cross wire over DCN; ``torus_qcross`` forces the int8 cross leg);
    flat books the full ring volume split on the slice-boundary
    fraction of a rank-major contiguous group."""
    e = max(int(per_rank_elems), 0)
    n = max(int(world), 1)
    k, slice_size = resolve_slices(n, num_slices)
    if strategy in ("hierarchical", "torus", "torus_qcross") and k > 1:
        h = _wire.hierarchical_wire_bytes(
            e, n, k, width,
            cross_wire=("int8" if strategy == "torus_qcross"
                        else cross_wire or ""))
        return {"ici": int(h["ici"]), "dcn": int(h["dcn"])}
    total = 2 * n * e * width
    frac = _ring_dcn_fraction(list(range(n)), slice_size) if k > 1 else 0.0
    dcn = int(round(total * frac))
    return {"ici": total - dcn, "dcn": dcn}


def check_cost(step_fn, args=(), kwargs=None, *, world_size=None,
               num_slices=None, config=None, dcn_budget_bytes=None,
               **check_kwargs):
    """Convenience: :func:`check_program` + :func:`cost_report` in one
    call. Returns ``(check_report, cost_report)``."""
    from horovod_tpu.analysis.program import check_program
    rep = check_program(step_fn, args, kwargs, world_size=world_size,
                        config=config, **check_kwargs)
    return rep, cost_report(rep, config=config, num_slices=num_slices,
                            dcn_budget_bytes=dcn_budget_bytes)


def _measured_wire_bytes(snapshot):
    """``dtype -> value`` from a metrics snapshot's ``wire_bytes_total``
    family (``hvd.metrics_snapshot()`` shape), summed across the tier
    label (the counter is ``{dtype, tier}`` since the hierarchical
    dispatch tier split it)."""
    out = {}
    fam = (snapshot or {}).get("wire_bytes_total") or {}
    for s in fam.get("series", ()):
        dtype = str(s.get("labels", {}).get("dtype"))
        out[dtype] = out.get(dtype, 0.0) + float(s.get("value", 0.0))
    return out


def _measured_tier_bytes(snapshot):
    """``tier -> value`` from ``wire_bytes_total{dtype,tier}``, summed
    across dtypes."""
    out = {}
    fam = (snapshot or {}).get("wire_bytes_total") or {}
    for s in fam.get("series", ()):
        t = s.get("labels", {}).get("tier")
        if t:
            out[str(t)] = out.get(str(t), 0.0) + float(s.get("value",
                                                             0.0))
    return out


def cross_check_bytes(cost, metrics_snapshot, baseline_snapshot=None,
                      rel_tol=0.05, steps=1):
    """Diff the static per-dtype wire prediction against the runtime's
    ``wire_bytes_total{dtype}`` counters in one call.

    ``cost`` is a :class:`CostReport` (or a
    :class:`~horovod_tpu.analysis.program.CheckReport`, priced with
    defaults); ``metrics_snapshot`` an ``hvd.metrics_snapshot()`` taken
    after the measured window, ``baseline_snapshot`` one taken before it
    (so compile-time jit accounting and earlier traffic subtract out);
    ``steps`` divides the measured deltas when the window ran the step
    more than once. Returns ``{"match", "rel_tol", "per_dtype": {dtype:
    {"predicted", "measured", "delta", "within"}}, "per_tier": {tier:
    ...}, "unpredicted"}`` — ``match`` is True when every predicted
    dtype AND tier lands within ``rel_tol`` and the prediction is exact
    (no unbounded repeats). The per-tier side diffs the runtime-metered
    prediction (``runtime_bytes_by_tier`` — under hierarchical dispatch
    that IS the hierarchical what-if, leg for leg) against the
    ``wire_bytes_total{tier}`` counters: delta 0 on the CPU tier."""
    if not isinstance(cost, CostReport):
        cost = cost_report(cost)
    measured = _measured_wire_bytes(metrics_snapshot)
    measured_tier = _measured_tier_bytes(metrics_snapshot)
    if baseline_snapshot is not None:
        base = _measured_wire_bytes(baseline_snapshot)
        measured = {k: v - base.get(k, 0.0) for k, v in measured.items()}
        base_t = _measured_tier_bytes(baseline_snapshot)
        measured_tier = {k: v - base_t.get(k, 0.0)
                         for k, v in measured_tier.items()}
    steps = max(int(steps), 1)
    measured = {k: v / steps for k, v in measured.items()}
    measured_tier = {k: v / steps for k, v in measured_tier.items()}
    per_dtype = {}
    ok = cost.exact
    for dtype, predicted in sorted(cost.bytes_by_dtype.items()):
        got = measured.get(dtype, 0.0)
        delta = got - predicted
        within = abs(delta) <= rel_tol * max(predicted, 1.0)
        per_dtype[dtype] = {"predicted": predicted, "measured": got,
                            "delta": delta, "within": within}
        ok = ok and within
    # The per-tier diff gates `match` only when the LIVE slice layout is
    # the one the report priced: a counterfactual what-if (e.g. priced at
    # num_slices=2 against a 1-slice run) keeps its per-dtype gate but
    # reports the tier rows informationally.
    try:
        from horovod_tpu.ops.collective_ops import _live_slices
        tier_gates = _live_slices(cost.world_size)[0] == cost.num_slices
    except Exception:  # noqa: BLE001 — uninitialized: dtype gate only
        tier_gates = False
    per_tier = {}
    for t, predicted in sorted(cost.runtime_bytes_by_tier.items()):
        got = measured_tier.get(t, 0.0)
        delta = got - predicted
        within = abs(delta) <= rel_tol * max(predicted, 1.0)
        per_tier[t] = {"predicted": predicted, "measured": got,
                       "delta": delta, "within": within,
                       "gates_match": tier_gates}
        if tier_gates:
            ok = ok and within
    unpredicted = {k: v for k, v in measured.items()
                   if k not in cost.bytes_by_dtype and v > 0}
    return {"match": ok, "rel_tol": rel_tol, "per_dtype": per_dtype,
            "per_tier": per_tier, "unpredicted": unpredicted}


# ----------------------------------------------------------------------------
# CLI: the CI gate. `python -m horovod_tpu.analysis.cost` prices a step
# program (the built-in representative fused+quantized step by default, or
# a user factory via --spec) and exits 1 on any error-severity finding —
# lint-style, wired behind scripts/lint.py --cost and the tier-1
# TestCostCLI gate.
# ----------------------------------------------------------------------------

def _representative_step(world, payload_elems):
    """The built-in CLI subject: one quantized-eligible fused-size
    allreduce, one tiny fp32 allreduce, one allgather, one barrier — the
    shape of a real training step's collective tail."""
    import numpy as np

    import horovod_tpu as hvd

    def step(grads, stats, metrics):
        g = hvd.allreduce(grads, op=hvd.Sum, name="grads")
        s = hvd.allreduce(stats, name="stats")
        gathered = hvd.allgather(metrics, name="metrics")
        hvd.barrier()
        return g, s, gathered

    grads = np.zeros((world, int(payload_elems)), np.float32)
    stats = np.zeros((world, 8), np.float32)
    metrics = np.zeros((world, 8), np.float32)
    return step, (grads, stats, metrics)


def _load_spec(spec):
    """``module:attr`` -> ``(step_fn, args[, kwargs])`` from a factory
    callable (called with no arguments)."""
    import importlib
    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(f"--spec {spec!r}: expected module:callable")
    factory = getattr(importlib.import_module(mod_name), attr)
    built = factory()
    if not isinstance(built, tuple) or len(built) < 2:
        raise ValueError(f"--spec {spec!r}: factory must return "
                         "(step_fn, args[, kwargs])")
    step_fn, args = built[0], built[1]
    kwargs = built[2] if len(built) > 2 else None
    return step_fn, args, kwargs


def main(argv=None):
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.cost",
        description="Static per-link-tier communication cost model + "
                    "elastic world-transition checker "
                    "(docs/static_analysis.md).")
    p.add_argument("--world", type=int, default=8,
                   help="simulated world size (default 8)")
    p.add_argument("--slices", type=int, default=0,
                   help="slice count for the tier split (default: live "
                        "topology / HOROVOD_MESH_SLICES / 1)")
    p.add_argument("--wire", default=None,
                   help="wire dtype to price (int8/fp8/bfloat16/float16; "
                        "default: HOROVOD_WIRE_DTYPE)")
    p.add_argument("--dcn-budget", type=int, default=None,
                   help="per-step DCN byte budget (HVP111; default: "
                        "HOROVOD_DCN_BYTES_BUDGET)")
    p.add_argument("--payload-kb", type=int, default=4096,
                   help="per-rank payload of the built-in representative "
                        "step, in KiB (default 4096)")
    p.add_argument("--elastic", default=None, metavar="W1,W2,...",
                   help="also model-check the step across this resize "
                        "ladder (e.g. 8,7,4,8) — HVP110 on any "
                        "world-dependent stream property")
    p.add_argument("--spec", default=None, metavar="MODULE:CALLABLE",
                   help="factory returning (step_fn, args[, kwargs]) to "
                        "analyze instead of the built-in step")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    args = p.parse_args(argv)

    from horovod_tpu.analysis.program import check_elastic, check_program
    from horovod_tpu.common.config import Config

    config = Config.from_env()
    if args.wire is not None:
        config.wire_dtype = args.wire
        config.__post_init__()
    if args.spec:
        step_fn, step_args, step_kwargs = _load_spec(args.spec)
    else:
        step_fn, step_args = _representative_step(
            args.world, args.payload_kb * 1024 // 4)
        step_kwargs = None
    rep = check_program(step_fn, step_args, step_kwargs,
                        world_size=args.world, config=config)
    cost = cost_report(rep, config=config,
                       num_slices=args.slices or None,
                       dcn_budget_bytes=args.dcn_budget)
    elastic = None
    if args.elastic:
        ladder = tuple(int(w) for w in args.elastic.split(","))

        def ladder_args(w):
            if args.spec:
                return step_args            # spec inputs are fixed
            return _representative_step(w, args.payload_kb * 1024 // 4)[1]

        elastic = check_elastic(step_fn, step_args, step_kwargs,
                                worlds=ladder, args_for=ladder_args,
                                config=config)
    failed = (not rep.ok) or (not cost.ok) \
        or (elastic is not None and not elastic.ok)
    if args.json:
        out = {"check": {"ok": rep.ok,
                         "findings": [f.to_dict() for f in rep.findings]},
               "cost": cost.to_dict()}
        if elastic is not None:
            out["elastic"] = {
                "ok": elastic.ok, "worlds": list(elastic.worlds),
                "findings": [f.to_dict() for f in elastic.findings]}
        print(json.dumps(out, indent=2, default=str))
    else:
        print(rep.render())
        print(cost.render())
        if elastic is not None:
            print(elastic.render())
        print("hvdcost: " + ("FAILED" if failed else "OK"))
    return 1 if failed else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
