"""``hvd.check_program``: static collective-correctness analysis.

Abstract-evals a step function once per simulated rank (``jax.make_jaxpr``
— zero device execution), extracts the ordered collective sequence each
rank would dispatch, and diffs the sequences to report desync hazards
BEFORE the run:

- framework eager ops (``hvd.allreduce``/...) are captured via the
  interception hook in :mod:`horovod_tpu.ops.collective_ops` — each call
  records the event the flight recorder WOULD record (same op label,
  process-set label, per-set seq and signature hash over the global
  stacked tensors) and returns an abstract stand-in, so rank-conditional
  Python control flow (``if hvd.rank() == 0: ...``) resolves per
  simulated rank through the :mod:`horovod_tpu.common.basics` overlay;
- raw in-jit collectives (``lax.psum``/``ppermute``/``all_gather``/
  ``all_to_all``/... inside ``shard_map``/``pjit``) are extracted from
  the traced jaxpr (:mod:`horovod_tpu.analysis.jaxpr_walk`).

Event ordering: eager events appear in Python call order; in-jit events
follow in equation order. A program interleaving BOTH styles gets the
eager events first — exact interleave would need trace markers; for
sequence-hash cross-checks against the flight recorder use eager-only (or
jit-only) steps.

The per-finding ``(op, ps, seq, sig)`` identity matches the flight
recorder's event fields, so a runtime ``flight.analyze`` desync can be
cross-checked against the static prediction with :func:`cross_check`.
"""

import dataclasses
import functools

import numpy as np

from horovod_tpu.analysis import jaxpr_walk
from horovod_tpu.analysis.events import (CollectiveEvent, assign_seqs,
                                         sequence_hash, signature_of)
from horovod_tpu.analysis.findings import (ERROR, INFO, WARNING, Finding,
                                           sort_findings)

# Advisory thresholds (HVP105): a program dispatching at least this many
# sync eager collectives, each under this fraction of the fusion
# threshold, is leaving the fusion buffer empty.
_FILL_MIN_EVENTS = 8
_FILL_SMALL_FRACTION = 0.01


def _ps_label(process_set):
    if process_set is None or getattr(process_set, "ranks", None) is None:
        return "global"
    pid = getattr(process_set, "process_set_id", None)
    return f"set{pid}" if pid is not None else "unregistered"


def _ps_size(process_set, world_size):
    if process_set is None or getattr(process_set, "ranks", None) is None:
        return world_size
    return len(process_set.ranks)


def _shape_dtype(x):
    shape = tuple(getattr(x, "shape", ()))
    dtype = getattr(x, "dtype", None)
    return shape, str(dtype) if dtype is not None else "float32"


class _TraceRecorder:
    """Per-rank event capture: the interception hook + reuse tracking."""

    def __init__(self, world_size):
        self.world_size = world_size
        self.events = []
        self.input_ids = {}          # id(tracer) -> first event index
        self.reused = []             # (first_idx, second_idx)

    def _note_inputs(self, tensors):
        idx = len(self.events)
        for t in tensors:
            key = id(t)
            first = self.input_ids.get(key)
            if first is not None:
                self.reused.append((first, idx))
            else:
                self.input_ids[key] = idx

    def record(self, op, tensors, process_set, name, origin="eager",
               red_op=None):
        """Record one eager dispatch the way the runtime would: signature
        over the GLOBAL stacked tensors (leading axis = set size)."""
        n = _ps_size(process_set, self.world_size)
        shapes, dtypes, nbytes = [], [], 0
        for t in tensors:
            shape, dtype = _shape_dtype(t)
            gshape = (n,) + tuple(shape[1:]) if shape else (n,)
            shapes.append(gshape)
            dtypes.append(dtype)
            width = jaxpr_walk.dtype_width(dtype)
            cnt = 1
            for d in gshape:
                cnt *= int(d)
            nbytes += cnt * width
        self._note_inputs(tensors)
        ranks = getattr(process_set, "ranks", None)
        self.events.append(CollectiveEvent(
            op=op, ps=_ps_label(process_set), seq=0, shapes=tuple(shapes),
            dtypes=tuple(dtypes), origin=origin, name=name, nbytes=nbytes,
            red_op=red_op,
            ps_ranks=tuple(int(r) for r in ranks) if ranks else None))


def _stub_outputs(kind, tensors, n, return_sizes=False):
    """Abstract results for an intercepted eager call: right shapes/dtypes,
    no device work (``jnp`` ops on tracers while the analyzer traces).
    ``tensors`` is the already-resolved input list, ``n`` the resolved
    process-set size."""
    import jax.numpy as jnp

    def zeros_like_rows(t, scale_axis1=1):
        shape, _ = _shape_dtype(t)
        if scale_axis1 != 1 and len(shape) > 1:
            shape = (shape[0], shape[1] * scale_axis1) + tuple(shape[2:])
        return jnp.zeros(shape, getattr(t, "dtype", jnp.float32))

    if kind in ("allreduce", "broadcast"):
        return [t + jnp.zeros((), getattr(t, "dtype", jnp.float32))
                if hasattr(t, "dtype") else t for t in tensors]
    if kind == "allgather":
        return [zeros_like_rows(t, scale_axis1=n) for t in tensors]
    if kind == "allgather_ragged":
        total = sum(int(t.shape[0]) for t in tensors)
        rest = tuple(tensors[0].shape[1:]) if tensors else ()
        out = jnp.zeros((total,) + rest,
                        getattr(tensors[0], "dtype", jnp.float32))
        if return_sizes:
            return out, [int(t.shape[0]) for t in tensors]
        return out
    if kind == "reducescatter":
        outs = []
        for t in tensors:
            shape, _ = _shape_dtype(t)
            rows = shape[1] // n if len(shape) > 1 and n else 0
            outs.append(jnp.zeros((shape[0], rows) + tuple(shape[2:]),
                                  getattr(t, "dtype", jnp.float32)))
        return outs
    if kind == "alltoall":
        return zeros_like_rows(tensors[0])
    if kind == "barrier":
        return None
    raise NotImplementedError(kind)


_GROUPED_KINDS = {"allreduce", "allgather", "broadcast", "reducescatter"}

# Positional index of process_set in each intercepted entry point's
# signature (collective_ops.py public API).
_PS_POS = {"allreduce": 4, "allgather": 1, "allgather_ragged": 1,
           "broadcast": 2, "reducescatter": 4, "alltoall": 2, "barrier": 0,
           "allreduce_async": 4, "allgather_async": 1,
           "broadcast_async": 2, "alltoall_async": 2,
           "reducescatter_async": 2}


def _make_hook(rec):
    """The collective_ops interception hook for one simulated rank."""

    def hook(kind, args, kwargs):
        def get(name, pos, default=None):
            if name in kwargs:
                return kwargs[name]
            return args[pos] if len(args) > pos else default

        ps_pos = _PS_POS.get(kind, 1)
        ps = get("process_set", ps_pos)
        name = get("name", ps_pos + 1)
        n = _ps_size(ps, rec.world_size)
        # Reduce-op name for the reductions (allreduce/reducescatter take
        # `op` at position 1) — the cost model's wire-eligibility check
        # mirrors the runtime's Sum/Average gate with it.
        red_op = None
        base_kind = kind[:-len("_async")] if kind.endswith("_async") \
            else kind
        if base_kind in ("allreduce", "reducescatter"):
            from horovod_tpu.ops.collective_ops import Average, ReduceOp, Sum
            default = Average if base_kind == "allreduce" else Sum
            try:
                red_op = ReduceOp(get("op", 1, default)).name.capitalize()
            except ValueError:
                red_op = None
        # The tensor operand may arrive positionally or by keyword; the
        # grouped ops spell it `tensors`, the singular ones `tensor`.
        first = get("tensors", 0, get("tensor", 0))
        was_list = isinstance(first, (list, tuple))
        tensors = list(first) if was_list else [first]
        if kind in _GROUPED_KINDS:
            rec.record(kind, tensors, ps, name, red_op=red_op)
            return _stub_outputs(kind, tensors, n)
        if kind == "allgather_ragged":
            rec.record("allgather", tensors, ps, name)
            return _stub_outputs(kind, tensors, n,
                                 return_sizes=bool(get("return_sizes", 3)))
        if kind == "alltoall":
            rec.record("alltoall", tensors, ps, name)
            return _stub_outputs(kind, tensors, n)
        if kind == "barrier":
            rec.record("barrier", [], ps, name)
            return None
        if kind.endswith("_async"):
            from horovod_tpu.ops.collective_ops import Handle
            base = kind[:-len("_async")]
            # Async allreduce rides the fusion runtime: its flush order is
            # cycle-timed, so seq prediction is approximate -> "fused".
            origin = "fused" if base == "allreduce" else "eager"
            rec.record(base, tensors, ps, name, origin=origin,
                       red_op=red_op)
            out = _stub_outputs(base, tensors, n)
            if not was_list and isinstance(out, list):
                out = out[0]
            return Handle(out, name)
        return NotImplemented

    return hook


@dataclasses.dataclass
class CheckReport:
    """Result of :func:`check_program`."""

    world_size: int
    ranks: tuple
    sequences: dict                  # rank -> [CollectiveEvent]
    findings: list
    sampled: bool = False            # True when not every rank was traced

    @property
    def ok(self):
        return not any(f.severity == ERROR for f in self.findings)

    def errors(self):
        return [f for f in self.findings if f.severity == ERROR]

    def sequence_hash(self, rank=None, ps=None):
        """Stable hash of one rank's predicted sequence (default: the
        lowest simulated rank) — comparable with
        ``events.sequence_hash(flight_events)`` from a real run."""
        rank = self.ranks[0] if rank is None else rank
        return sequence_hash(self.sequences[rank], ps=ps)

    def predicted(self, rank=None):
        """The ordered ``(op, ps, seq, sig)`` identities for one rank."""
        rank = self.ranks[0] if rank is None else rank
        return [e.identity() for e in self.sequences[rank]]

    def render(self):
        lines = [f"check_program: world_size={self.world_size} "
                 f"ranks={list(self.ranks)}"
                 + (" (sampled)" if self.sampled else "")]
        ref = self.sequences.get(self.ranks[0], [])
        lines.append(f"  predicted collectives (rank {self.ranks[0]}): "
                     f"{len(ref)}")
        for e in ref[:32]:
            lines.append(f"    {e.describe()}")
        if len(ref) > 32:
            lines.append(f"    ... {len(ref) - 32} more")
        if not self.findings:
            lines.append("  findings: none — program is desync-clean")
        else:
            lines.append(f"  findings: {len(self.findings)}")
            for f in sort_findings(self.findings):
                lines.append(f"    {f.render()}")
        return "\n".join(lines)


def _abstractify(args, kwargs):
    """Split pytree leaves into dynamic (traced: arrays / ShapeDtypeStruct)
    and static (python scalars, strings — kept concrete so user control
    flow on them still works)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    dyn_idx, dyn_specs = [], []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.ShapeDtypeStruct):
            dyn_idx.append(i)
            dyn_specs.append(leaf)
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            dyn_idx.append(i)
            dyn_specs.append(jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
    return leaves, treedef, dyn_idx, dyn_specs


def _trace_rank(step_fn, args, kwargs, rank, world_size, local_size):
    """One simulated-rank abstract eval; returns (events, reused, jaxpr)."""
    import jax

    from horovod_tpu.common import basics
    from horovod_tpu.ops import collective_ops

    rec = _TraceRecorder(world_size)
    leaves, treedef, dyn_idx, dyn_specs = _abstractify(args, kwargs)

    def traced(*dyn):
        filled = list(leaves)
        for i, v in zip(dyn_idx, dyn):
            filled[i] = v
        a, kw = jax.tree_util.tree_unflatten(treedef, filled)
        return step_fn(*a, **kw)

    prev_sim = basics._set_sim_world(
        basics._SimWorld(rank, world_size, local_size))
    prev_hook = collective_ops.set_intercept(_make_hook(rec))
    try:
        closed = jax.make_jaxpr(traced)(*dyn_specs)
    finally:
        collective_ops.set_intercept(prev_hook)
        basics._set_sim_world(prev_sim)
    return rec, closed


def _jit_events(closed):
    """In-jit collectives of a traced step as CollectiveEvents; also
    returns the cond-gated and degenerate (1-sized axis) subsets."""
    events = []
    cond_ops = []
    degenerate = []
    for c in jaxpr_walk.collect(closed):
        ax = ",".join(c.axes) if c.axes else "?"
        events.append(CollectiveEvent(
            op=c.op, ps=f"axis:{ax}", seq=0, shapes=c.shapes,
            dtypes=c.dtypes, origin="jit", nbytes=c.nbytes,
            repeat=max(c.repeat, 0),   # 0 = unknown (while-loop body)
            axis_sizes=tuple(c.axis_sizes)))
        if c.in_cond:
            cond_ops.append(c)
        if any(s == 1 for s in c.axis_sizes if s is not None):
            degenerate.append(c)
    return events, cond_ops, degenerate


def check_program(step_fn, args=(), kwargs=None, *, world_size=None,
                  local_size=None, ranks=None, config=None,
                  include_advisories=True, max_traced_ranks=16):
    """Statically analyze ``step_fn(*args, **kwargs)`` for collective
    desync hazards across a simulated world.

    Array(-like) leaves of ``args``/``kwargs`` are abstracted to
    shape/dtype (pass real arrays or ``jax.ShapeDtypeStruct``); python
    scalars stay concrete. ``world_size`` defaults to the live
    ``hvd.size()`` when initialized, else 2. ``ranks`` selects which ranks
    to simulate (default: all, sampled down to ``max_traced_ranks``
    boundary ranks for very large worlds). Returns a :class:`CheckReport`.

    Zero device execution: the step is traced per rank with
    ``jax.make_jaxpr``; eager framework collectives are intercepted, raw
    in-jit collectives are read from the jaxpr.
    """
    from horovod_tpu.common import basics
    from horovod_tpu.common.config import Config

    if world_size is None:
        try:
            world_size = basics.size()
        except Exception:
            world_size = 2
    world_size = int(world_size)
    if world_size < 1:
        raise ValueError(f"world_size={world_size}")
    sampled = False
    if ranks is None:
        if world_size <= max_traced_ranks:
            ranks = tuple(range(world_size))
        else:
            # Boundary ranks catch the usual rank-gated patterns (first,
            # second, middle, last); mid is sampled WITH its neighbors —
            # `rank == size // 2 + 1`-style gates (pipeline halves, the
            # odd-world leader pick) otherwise escape HVP101 entirely.
            # Sampling is reported on the report.
            mid = world_size // 2
            ranks = tuple(sorted({0, 1, mid - 1, mid, mid + 1,
                                  world_size - 2, world_size - 1}))
            sampled = True
    else:
        ranks = tuple(sorted(set(int(r) for r in ranks)))
        for r in ranks:
            if not 0 <= r < world_size:
                raise ValueError(f"rank {r} outside world {world_size}")
    if config is None:
        try:
            config = basics.config()
        except Exception:
            config = Config()

    sequences, reuse_by_rank, cond_by_rank, degen_by_rank = {}, {}, {}, {}
    for r in ranks:
        rec, closed = _trace_rank(step_fn, args, kwargs, r, world_size,
                                  local_size)
        jit_events, cond_ops, degen_jit = _jit_events(closed)
        sequences[r] = assign_seqs(rec.events + jit_events)
        reuse_by_rank[r] = (rec, rec.reused)
        cond_by_rank[r] = cond_ops
        degen_by_rank[r] = degen_jit

    findings = _diff_sequences(sequences, ranks)
    findings += _degenerate_findings(sequences, ranks)
    r0 = ranks[0]
    findings += _cond_findings(cond_by_rank[r0], r0)
    for c in degen_by_rank[r0]:
        ax = ",".join(c.axes) if c.axes else "?"
        findings.append(Finding(
            code="HVP104", severity=WARNING,
            message=(f"degenerate sharding: {c.op} over 1-device mesh "
                     f"axis {ax} — all dispatch cost, no exchange"),
            rank=r0, op=c.op, ps=f"axis:{ax}"))
    if include_advisories:
        findings += _advisory_findings(sequences[r0], r0, config,
                                       reuse_by_rank[r0], world_size)
    return CheckReport(world_size=world_size, ranks=ranks,
                       sequences=sequences,
                       findings=sort_findings(findings), sampled=sampled)


def _diff_sequences(sequences, ranks):
    """Cross-rank sequence diff per process set: HVP101/102/103."""
    findings = []
    all_ps = []
    for r in ranks:
        for e in sequences[r]:
            if e.ps not in all_ps:
                all_ps.append(e.ps)
    r0 = ranks[0]
    for ps in all_ps:
        streams = {r: [e for e in sequences[r] if e.ps == ps]
                   for r in ranks}
        lens = {r: len(s) for r, s in streams.items()}
        if len(set(lens.values())) > 1:
            # Count mismatch: the rank-gated-collective deadlock class.
            # Name the first seq where a longer rank has an event some
            # shorter rank does not.
            short = min(lens, key=lambda r: lens[r])
            long = max(lens, key=lambda r: lens[r])
            pos = _first_divergence(streams[short], streams[long])
            extra = streams[long][min(pos, lens[long] - 1)]
            gated = sorted(r for r in ranks if lens[r] == lens[long])
            absent = sorted(r for r in ranks if lens[r] == lens[short])
            findings.append(Finding(
                code="HVP101", severity=ERROR,
                message=(f"rank-gated collective: rank(s) {gated} dispatch "
                         f"{extra.op} on {ps} at seq {extra.seq} that "
                         f"rank(s) {absent} never dispatch — ranks "
                         f"{absent} block forever at their next {ps} "
                         f"collective (or the job wedges at this one)"),
                rank=gated[0], op=extra.op, ps=ps, seq=extra.seq,
                sig=extra.sig))
            continue
        base = streams[r0]
        for r in ranks[1:]:
            pos = _first_divergence(base, streams[r])
            if pos is None:
                continue
            a, b = base[pos], streams[r][pos]
            ops_aligned = [e.op for e in base] \
                == [e.op for e in streams[r]]
            if ops_aligned and a.sig != b.sig:
                code, sev, what = "HVP103", ERROR, (
                    f"dtype/shape mismatch: rank {r0} dispatches "
                    f"{a.op} with sig {a.sig} "
                    f"{a.shapes}/{a.dtypes} at {ps} seq {a.seq}, rank {r} "
                    f"dispatches sig {b.sig} {b.shapes}/{b.dtypes}")
            elif {e.key() for e in base} == {e.key() for e in streams[r]}:
                code, sev, what = "HVP102", ERROR, (
                    f"op-order mismatch on {ps}: at seq {a.seq} rank "
                    f"{r0} dispatches {a.op} (sig {a.sig}) while rank "
                    f"{r} dispatches {b.op} (sig {b.sig})")
            else:
                code, sev, what = "HVP101", ERROR, (
                    f"rank-divergent collective stream on {ps}: first "
                    f"divergence at seq {a.seq} — rank {r0}: {a.op} "
                    f"(sig {a.sig}) vs rank {r}: {b.op} (sig {b.sig})")
            findings.append(Finding(
                code=code, severity=sev, message=what, rank=r, op=b.op,
                ps=ps, seq=b.seq, sig=b.sig))
            break
    return findings


def _first_divergence(a, b):
    for i, (x, y) in enumerate(zip(a, b)):
        if x.key() != y.key():
            return i
    if len(a) != len(b):
        return min(len(a), len(b))
    return None


def _degenerate_findings(sequences, ranks):
    findings = []
    seen = set()
    for r in ranks:
        for e in sequences[r]:
            if e.origin == "jit":
                continue
            # eager op over a 1-member set: leading global dim == 1
            n = e.shapes[0][0] if e.shapes and e.shapes[0] else None
            if n == 1 and e.op != "barrier":
                key = (e.op, e.ps, e.seq)
                if key not in seen:
                    seen.add(key)
                    findings.append(Finding(
                        code="HVP104", severity=WARNING,
                        message=(f"degenerate collective: {e.op} over "
                                 f"1-member process set {e.ps} — all "
                                 "dispatch cost, no exchange"),
                        rank=r, op=e.op, ps=e.ps, seq=e.seq, sig=e.sig))
    return findings


def hier_triads(events):
    """Recognize the hierarchical 2-level exchange shape in a predicted
    event stream: a ``reduce_scatter`` over the LOCAL mesh axis, followed
    by cross-axis collective(s) (the DCN leg — exact psum or the
    block-scaled exchange's int8/fp8 all_to_all+all_gather), followed by
    an ``all_gather`` back over the LOCAL axis (``NCCLTorusAllreduce`` /
    ``strategies.allreduce_torus``'s jaxpr footprint). Returns one dict
    per decomposition: ``{"rs": event, "cross": [events],
    "quantized": bool}`` — ``quantized`` when the cross leg moves a
    block-scaled wire dtype, which is what suppresses HVP106 for the
    decomposition's deliberately-full-precision ICI legs."""
    from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
    jit = [e for e in events if e.origin == "jit"]
    local_ps, cross_ps = f"axis:{LOCAL_AXIS}", f"axis:{CROSS_AXIS}"
    triads = []
    for i, e in enumerate(jit):
        if e.op != "reduce_scatter" or e.ps != local_ps:
            continue
        cross = [c for c in jit[i + 1:] if c.ps == cross_ps]
        ag = [g for g in jit[i + 1:]
              if g.op == "all_gather" and g.ps == local_ps]
        if cross and ag:
            quantized = any(
                d == "int8" or str(d).startswith("float8")
                for c in cross for d in c.dtypes)
            triads.append({"rs": e, "cross": cross,
                           "quantized": quantized})
    return triads


def _cond_findings(cond_ops, rank):
    findings = []
    for c in cond_ops:
        ax = ",".join(c.axes) if c.axes else "?"
        findings.append(Finding(
            code="HVP108", severity=WARNING,
            message=(f"{c.op} over axis {ax} inside a lax.cond branch "
                     f"{c.branch}: if the predicate varies across the "
                     "mesh, a device subset enters the collective and "
                     "the rendezvous deadlocks (see "
                     "parallel/pp.py head-gating)"),
            rank=rank, op=c.op, ps=f"axis:{ax}"))
    return findings


def _advisory_findings(events, rank, config, reuse_info, world_size=None):
    findings = []
    rec, reused = reuse_info
    sync_eager = [e for e in events if e.origin == "eager"
                  and e.op != "barrier"]
    threshold = int(getattr(config, "fusion_threshold", 0)) or 1
    small = [e for e in sync_eager
             if e.nbytes < threshold * _FILL_SMALL_FRACTION]
    if len(small) >= _FILL_MIN_EVENTS:
        total = sum(e.nbytes for e in small)
        findings.append(Finding(
            code="HVP105", severity=INFO,
            message=(f"{len(small)} sync eager collectives of "
                     f"{total} B total — fusion-threshold fill ratio "
                     f"{total / threshold:.1%}; *_async dispatch would "
                     "batch them into one fused flush"),
            rank=rank))
    for e in events:
        if e.origin == "fused" and e.nbytes > threshold:
            findings.append(Finding(
                code="HVP105", severity=INFO,
                message=(f"async {e.op} of {e.nbytes} B exceeds the "
                         f"fusion threshold ({threshold} B): every "
                         "enqueue flushes immediately — raise "
                         "HOROVOD_FUSION_THRESHOLD or dispatch sync"),
                rank=rank, op=e.op, ps=e.ps, seq=e.seq, sig=e.sig))
            break
    wire = getattr(config, "wire_dtype", "")
    cross_cfg = getattr(config, "wire_dtype_dcn", "")
    a2a_cross_cfg = getattr(config, "alltoall_cross_dtype", "")
    # The block-scaled quantized exchange shows up in the jaxpr as 1-byte
    # collectives (int8 / float8 all_to_all + all_gather, ops/wire.py):
    # its presence means the program IS quantizing in jit — the small
    # fp32 collectives alongside it are the exchange's own block scales,
    # not an unquantized wire. This check also covers a hierarchical
    # decomposition whose CROSS leg is block-scaled (hier_triads'
    # `quantized` flag is derived from the same int8/fp8 jit events):
    # its full-precision local legs are the tier's deliberate ICI
    # policy, not a missed wire.
    quant_jit = [e for e in events if e.origin == "jit"
                 and any(d == "int8" or str(d).startswith("float8")
                         for d in e.dtypes)]
    if (wire or cross_cfg or a2a_cross_cfg) and not quant_jit:
        fp32_jit = [e for e in events if e.origin == "jit"
                    and any("float32" in d for d in e.dtypes)]
        if fp32_jit:
            e = fp32_jit[0]
            knob = f"wire_dtype={wire}" if wire \
                else (f"wire_dtype_dcn={cross_cfg}" if cross_cfg
                      else f"alltoall_cross_dtype={a2a_cross_cfg}")
            findings.append(Finding(
                code="HVP106", severity=INFO,
                message=(f"{knob} is configured but "
                         f"{len(fp32_jit)} in-jit collective(s) move "
                         "float32 on the wire — the wire tier covers "
                         "eager/fused dispatches; inside jit use "
                         "Compression.int8 on the optimizer, "
                         "strategies.allreduce_quantized, or the "
                         "2-level strategies.allreduce_tiered / "
                         "alltoall_tiered"),
                rank=rank, op=e.op, ps=e.ps))
    # HVP113: the hierarchical decomposition over a 1-slice layout is
    # pure overhead — two extra ICI legs (local RS + AG) and no DCN to
    # save, since every 'cross' hop is in-slice interconnect anyway.
    if world_size:
        from horovod_tpu.analysis.cost import resolve_slices
        n_slices, _ = resolve_slices(world_size)
        if n_slices <= 1:
            # Only consulted on 1-slice layouts: the triad scan is
            # O(RS x jit events) and would be pure waste on the common
            # multi-slice path.
            triads = hier_triads(events)
            if triads:
                t = triads[0]
                findings.append(Finding(
                    code="HVP113", severity=INFO,
                    message=("hierarchical allreduce (local RS -> cross "
                             "-> local AG) over a 1-slice layout: the "
                             "cross leg rides the same ICI as the local "
                             "legs, so the decomposition adds two extra "
                             "legs for no DCN saving — use the flat "
                             "allreduce, or set HOROVOD_MESH_SLICES to "
                             "the real slice hierarchy"),
                    rank=rank, op=t["rs"].op, ps=t["rs"].ps))
            elif getattr(config, "hierarchical_dispatch", False) and any(
                    e.op == "allreduce" and e.origin != "jit"
                    for e in events):
                findings.append(Finding(
                    code="HVP113", severity=INFO,
                    message=("HOROVOD_HIERARCHICAL_DISPATCH is on but "
                             f"the {world_size}-rank world has a 1-slice "
                             "layout — the dispatch layer will keep "
                             "every allreduce flat (the decomposition "
                             "would be pure overhead); set "
                             "HOROVOD_MESH_SLICES / run multi-slice, or "
                             "drop the knob"),
                    rank=rank, op="allreduce", ps="global"))
            # The a2a twin: the hierarchical alltoall tier armed (knob or
            # registry pin) over a 1-slice layout — the slice-local leg
            # duplicates the whole exchange on the same ICI the 'cross'
            # leg rides, pure overhead with no DCN to save.
            from horovod_tpu.ops import wire as _wire_mod
            a2a_armed = getattr(config, "hierarchical_alltoall", False) \
                or _wire_mod.alltoall_strategy_for("global") \
                in ("hier", "hier_qcross")
            if a2a_armed and any(e.op == "alltoall" and e.origin != "jit"
                                 for e in events):
                findings.append(Finding(
                    code="HVP113", severity=INFO,
                    message=("HOROVOD_HIERARCHICAL_ALLTOALL is armed but "
                             f"the {world_size}-rank world has a 1-slice "
                             "layout — the dispatch layer will keep "
                             "every alltoall flat (the slice-local leg "
                             "would duplicate the exchange on the same "
                             "ICI for no DCN saving); set "
                             "HOROVOD_MESH_SLICES / run multi-slice, or "
                             "drop the knob"),
                    rank=rank, op="alltoall", ps="global"))
    if quant_jit and getattr(config, "wire_error_feedback", False) \
            and wire in ("int8", "fp8"):
        # The eager/fused paths keep their residuals in the runtime store,
        # which clear_program_caches zeroes on every elastic reset. An
        # IN-JIT quantized exchange is outside that store: either its
        # error feedback is silently inactive, or the residual lives in
        # user/optimizer state — where a resized mesh replays stale
        # residuals unless the caller zeroes them on reset.
        e = quant_jit[0]
        findings.append(Finding(
            code="HVP109", severity=INFO,
            message=(f"error feedback is configured "
                     f"(wire_dtype={wire}, wire_error_feedback=1) but "
                     f"{len(quant_jit)} in-jit quantized exchange(s) are "
                     "outside the runtime residual store — if the "
                     "optimizer threads residuals "
                     "(strategies.allreduce_quantized residual=...), it "
                     "must zero them on elastic reset "
                     "(clear_program_caches cannot reach jit state); "
                     "otherwise error feedback is silently inactive on "
                     "this path"),
            rank=rank, op=e.op, ps=e.ps))
    if reused:
        first, second = reused[0]
        ev = events[first] if first < len(events) else None
        donate = bool(getattr(config, "donate_eager", False))
        findings.append(Finding(
            code="HVP107",
            severity=WARNING if donate else INFO,
            message=(("donated buffer reused: the same input buffer "
                      "feeds collectives at event positions "
                      f"{first} and {second} while HOROVOD_DONATE_BUFFERS "
                      "is armed — the first dispatch invalidates it")
                     if donate else
                     ("non-donated buffer reuse: one input buffer feeds "
                      f"collectives at event positions {first} and "
                      f"{second}; eager donation "
                      "(HOROVOD_DONATE_BUFFERS) cannot apply to reused "
                      "buffers")),
            rank=rank, op=getattr(ev, "op", None),
            ps=getattr(ev, "ps", None),
            seq=getattr(ev, "seq", None),
            sig=getattr(ev, "sig", None) if ev else None))
    return findings


@dataclasses.dataclass
class ElasticReport:
    """Result of :func:`check_elastic`: one :class:`CheckReport` per
    distinct world size in the ladder, plus the cross-GENERATION findings
    (``HVP110`` world_dependent_signature / ``HVP112`` unbounded_repeat)
    the per-world analysis cannot see."""

    worlds: tuple                    # the resize ladder as given
    reports: dict                    # world_size -> CheckReport
    findings: list                   # cross-generation findings

    @property
    def ok(self):
        return (not any(f.severity == ERROR for f in self.findings)
                and all(r.ok for r in self.reports.values()))

    def errors(self):
        errs = [f for f in self.findings if f.severity == ERROR]
        for w in sorted(self.reports):
            errs += self.reports[w].errors()
        return errs

    def render(self):
        lines = [f"check_elastic: ladder {'->'.join(map(str, self.worlds))}"]
        for w in sorted(self.reports):
            r = self.reports[w]
            n_err = len(r.errors())
            lines.append(f"  world {w}: {len(r.sequences[r.ranks[0]])} "
                         f"collectives, "
                         + ("clean" if r.ok else f"{n_err} error(s)"))
        if not self.findings:
            lines.append("  generations: streams are world-invariant — "
                         "safe to resize across this ladder")
        else:
            lines.append(f"  generation findings: {len(self.findings)}")
            for f in sort_findings(self.findings):
                lines.append(f"    {f.render()}")
        return "\n".join(lines)


def _payload_consistent(ev_a, world_a, ev_b, world_b):
    """True when two aligned events' payloads are explained by one
    world-INDEPENDENT logical buffer: either each participant contributes
    the same elements at both worlds (replicated payload, the
    data-parallel gradient case), or the per-rank shares are an even
    reshard of the same logical total — ``ceil(B/n)`` shards differ across
    worlds by at most the ceil padding (plus a small relative slack for
    block-aligned shards, e.g. the quantized wire's 1024 blocks)."""
    pa, pb = ev_a.per_rank_elems(), ev_b.per_rank_elems()
    if pa == pb:
        return True
    na = ev_a.group_size(world_a) or world_a
    nb = ev_b.group_size(world_b) or world_b
    ta, tb = na * pa, nb * pb
    spread = abs(ta - tb)
    return spread <= max(na, nb) or spread <= 0.02 * max(min(ta, tb), 1)


def _diff_generations(base_world, base_events, world, events):
    """Cross-generation stream diff (same rank, two world sizes): HVP110
    when the collective stream is a function of world size — a resized
    mesh would replay the step against mismatched peers."""
    findings = []
    all_ps = []
    for e in list(base_events) + list(events):
        if e.ps not in all_ps:
            all_ps.append(e.ps)
    for ps in all_ps:
        a = [e for e in base_events if e.ps == ps]
        b = [e for e in events if e.ps == ps]
        if len(a) != len(b):
            longer, lw = (a, base_world) if len(a) > len(b) else (b, world)
            extra = longer[min(len(a), len(b))]
            findings.append(Finding(
                code="HVP110", severity=ERROR,
                message=(f"world-dependent collective stream on {ps}: "
                         f"{len(a)} event(s) at world {base_world} vs "
                         f"{len(b)} at world {world} — first extra: "
                         f"{extra.op} seq {extra.seq} (world {lw}); a "
                         "world-size-gated collective desyncs the resized "
                         "generation"),
                op=extra.op, ps=ps, seq=extra.seq))
            continue
        for ea, eb in zip(a, b):
            if ea.op != eb.op:
                findings.append(Finding(
                    code="HVP110", severity=ERROR,
                    message=(f"world-dependent collective order on {ps} "
                             f"at seq {ea.seq}: {ea.op} at world "
                             f"{base_world} vs {eb.op} at world {world}"),
                    op=eb.op, ps=ps, seq=eb.seq, sig=eb.sig))
                break
            if ea.dtypes != eb.dtypes:
                findings.append(Finding(
                    code="HVP110", severity=ERROR,
                    message=(f"world-dependent signature: {ea.op} on {ps} "
                             f"at seq {ea.seq} moves {ea.dtypes} at world "
                             f"{base_world} but {eb.dtypes} at world "
                             f"{world} — a resized mesh replays this "
                             "collective against mismatched peers"),
                    op=eb.op, ps=ps, seq=eb.seq, sig=eb.sig))
                break
            if ea.repeat != eb.repeat and ea.repeat > 0 and eb.repeat > 0:
                findings.append(Finding(
                    code="HVP110", severity=ERROR,
                    message=(f"world-dependent repeat: {ea.op} on {ps} at "
                             f"seq {ea.seq} runs x{ea.repeat} at world "
                             f"{base_world} but x{eb.repeat} at world "
                             f"{world} (a scan whose length tracks the "
                             "world desyncs the resized generation)"),
                    op=eb.op, ps=ps, seq=eb.seq, sig=eb.sig))
                break
            if not _payload_consistent(ea, base_world, eb, world):
                findings.append(Finding(
                    code="HVP110", severity=ERROR,
                    message=(f"world-dependent signature: {ea.op} on {ps} "
                             f"at seq {ea.seq} — per-rank payload "
                             f"{ea.per_rank_elems()} elems at world "
                             f"{base_world} vs {eb.per_rank_elems()} at "
                             f"world {world}, not explained by an even "
                             "reshard of one logical buffer (ZeRO-style "
                             "ceil(B/n) shards and replicated payloads "
                             "both pass); a resized mesh replays this "
                             "collective against mismatched peers"),
                    op=eb.op, ps=ps, seq=eb.seq, sig=eb.sig))
                break
    return findings


def check_elastic(step_fn, args=(), kwargs=None, *, worlds=(8, 7, 4, 8),
                  args_for=None, local_size=None, config=None,
                  include_advisories=False, max_traced_ranks=16):
    """Model-check a step program across an elastic resize ladder (the
    shrink/grow set the chaos soaks exercise, e.g. ``8 -> 7 -> 4 -> 8``):
    re-run the per-rank abstract eval at every distinct world size and
    diff the collective streams *across generations*.

    ``args_for(world_size)`` builds the generation's inputs — exactly what
    the elastic driver does between generations (gather -> reshard -> new
    mesh); return an args tuple or an ``(args, kwargs)`` pair. Without it
    every generation traces the same ``args`` (correct for inputs whose
    shapes don't track the world). Returns an :class:`ElasticReport`;
    ``HVP110`` (error) marks any stream property that is a function of
    world size, ``HVP112`` (advisory) marks while-loop collectives whose
    presence-only diff makes the generation check a lower bound.
    """
    worlds = tuple(int(w) for w in worlds)
    if len(worlds) < 2:
        raise ValueError("check_elastic needs a ladder of >= 2 world "
                         f"sizes, got {worlds}")
    reports = {}
    for w in worlds:
        if w in reports:
            continue
        gen_args, gen_kwargs = args, kwargs
        if args_for is not None:
            built = args_for(w)
            if (isinstance(built, tuple) and len(built) == 2
                    and isinstance(built[0], tuple)
                    and isinstance(built[1], dict)):
                gen_args, gen_kwargs = built
            else:
                gen_args = built
        reports[w] = check_program(
            step_fn, gen_args, gen_kwargs, world_size=w,
            local_size=local_size, config=config,
            include_advisories=include_advisories,
            max_traced_ranks=max_traced_ranks)
    findings = []
    base = worlds[0]
    base_events = reports[base].sequences[reports[base].ranks[0]]
    for w in dict.fromkeys(worlds[1:]):
        if w == base:
            continue
        findings += _diff_generations(
            base, base_events, w, reports[w].sequences[reports[w].ranks[0]])
    seen_unbounded = set()
    for e in base_events:
        if e.repeat == 0 and (e.op, e.ps) not in seen_unbounded:
            seen_unbounded.add((e.op, e.ps))
            findings.append(Finding(
                code="HVP112", severity=INFO,
                message=(f"{e.op} on {e.ps} sits under a while loop with "
                         "no static trip count: generations are diffed "
                         "for its PRESENCE only, so the elastic check is "
                         "a lower bound for this collective"),
                op=e.op, ps=e.ps, seq=e.seq))
    return ElasticReport(worlds=worlds, reports=reports,
                         findings=sort_findings(findings))


def cross_check(report, flight_events, rank=None, ps="global"):
    """Compare a static :class:`CheckReport` against flight-recorder events
    from a real run (``flight.recorder.events()`` dicts or a loaded dump).

    Returns a dict: ``match`` (bool), ``predicted``/``recorded`` hash,
    ``first_mismatch`` (None or ``(predicted_identity,
    recorded_identity)``), ``n_predicted``/``n_recorded``."""
    rank = report.ranks[0] if rank is None else rank
    predicted = [e for e in report.sequences[rank]
                 if ps is None or e.ps == ps]
    recorded = [e for e in flight_events
                if e.get("kind") == "dispatch"
                and (ps is None or e.get("ps") == ps)]
    pred_ids = [e.identity() for e in predicted]
    rec_ids = [(e.get("op"), e.get("ps"), e.get("seq"), e.get("sig"))
               for e in recorded]
    first_mismatch = None
    for i in range(max(len(pred_ids), len(rec_ids))):
        p = pred_ids[i] if i < len(pred_ids) else None
        q = rec_ids[i] if i < len(rec_ids) else None
        if p != q:
            first_mismatch = (p, q)
            break
    return {
        "match": first_mismatch is None,
        "predicted_hash": sequence_hash(predicted, ps=ps),
        "recorded_hash": sequence_hash(recorded, ps=ps),
        "first_mismatch": first_mismatch,
        "n_predicted": len(pred_ids),
        "n_recorded": len(rec_ids),
    }
