"""hvdlint: AST-based codebase lint for distributed-runtime hazards.

``python -m horovod_tpu.analysis.lint [paths...]`` (or ``scripts/lint.py``)
checks Python sources for the bug classes previous PRs fixed by hand:

- **HVL002 undeclared env knob** — an ``os.environ`` /
  ``_env_bool/int/float`` read of a ``HOROVOD_*``/``HVD_*`` name that
  ``common/config.py::Config`` does not declare. Undeclared knobs are
  unpropagated by the launcher's worker-env list and invisible to the
  docs catalogues. The declared set is parsed from config.py's AST, so
  declaring the knob fixes the finding with no lint change.
- **HVL003 ambient env write** — mutating ``HOROVOD_*``/``HVD_*`` env
  outside the launcher / config / test layers: invisible config drift.
- **HVL004 rank-conditional collective** — an eager collective inside an
  ``if`` gated on ``rank()``/``local_rank()``/``cross_rank()``/
  ``process_index()`` in example/test code: the deadlock
  ``hvd.check_program`` flags statically (library internals legitimately
  rank-branch around *mirror* dispatches, so the rule applies to
  user-code roots only).
- **HVL005 non-daemon thread** — ``threading.Thread(...)`` without
  ``daemon=True``: a forgotten thread blocks interpreter exit (the
  elastic teardown wedges the PR-4 soak chased).
- **HVL007 unpropagated knob** — a knob declared in
  ``common/config.py::Config`` whose env spelling never appears in the
  launcher's worker-env plumbing (``runner/launch.py`` +
  ``runner/config_parser.py``): set on the driver, silently absent on
  every worker. The inverse of HVL002.

HVL001 (lock-held blocking call) and HVL006 (lock-held sleep) are
RETIRED: both only saw a hard-coded call list under a syntactically
visible ``with lock:``. hvdrace's HVR202
(``python -m horovod_tpu.analysis.race``) subsumes them with a
call-graph-aware hold analysis that follows the lock across function
and module boundaries.

Suppression: ``# hvdlint: disable=HVL002 -- <reason>`` on the offending
line or its enclosing ``with``/``def`` line; the reason is REQUIRED (a
bare disable is itself reported). ``# hvdlint: skip-file -- <reason>``
at the top of a file skips it entirely.
"""

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
import time

_COLLECTIVE_CALLS = frozenset({
    "allreduce", "grouped_allreduce", "allgather", "grouped_allgather",
    "allgather_ragged", "broadcast", "grouped_broadcast", "reducescatter",
    "grouped_reducescatter", "alltoall", "barrier", "join",
    "allreduce_async", "grouped_allreduce_async", "allgather_async",
    "broadcast_async", "alltoall_async", "reducescatter_async",
    "broadcast_object", "allgather_object", "broadcast_parameters",
    "broadcast_object_tree",
})
_RANK_CALLS = frozenset({"rank", "local_rank", "cross_rank",
                         "process_index"})

_KNOB_RE = re.compile(r"^(HOROVOD|HVD)_[A-Z0-9_]+$")

# Launcher/bootstrap plumbing: set by hvdrun / cluster managers per
# worker, read back by the core — not user-facing knobs, so not declared
# as Config fields (rank/size ARE fields, listed here for their env
# spellings' sake in non-config modules).
_BOOTSTRAP_VARS = frozenset({
    "HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
    "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE",
    "HOROVOD_COORDINATOR_ADDR", "HOROVOD_COORDINATOR_PORT",
    "HOROVOD_KV_ADDR", "HOROVOD_KV_PORT", "HOROVOD_KV_SHARD_PORTS",
    "HOROVOD_SECRET_KEY",
    "HOROVOD_HOSTNAME", "HOROVOD_HOST_KEY",
    "HOROVOD_ELASTIC_INIT_VERSION",
    # test-harness only
    "HVD_TEST_TIMEOUT",
})

# Recognized non-Config namespaces: bench-harness sweep parameters are
# set per-invocation by the external bench driver (bench.py reads them on
# the single process it runs on — nothing to propagate or document in the
# runtime knob catalogue). HOROVOD_FUSION_THRESHOLD-style runtime knobs
# must NOT move here.
# HVD_LOCK_* is the hvdrace runtime-witness namespace (HVD_LOCK_WITNESS,
# HVD_LOCK_WITNESS_FILE): diagnostic instrumentation toggled per-process
# by the person debugging, never launcher-propagated config.
_HARNESS_PREFIXES = ("HVD_BENCH_", "HVD_SENTINEL_", "HVD_LOCK_")

# HVL001/HVL006 (lock-held blocking call / sleep) are retired: hvdrace's
# HVR202 subsumes both with call-graph-aware hold propagation
# (horovod_tpu/analysis/race.py, docs/static_analysis.md).
_DEFAULT_RULES = frozenset(
    {"HVL002", "HVL003", "HVL004", "HVL005", "HVL007"})

# Modules allowed to WRITE ambient HOROVOD_*/HVD_* env (HVL003): the
# launcher stack (its whole job is exporting worker env), config
# plumbing, and harnesses that save/restore around subprocesses.
_ENV_WRITER_PATHS = ("runner/", "spark/", "ray/", "chaos/soak",
                     "elastic/worker", "flight/recorder", "tests/",
                     "scripts/", "examples/")

# Paths where HVL004 (rank-conditional collective) applies: user-facing
# code that check_program would flag at runtime-shape level. Library
# internals legitimately rank-branch around mirror dispatch / driver
# logic.
_USER_CODE_PATHS = ("examples/", "docs/")

_DISABLE_RE = re.compile(
    r"#\s*hvdlint:\s*disable=([A-Z0-9, ]+?)\s*(?:--\s*(.*))?$")
_SKIP_FILE_RE = re.compile(r"#\s*hvdlint:\s*skip-file\s*(?:--\s*(.*))?$")


@dataclasses.dataclass(frozen=True)
class LintFinding:
    code: str
    path: str
    line: int
    message: str

    def render(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_dict(self):
        return dataclasses.asdict(self)


def declared_knobs(config_path=None):
    """Parse ``common/config.py`` (AST only, no import) and return every
    env-var name it declares: string literals matching ``HOROVOD_*`` /
    ``HVD_*`` anywhere in the module (the ``from_env`` reads plus
    documented aliases)."""
    if config_path is None:
        config_path = os.path.join(os.path.dirname(__file__), os.pardir,
                                   "common", "config.py")
    try:
        with open(config_path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return frozenset()
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _KNOB_RE.match(node.value):
            names.add(node.value)
    return frozenset(names)


def propagated_knobs(launch_path=None, parser_path=None):
    """Every ``HOROVOD_*``/``HVD_*`` literal in the launcher's worker-env
    plumbing — ``runner/launch.py`` (build_worker_env's propagation
    tuple) plus ``runner/config_parser.py`` (the CLI arg → env map).
    That union is exactly the set of knobs a worker process can actually
    receive; HVL007 diffs the declared set against it."""
    here = os.path.dirname(__file__)
    if launch_path is None:
        launch_path = os.path.join(here, os.pardir, "runner", "launch.py")
    if parser_path is None:
        parser_path = os.path.join(here, os.pardir, "runner",
                                   "config_parser.py")
    names = set()
    for path in (launch_path, parser_path):
        try:
            with open(path) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _KNOB_RE.match(node.value):
                names.add(node.value)
    return frozenset(names)


def _call_name(node):
    """Terminal name of a call: ``f(...)`` -> f, ``a.b.c(...)`` -> c."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _env_read_name(node):
    """The env-var name a Call reads, or None: ``os.environ.get(K)``,
    ``os.environ[K]`` handled separately, ``os.getenv(K)``,
    ``_env_bool/int/float(K, ...)``."""
    name = _call_name(node)
    args = node.args
    if name in ("get", "pop") and args:
        # os.environ.get / environ.pop — require the receiver to mention
        # environ to avoid flagging dict.get("HOROVOD_X") on metrics maps
        recv = node.func.value if isinstance(node.func, ast.Attribute) \
            else None
        if recv is not None and "environ" in ast.dump(recv):
            return _const_str(args[0])
        return None
    if name == "getenv" and args:
        return _const_str(args[0])
    if name in ("_env_bool", "_env_int", "_env_float") and args:
        return _const_str(args[0])
    return None


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path, rel, source, declared, rules):
        self.path = path
        self.rel = rel
        self.lines = source.splitlines()
        self.declared = declared
        self.rules = rules
        self.findings = []
        self.suppressions = {}      # line -> (codes or None=all, reason)
        self.bad_suppressions = []
        self._def_lines = []        # enclosing def/with lines (suppression)
        self._collect_suppressions()

    # --- suppression bookkeeping ---------------------------------------

    def _collect_suppressions(self):
        for i, line in enumerate(self.lines, 1):
            m = _DISABLE_RE.search(line)
            if m:
                codes = frozenset(
                    c.strip() for c in m.group(1).split(",") if c.strip())
                reason = (m.group(2) or "").strip()
                if not reason:
                    self.bad_suppressions.append(i)
                self.suppressions[i] = (codes, reason)

    def _suppressed(self, code, line):
        for ln in (line, *self._def_lines):
            entry = self.suppressions.get(ln)
            if entry and (not entry[0] or code in entry[0]) and entry[1]:
                return True
        return False

    def _emit(self, code, node, message):
        if code not in self.rules:
            return
        line = getattr(node, "lineno", 1)
        if self._suppressed(code, line):
            return
        self.findings.append(
            LintFinding(code=code, path=self.rel, line=line,
                        message=message))

    # --- visitors -------------------------------------------------------

    def visit_FunctionDef(self, node):
        self._def_lines.append(node.lineno)
        self.generic_visit(node)
        self._def_lines.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        self._def_lines.append(node.lineno)
        self.generic_visit(node)
        self._def_lines.pop()

    def visit_Call(self, node):
        name = _call_name(node)
        env_name = _env_read_name(node)
        if env_name and _KNOB_RE.match(env_name) \
                and env_name not in self.declared \
                and env_name not in _BOOTSTRAP_VARS \
                and not env_name.startswith(_HARNESS_PREFIXES):
            self._emit("HVL002", node,
                       f"undeclared env knob {env_name}: declare it in "
                       "common/config.py::Config (launcher propagation + "
                       "docs catalogue) or it silently stays "
                       "single-process")
        if name == "Thread":
            kw = {k.arg for k in node.keywords}
            if "daemon" not in kw and not self._daemon_set_nearby(node):
                self._emit("HVL005", node,
                           "threading.Thread without daemon=True: a "
                           "forgotten non-daemon thread blocks "
                           "interpreter exit (register an explicit "
                           "shutdown path or mark it daemon)")
        self.generic_visit(node)

    def _daemon_set_nearby(self, node):
        """``t = Thread(...); t.daemon = True`` within a few lines."""
        window = range(node.lineno, min(node.lineno + 6,
                                        len(self.lines) + 1))
        return any(".daemon" in self.lines[i - 1] for i in window
                   if 0 < i <= len(self.lines))

    def visit_Subscript(self, node):
        # os.environ["K"] direct read (writes are Assign targets, handled
        # there under HVL003; Del is launcher cleanup)
        if isinstance(node.ctx, ast.Load) \
                and "environ" in ast.dump(node.value):
            key = _const_str(node.slice)
            if key and _KNOB_RE.match(key) \
                    and key not in self.declared \
                    and key not in _BOOTSTRAP_VARS \
                    and not key.startswith(_HARNESS_PREFIXES):
                self._emit("HVL002", node,
                           f"undeclared env knob {key}: declare it in "
                           "common/config.py::Config (launcher "
                           "propagation + docs catalogue) or it silently "
                           "stays single-process")
        self.generic_visit(node)

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._check_env_write(tgt, node)
        self.generic_visit(node)

    def _check_env_write(self, tgt, node):
        if not isinstance(tgt, ast.Subscript):
            return
        if "environ" not in ast.dump(tgt.value):
            return
        key = _const_str(tgt.slice) if not isinstance(tgt.slice, ast.Tuple) \
            else None
        if key and _KNOB_RE.match(key) and not self._env_writer_allowed():
            self._emit("HVL003", node,
                       f"ambient env write of {key} outside the launcher/"
                       "config layer: exported config must flow through "
                       "Config / build_worker_env")

    def _env_writer_allowed(self):
        rel = self.rel.replace(os.sep, "/")
        return any(p in rel for p in _ENV_WRITER_PATHS) \
            or rel.endswith("common/config.py")

    def visit_If(self, node):
        if self._rank_conditional(node.test) and self._user_code():
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and _call_name(sub) in _COLLECTIVE_CALLS:
                    self._emit(
                        "HVL004", sub,
                        f"rank-conditional collective "
                        f"{_call_name(sub)}() (if-gated on rank at line "
                        f"{node.lineno}): other ranks never enter the "
                        "dispatch and the job deadlocks — run "
                        "hvd.check_program on this step")
                    break
        self.generic_visit(node)

    def _user_code(self):
        rel = self.rel.replace(os.sep, "/")
        return any(p in rel for p in _USER_CODE_PATHS) \
            or rel.startswith("tests/") or "/tests/" in rel

    def _rank_conditional(self, test):
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) \
                    and _call_name(sub) in _RANK_CALLS:
                return True
        return False


def lint_source(source, rel_path="<string>", declared=None, rules=None,
                path=None, propagated=None):
    """Lint one source string; returns a list of :class:`LintFinding`.

    ``propagated`` is the launcher-exported knob set for HVL007 (parsed
    from the real launch/config_parser files when None); it is only
    consulted when ``rel_path`` is the Config module itself."""
    declared = declared if declared is not None else declared_knobs()
    rules = frozenset(rules) if rules else _DEFAULT_RULES
    first = source.split("\n", 2)[:2]
    for line in first:
        m = _SKIP_FILE_RE.search(line)
        if m:
            if (m.group(1) or "").strip():
                return []
            return [LintFinding(code="HVL000", path=rel_path, line=1,
                                message="skip-file without a reason "
                                        "(append `-- <why>`)")]
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [LintFinding(code="HVL999", path=rel_path,
                            line=e.lineno or 1,
                            message=f"syntax error: {e.msg}")]
    linter = _FileLinter(path or rel_path, rel_path, source, declared,
                         rules)
    linter.visit(tree)
    if "HVL007" in rules \
            and rel_path.replace(os.sep, "/").endswith("common/config.py"):
        if propagated is None:
            propagated = propagated_knobs()
        # First declaring line per knob: the anchor the fix lands on.
        decl_lines = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _KNOB_RE.match(node.value):
                decl_lines.setdefault(node.value, node.lineno)
        for knob in sorted(decl_lines):
            if knob in propagated or knob in _BOOTSTRAP_VARS \
                    or knob.startswith(_HARNESS_PREFIXES):
                continue
            line = decl_lines[knob]
            if linter._suppressed("HVL007", line):
                continue
            linter.findings.append(LintFinding(
                code="HVL007", path=rel_path, line=line,
                message=f"knob {knob} is declared in Config but never "
                        "exported by build_worker_env / the CLI arg map: "
                        "set on the driver, it silently stays unset on "
                        "every worker (add it to launch.py's propagation "
                        "tuple)"))
    for ln in linter.bad_suppressions:
        linter.findings.append(LintFinding(
            code="HVL000", path=rel_path, line=ln,
            message="hvdlint disable without a reason (append "
                    "`-- <why>`)"))
    return linter.findings


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths, declared=None, rules=None, base=None):
    """Lint files/trees; returns (findings, n_files)."""
    declared = declared if declared is not None else declared_knobs()
    findings, n = [], 0
    base = base or os.getcwd()
    for path in iter_py_files(paths):
        n += 1
        rel = os.path.relpath(path, base)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(LintFinding(code="HVL999", path=rel, line=1,
                                        message=str(e)))
            continue
        findings.extend(
            lint_source(source, rel_path=rel, declared=declared,
                        rules=rules, path=path))
    return findings, n


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.lint",
        description="hvdlint: static lint for distributed-runtime "
                    "hazards (see docs/static_analysis.md)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories (default: the "
                             "horovod_tpu package)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule codes to enable")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--config", default=None,
                        help="path to the Config module to parse for "
                             "declared knobs")
    args = parser.parse_args(argv)
    paths = args.paths or [os.path.join(os.path.dirname(__file__),
                                        os.pardir)]
    rules = frozenset(args.rules.split(",")) if args.rules else None
    t0 = time.monotonic()
    findings, n_files = lint_paths(
        paths, declared=declared_knobs(args.config), rules=rules)
    dt = time.monotonic() - t0
    if args.format == "json":
        print(json.dumps({"files": n_files, "seconds": round(dt, 3),
                          "findings": [f.to_dict() for f in findings]},
                         indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"hvdlint: {len(findings)} finding(s) in {n_files} files "
              f"({dt:.2f}s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
