"""Jaxpr traversal: extract the ordered in-jit collective sequence.

Walks a (closed) jaxpr in equation order, recursing into every
higher-order primitive that carries sub-jaxprs (``pjit``, ``shard_map``,
``scan``/``while``/``cond``, ``custom_jvp/vjp_call``, ``remat`` — found
generically by probing params for jaxpr-shaped values), and yields one
record per communication primitive: ``psum``/``pmin``/``pmax``/
``ppermute``/``all_gather``/``all_to_all``/``reduce_scatter``/``pgather``.

``pbroadcast``/``pvary`` are type-system bookkeeping (no bytes move) and
are excluded. A ``lax.scan`` multiplies its body's collectives by the
static trip count (``repeat``); a ``while`` has no static count, so its
body events carry ``repeat=0`` (unknown — excluded from exact sequence
hashes, still diffed for presence). ``cond`` branches are walked
separately so the caller can flag branch-divergent collectives (the
subset-participation deadlock class, see ``parallel/pp.py``).
"""

import dataclasses

# Primitive-name -> canonical op label. JAX renames across versions
# (psum/psum2/psum_invariant); canonicalize so findings and hashes are
# version-stable.
_WIRE_PRIMS = {
    "psum": "psum", "psum2": "psum", "psum_invariant": "psum",
    "pmin": "pmin", "pmax": "pmax",
    "ppermute": "ppermute", "pgather": "pgather",
    "all_gather": "all_gather", "all_gather_invariant": "all_gather",
    "all_to_all": "all_to_all",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
}


@dataclasses.dataclass(frozen=True)
class JitCollective:
    """One communication primitive found in a jaxpr."""

    op: str                 # canonical label ("psum", "all_gather", ...)
    axes: tuple             # named mesh axes it communicates over
    shapes: tuple           # operand shapes (per-device view)
    dtypes: tuple           # operand dtype strings
    axis_sizes: tuple       # size of each named axis (None = unknown)
    repeat: int = 1         # static trip count (0 = unknown: the event
    #                         sits under a while whose count is data-
    #                         dependent — HVP112, lower-bound costing)
    in_cond: bool = False   # inside a lax.cond branch
    branch: int = None      # which branch, when in_cond

    @property
    def nbytes(self):
        total = 0
        for s, d in zip(self.shapes, self.dtypes):
            n = 1
            for dim in s:
                n *= int(dim)
            total += n * _dtype_width(d)
        return total


def dtype_width(dtype_str):
    """Byte width of a dtype string (shared by the event byte counts and
    the analysis cost model's wire-byte formulas)."""
    s = str(dtype_str)
    for w, names in ((8, ("float64", "int64", "uint64", "complex64")),
                     (4, ("float32", "int32", "uint32")),
                     (2, ("float16", "bfloat16", "int16", "uint16")),
                     (1, ("int8", "uint8", "bool", "float8"))):
        if any(n in s for n in names):
            return w
    return 4


_dtype_width = dtype_width          # back-compat alias (pre-cost callers)


def _axis_names(eqn):
    """The named axes a communication eqn touches (params spell it
    ``axes``, ``axis_name`` or inside ``perm``-less ppermute params)."""
    p = eqn.params
    ax = p.get("axes", p.get("axis_name", ()))
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    # positional (int) axes of a pmap-free jaxpr aren't mesh axes
    return tuple(a for a in ax if isinstance(a, str))


def _sub_jaxprs(eqn):
    """Generic sub-jaxpr discovery: any param value that is (or wraps, or
    contains) something with ``.eqns``."""
    found = []
    for key, v in eqn.params.items():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            sub = getattr(item, "jaxpr", item)
            if hasattr(sub, "eqns"):
                found.append((key, sub))
    return found


def _scan_length(eqn):
    if eqn.primitive.name == "scan":
        return int(eqn.params.get("length", 1))
    return None


def iter_collectives(jaxpr, axis_sizes=None, repeat=1, in_cond=False,
                     branch=None):
    """Yield :class:`JitCollective` for every communication primitive in
    ``jaxpr`` (equation order, depth-first). ``axis_sizes`` maps axis name
    -> size, extended by ``shard_map`` meshes on the way down."""
    axis_sizes = dict(axis_sizes or {})
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        op = _WIRE_PRIMS.get(name)
        if op is not None:
            axes = _axis_names(eqn)
            avals = [getattr(v, "aval", None) for v in eqn.invars]
            shapes = tuple(tuple(getattr(a, "shape", ())) for a in avals)
            dtypes = tuple(str(getattr(a, "dtype", "")) for a in avals)
            yield JitCollective(
                op=op, axes=axes, shapes=shapes, dtypes=dtypes,
                axis_sizes=tuple(axis_sizes.get(a) for a in axes),
                repeat=repeat, in_cond=in_cond, branch=branch)
            continue
        subs = _sub_jaxprs(eqn)
        if not subs:
            continue
        sub_sizes = axis_sizes
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            shape = getattr(mesh, "shape", None)
            if shape:
                sub_sizes = dict(axis_sizes)
                sub_sizes.update({str(k): int(v)
                                  for k, v in dict(shape).items()})
        length = _scan_length(eqn)
        sub_repeat = repeat
        if length is not None:
            sub_repeat = repeat * length
        elif name == "while":
            sub_repeat = 0                      # unknown trip count
        is_cond = name == "cond"
        for i, (_key, sub) in enumerate(subs):
            yield from iter_collectives(
                sub, sub_sizes, repeat=sub_repeat,
                in_cond=in_cond or is_cond,
                branch=i if is_cond else branch)


def collect(closed_jaxpr, axis_sizes=None):
    """All communication primitives of a ``ClosedJaxpr`` (or raw jaxpr)."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    return list(iter_collectives(jaxpr, axis_sizes))
