"""``python -m horovod_tpu.analysis``: run the codebase lint (the program
analyzer is an API — ``hvd.check_program`` — since it needs your step
function and inputs; see docs/static_analysis.md)."""

import sys

from horovod_tpu.analysis.lint import main

sys.exit(main())
