// fp16/bf16 <-> fp32 conversion and fused accumulation.
//
// Reference: horovod/common/half.cc — HalfBits2Float/Float2HalfBits plus
// AVX/F16C vectorized fp16 sums used for custom MPI reductions. The TPU
// build's wire dtype is bfloat16 (same exponent range as fp32 — conversion
// is a shift with round-to-nearest-even), with fp16 kept for parity. Loops
// are written so the compiler auto-vectorizes (-O3 -march=native).

#include <cstring>

#include "api.h"

namespace {

inline uint16_t F32ToBf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // NaN-safe round-to-nearest-even (the TPU hardware rounding).
  if ((bits & 0x7fffffffu) > 0x7f800000u) {
    return static_cast<uint16_t>((bits >> 16) | 0x0040);
  }
  uint32_t rounding = 0x7fffu + ((bits >> 16) & 1u);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

inline float Bf16ToF32(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

// Software fp16 (IEEE binary16) conversion — reference: half.cc
// HalfBits2Float/Float2HalfBits branch structure.
inline float Fp16ToF32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1fu;
  uint32_t man = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;  // +-0
    } else {        // subnormal: normalize
      int e = -1;
      uint32_t m = man;
      do {
        ++e;
        m <<= 1;
      } while ((m & 0x400u) == 0);
      bits = sign | ((127 - 15 - e) << 23) | ((m & 0x3ffu) << 13);
    }
  } else if (exp == 0x1fu) {
    bits = sign | 0x7f800000u | (man << 13);  // inf/nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t F32ToFp16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint16_t sign = static_cast<uint16_t>((bits >> 16) & 0x8000u);
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xffu) - 127 + 15;
  uint32_t man = bits & 0x7fffffu;
  if (((bits >> 23) & 0xffu) == 0xffu) {  // inf/nan
    return sign | 0x7c00u | (man ? 0x200u | (man >> 13) : 0);
  }
  if (exp >= 0x1f) return sign | 0x7c00u;  // overflow -> inf
  if (exp <= 0) {
    if (exp < -10) return sign;  // underflow -> 0
    man |= 0x800000u;            // implicit bit
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = man >> shift;
    uint32_t rem = man & ((1u << shift) - 1);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (half & 1))) ++half;  // RNE
    return sign | static_cast<uint16_t>(half);
  }
  uint32_t half = (static_cast<uint32_t>(exp) << 10) | (man >> 13);
  uint32_t rem = man & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) ++half;  // RNE
  return sign | static_cast<uint16_t>(half);
}

}  // namespace

extern "C" {

void hvd_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] = F32ToBf16(src[i]);
}

void hvd_bf16_to_fp32(const uint16_t* src, float* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] = Bf16ToF32(src[i]);
}

void hvd_fp32_to_fp16(const float* src, uint16_t* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] = F32ToFp16(src[i]);
}

void hvd_fp16_to_fp32(const uint16_t* src, float* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] = Fp16ToF32(src[i]);
}

void hvd_bf16_accumulate(const uint16_t* src, uint16_t* dst, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    dst[i] = F32ToBf16(Bf16ToF32(dst[i]) + Bf16ToF32(src[i]));
  }
}

}  // extern "C"
