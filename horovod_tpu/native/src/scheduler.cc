// Native bucketing scheduler: the host-side analog of the reference's
// fusion/cycle machinery (reference: horovod/common/operations.cc:747-853
// RunLoopOnce bucket assembly, fusion_buffer_manager.h threshold accounting,
// response_cache.h:45 LRU of negotiated responses, group_table.h grouped
// collectives). On TPU the data plane is XLA; what remains hot on the host
// is the per-step bookkeeping for thousands of enqueued gradients — done
// here in C++ behind the ctypes API in api.h.

#include <cstdint>
#include <list>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

struct Pending {
  int64_t tensor_id;
  int64_t key_hash;   // bucket compatibility key (op/dtype/scales)
  int64_t nbytes;
};

struct Scheduler {
  std::mutex mu;
  int64_t threshold;
  int64_t cache_capacity;
  std::vector<Pending> pending;
  int64_t pending_bytes = 0;

  // Response cache: signature -> stable slot id, LRU eviction.
  std::unordered_map<int64_t, std::pair<int64_t, std::list<int64_t>::iterator>>
      cache;               // sig -> (slot, lru iterator)
  std::list<int64_t> lru;  // most-recent at front, holds signatures
  int64_t next_slot = 0;
  int64_t hits = 0;

  // Group table: tensor_id -> group id.
  std::unordered_map<int64_t, int64_t> group_of;
  std::unordered_map<int64_t, std::vector<int64_t>> groups;
  int64_t next_group = 0;
};

std::mutex g_mu;
std::unordered_map<int64_t, std::shared_ptr<Scheduler>> g_registry;
int64_t g_next_handle = 1;

// Copies the shared_ptr out under g_mu so a concurrent hvd_sched_destroy
// cannot free the Scheduler between handle lookup and use (the returned
// reference keeps it alive until the caller's call completes).
std::shared_ptr<Scheduler> get(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_registry.find(h);
  return it == g_registry.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t hvd_sched_create(int64_t threshold_bytes, int64_t cache_capacity) {
  auto s = std::make_shared<Scheduler>();
  s->threshold = threshold_bytes > 0 ? threshold_bytes : (64ll << 20);
  s->cache_capacity = cache_capacity > 0 ? cache_capacity : 1024;
  std::lock_guard<std::mutex> l(g_mu);
  int64_t h = g_next_handle++;
  g_registry[h] = std::move(s);
  return h;
}

void hvd_sched_destroy(int64_t h) {
  // The erased shared_ptr defers deletion until in-flight calls holding a
  // reference (from get()) drop theirs.
  std::shared_ptr<Scheduler> s;
  {
    std::lock_guard<std::mutex> l(g_mu);
    auto it = g_registry.find(h);
    if (it == g_registry.end()) return;
    s = std::move(it->second);
    g_registry.erase(it);
  }
}

void hvd_sched_set_threshold(int64_t h, int64_t threshold_bytes) {
  auto s = get(h);
  if (!s) return;
  std::lock_guard<std::mutex> l(s->mu);
  s->threshold = threshold_bytes;
}

// Returns 1 when accumulated bytes crossed the threshold (time to flush).
int32_t hvd_sched_enqueue(int64_t h, int64_t tensor_id, int64_t key_hash,
                          int64_t nbytes) {
  auto s = get(h);
  if (!s) return 0;
  std::lock_guard<std::mutex> l(s->mu);
  s->pending.push_back({tensor_id, key_hash, nbytes});
  s->pending_bytes += nbytes;
  return s->pending_bytes >= s->threshold ? 1 : 0;
}

int64_t hvd_sched_pending(int64_t h) {
  auto s = get(h);
  if (!s) return 0;
  std::lock_guard<std::mutex> l(s->mu);
  return static_cast<int64_t>(s->pending.size());
}

// Assign pending tensors to buckets: same key fuses together, a bucket is
// closed when it exceeds the threshold (the FuseResponses rule,
// reference: controller.cc FuseResponses packing up to
// TensorFusionThresholdBytes). Grouped tensors (group_table) always land in
// one bucket regardless of size, preserving grouped-collective atomicity.
// Writes tensor ids (enqueue order) and their bucket ids; returns the number
// of buckets, or -1 if cap is too small. Clears the pending queue.
int64_t hvd_sched_flush(int64_t h, int64_t* tensor_ids, int64_t* bucket_ids,
                        int64_t cap) {
  auto s = get(h);
  if (!s) return 0;
  std::lock_guard<std::mutex> l(s->mu);
  const int64_t n = static_cast<int64_t>(s->pending.size());
  if (n > cap) return -1;
  // (key or group-key) -> (bucket id, bytes so far)
  struct Open { int64_t id; int64_t bytes; };
  std::unordered_map<int64_t, Open> open;
  int64_t next_bucket = 0;
  for (int64_t i = 0; i < n; ++i) {
    const Pending& p = s->pending[i];
    auto git = s->group_of.find(p.tensor_id);
    // A group's bucket key derives from the group id ALONE (not the
    // per-tensor key), so every member of a group lands in one bucket even
    // when their compatibility keys differ — grouped-collective atomicity.
    // Grouped buckets are also exempt from the threshold split below.
    const bool grouped = git != s->group_of.end();
    const int64_t key = grouped
        ? static_cast<int64_t>(0x517cc1b727220a95ull ^
                               (static_cast<uint64_t>(git->second) *
                                0x2545f4914f6cdd1dull))
        : p.key_hash;
    auto it = open.find(key);
    if (it == open.end()) {
      open[key] = {next_bucket++, p.nbytes};
    } else if (!grouped && it->second.bytes + p.nbytes > s->threshold &&
               it->second.bytes > 0) {
      it->second = {next_bucket++, p.nbytes};
    } else {
      it->second.bytes += p.nbytes;
    }
    tensor_ids[i] = p.tensor_id;
    bucket_ids[i] = open[key].id;
  }
  s->pending.clear();
  s->pending_bytes = 0;
  return next_bucket;
}

// LRU response cache keyed by bucket signature. A hit returns the stable
// slot id (>= 0) and refreshes recency; a miss inserts (evicting the least
// recently used entry at capacity) and returns -1.
int64_t hvd_cache_lookup(int64_t h, int64_t signature) {
  auto s = get(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> l(s->mu);
  auto it = s->cache.find(signature);
  if (it != s->cache.end()) {
    s->lru.erase(it->second.second);
    s->lru.push_front(signature);
    it->second.second = s->lru.begin();
    ++s->hits;
    return it->second.first;
  }
  if (static_cast<int64_t>(s->cache.size()) >= s->cache_capacity) {
    int64_t victim = s->lru.back();
    s->lru.pop_back();
    s->cache.erase(victim);
  }
  s->lru.push_front(signature);
  s->cache[signature] = {s->next_slot++, s->lru.begin()};
  return -1;
}

int64_t hvd_cache_hits(int64_t h) {
  auto s = get(h);
  if (!s) return 0;
  std::lock_guard<std::mutex> l(s->mu);
  return s->hits;
}

int64_t hvd_cache_size(int64_t h) {
  auto s = get(h);
  if (!s) return 0;
  std::lock_guard<std::mutex> l(s->mu);
  return static_cast<int64_t>(s->cache.size());
}

// Group table (reference: group_table.h RegisterGroup/DeregisterGroups).
int64_t hvd_group_register(int64_t h, const int64_t* ids, int64_t n) {
  auto s = get(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> l(s->mu);
  int64_t gid = s->next_group++;
  auto& vec = s->groups[gid];
  vec.assign(ids, ids + n);
  for (int64_t i = 0; i < n; ++i) s->group_of[ids[i]] = gid;
  return gid;
}

int64_t hvd_group_of(int64_t h, int64_t tensor_id) {
  auto s = get(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> l(s->mu);
  auto it = s->group_of.find(tensor_id);
  return it == s->group_of.end() ? -1 : it->second;
}

void hvd_group_deregister(int64_t h, int64_t group_id) {
  auto s = get(h);
  if (!s) return;
  std::lock_guard<std::mutex> l(s->mu);
  auto it = s->groups.find(group_id);
  if (it == s->groups.end()) return;
  for (int64_t id : it->second) s->group_of.erase(id);
  s->groups.erase(it);
}

}  // extern "C"
