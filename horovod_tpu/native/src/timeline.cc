// Chrome-trace timeline writer with a background drain thread.
//
// Reference design (horovod/common/timeline.cc): producers enqueue fixed-size
// records into a lock-free queue; a writer thread drains and streams JSON so
// tracing never blocks the training path. Same shape here: a bounded MPMC
// ring (mutex-guarded head/tail — producers only copy a small POD under the
// lock) drained by one std::thread streaming the trace-event array
// incrementally to disk.

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "api.h"

namespace {

struct Event {
  char name[96];
  char cat[24];
  char ph;
  double ts_us;
  double dur_us;
  int64_t tid;
};

class TimelineWriter {
 public:
  explicit TimelineWriter(const char* path)
      : file_(std::fopen(path, "w")), stop_(false), count_(0) {
    if (!file_) return;
    std::fputs("{\"displayTimeUnit\":\"ms\",\"traceEvents\":[", file_);
    thread_ = std::thread([this] { Drain(); });
  }

  bool ok() const { return file_ != nullptr; }

  void Record(const Event& ev) {
    {
      std::lock_guard<std::mutex> g(mu_);
      queue_.push_back(ev);
    }
    cv_.notify_one();
  }

  int64_t Count() const { return count_.load(); }

  void Close() {
    {
      std::lock_guard<std::mutex> g(mu_);
      stop_ = true;
    }
    cv_.notify_one();
    if (thread_.joinable()) thread_.join();
    if (file_) {
      std::fputs("]}", file_);
      std::fclose(file_);
      file_ = nullptr;
    }
  }

  ~TimelineWriter() { Close(); }

 private:
  void Drain() {
    bool first = true;
    std::vector<Event> local;
    for (;;) {
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [this] { return stop_ || !queue_.empty(); });
        local.swap(queue_);
        if (local.empty() && stop_) return;
      }
      for (const auto& ev : local) Write(ev, &first);
      std::fflush(file_);
      local.clear();
    }
  }

  static void JsonEscape(const char* in, char* out, size_t cap) {
    size_t j = 0;
    for (size_t i = 0; in[i] && j + 2 < cap; ++i) {
      char c = in[i];
      if (c == '"' || c == '\\') out[j++] = '\\';
      if (static_cast<unsigned char>(c) < 0x20) c = ' ';
      out[j++] = c;
    }
    out[j] = 0;
  }

  void Write(const Event& ev, bool* first) {
    char name[200], cat[48];
    JsonEscape(ev.name, name, sizeof(name));
    JsonEscape(ev.cat, cat, sizeof(cat));
    if (!*first) std::fputc(',', file_);
    *first = false;
    if (ev.ph == 'X') {
      std::fprintf(file_,
                   "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"X\",\"ts\":%.3f,"
                   "\"dur\":%.3f,\"pid\":0,\"tid\":%lld}",
                   name, cat, ev.ts_us, ev.dur_us,
                   static_cast<long long>(ev.tid));
    } else {
      std::fprintf(file_,
                   "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"%c\",\"ts\":%.3f,"
                   "\"pid\":0,\"tid\":%lld,\"s\":\"g\"}",
                   name, cat, ev.ph, ev.ts_us,
                   static_cast<long long>(ev.tid));
    }
    count_.fetch_add(1);
  }

  std::FILE* file_;
  std::thread thread_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<Event> queue_;
  bool stop_;
  std::atomic<int64_t> count_;
};

std::mutex g_registry_mu;
std::unordered_map<int64_t, TimelineWriter*> g_registry;
int64_t g_next_handle = 1;

}  // namespace

extern "C" {

int64_t hvd_timeline_create(const char* path) {
  auto* w = new TimelineWriter(path);
  if (!w->ok()) {
    delete w;
    return 0;
  }
  std::lock_guard<std::mutex> g(g_registry_mu);
  int64_t h = g_next_handle++;
  g_registry[h] = w;
  return h;
}

void hvd_timeline_record(int64_t handle, const char* name, const char* cat,
                         char ph, double ts_us, double dur_us, int64_t tid) {
  Event ev;
  std::snprintf(ev.name, sizeof(ev.name), "%s", name ? name : "");
  std::snprintf(ev.cat, sizeof(ev.cat), "%s", cat ? cat : "");
  ev.ph = ph;
  ev.ts_us = ts_us;
  ev.dur_us = dur_us;
  ev.tid = tid;
  // Record under the registry lock: hvd_timeline_close erases + deletes the
  // writer under the same lock, so a concurrent close can't free the writer
  // out from under us. Record() only copies a POD into the queue, so the
  // critical section stays short.
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_registry.find(handle);
  if (it == g_registry.end()) return;
  it->second->Record(ev);
}

int64_t hvd_timeline_count(int64_t handle) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_registry.find(handle);
  return it == g_registry.end() ? -1 : it->second->Count();
}

void hvd_timeline_close(int64_t handle) {
  TimelineWriter* w = nullptr;
  {
    std::lock_guard<std::mutex> g(g_registry_mu);
    auto it = g_registry.find(handle);
    if (it == g_registry.end()) return;
    w = it->second;
    g_registry.erase(it);
  }
  w->Close();
  delete w;
}

}  // extern "C"
