// extern "C" interface of libhvdtpu — the native host-side runtime pieces.
//
// Parity with the reference's native core where native genuinely helps a TPU
// runtime (the device data plane is XLA; these are the host-side hot paths):
//  - timeline: lock-minimal event recording + background writer thread
//    (reference: horovod/common/timeline.cc, 678 LoC, boost SPSC + writer)
//  - half/bf16: vectorizable fp16/bf16 <-> fp32 conversion and fused
//    accumulate (reference: horovod/common/half.cc AVX F16C paths)
//  - adasum: the scale-invariant combine, the CPU ground truth used to
//    validate device numerics (reference: horovod/common/ops/adasum/adasum.h)

#pragma once
#include <cstdint>

extern "C" {

// ---- timeline ----
// Returns an opaque handle (>0) or 0 on failure.
int64_t hvd_timeline_create(const char* path);
// ph: 'X' complete event (dur_us used), 'i' instant.
void hvd_timeline_record(int64_t handle, const char* name, const char* cat,
                         char ph, double ts_us, double dur_us, int64_t tid);
// Flush + finalize JSON; invalidates the handle.
void hvd_timeline_close(int64_t handle);
// Number of events written so far (for tests/diagnostics).
int64_t hvd_timeline_count(int64_t handle);

// ---- half / bf16 ----
void hvd_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n);
void hvd_bf16_to_fp32(const uint16_t* src, float* dst, int64_t n);
void hvd_fp32_to_fp16(const float* src, uint16_t* dst, int64_t n);
void hvd_fp16_to_fp32(const uint16_t* src, float* dst, int64_t n);
// dst += src elementwise, accumulating in fp32 (wire-dtype host reduction).
void hvd_bf16_accumulate(const uint16_t* src, uint16_t* dst, int64_t n);

// ---- adasum ----
// out = (1 - dot/(2*||a||^2)) a + (1 - dot/(2*||b||^2)) b, fp32.
void hvd_adasum_combine(const float* a, const float* b, float* out,
                        int64_t n);

}  // extern "C"
