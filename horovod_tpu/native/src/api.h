// extern "C" interface of libhvdtpu — the native host-side runtime pieces.
//
// Parity with the reference's native core where native genuinely helps a TPU
// runtime (the device data plane is XLA; these are the host-side hot paths):
//  - timeline: lock-minimal event recording + background writer thread
//    (reference: horovod/common/timeline.cc, 678 LoC, boost SPSC + writer)
//  - half/bf16: vectorizable fp16/bf16 <-> fp32 conversion and fused
//    accumulate (reference: horovod/common/half.cc AVX F16C paths)
//  - adasum: the scale-invariant combine, the CPU ground truth used to
//    validate device numerics (reference: horovod/common/ops/adasum/adasum.h)

#pragma once
#include <cstdint>

extern "C" {

// ---- timeline ----
// Returns an opaque handle (>0) or 0 on failure.
int64_t hvd_timeline_create(const char* path);
// ph: 'X' complete event (dur_us used), 'i' instant.
void hvd_timeline_record(int64_t handle, const char* name, const char* cat,
                         char ph, double ts_us, double dur_us, int64_t tid);
// Flush + finalize JSON; invalidates the handle.
void hvd_timeline_close(int64_t handle);
// Number of events written so far (for tests/diagnostics).
int64_t hvd_timeline_count(int64_t handle);

// ---- half / bf16 ----
void hvd_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n);
void hvd_bf16_to_fp32(const uint16_t* src, float* dst, int64_t n);
void hvd_fp32_to_fp16(const float* src, uint16_t* dst, int64_t n);
void hvd_fp16_to_fp32(const uint16_t* src, float* dst, int64_t n);
// dst += src elementwise, accumulating in fp32 (wire-dtype host reduction).
void hvd_bf16_accumulate(const uint16_t* src, uint16_t* dst, int64_t n);

// ---- adasum ----
// out = (1 - dot/(2*||a||^2)) a + (1 - dot/(2*||b||^2)) b, fp32.
void hvd_adasum_combine(const float* a, const float* b, float* out,
                        int64_t n);

// ---- bucketing scheduler / response cache / group table ----
// (reference: operations.cc:747-853 cycle bucket assembly,
//  response_cache.h:45 LRU, group_table.h)
int64_t hvd_sched_create(int64_t threshold_bytes, int64_t cache_capacity);
void hvd_sched_destroy(int64_t handle);
void hvd_sched_set_threshold(int64_t handle, int64_t threshold_bytes);
// Returns 1 when accumulated pending bytes crossed the threshold.
int32_t hvd_sched_enqueue(int64_t handle, int64_t tensor_id,
                          int64_t key_hash, int64_t nbytes);
int64_t hvd_sched_pending(int64_t handle);
// Fills tensor_ids/bucket_ids (cap entries available); returns bucket count
// or -1 if cap too small. Clears the pending queue.
int64_t hvd_sched_flush(int64_t handle, int64_t* tensor_ids,
                        int64_t* bucket_ids, int64_t cap);
// LRU cache: hit -> stable slot id (>=0) and recency refresh; miss -> -1
// (inserted, evicting LRU at capacity).
int64_t hvd_cache_lookup(int64_t handle, int64_t signature);
int64_t hvd_cache_hits(int64_t handle);
int64_t hvd_cache_size(int64_t handle);
int64_t hvd_group_register(int64_t handle, const int64_t* tensor_ids,
                           int64_t n);
int64_t hvd_group_of(int64_t handle, int64_t tensor_id);
void hvd_group_deregister(int64_t handle, int64_t group_id);

}  // extern "C"
