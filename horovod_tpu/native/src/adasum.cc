// Adasum combine — CPU ground truth for validating device numerics.
//
// Reference: horovod/common/ops/adasum/adasum.h:103+ — the scale-invariant
// pairwise combination. Single pass computes the three reductions (a.b,
// ||a||^2, ||b||^2); the compiler vectorizes the loops under -O3.

#include <cmath>

#include "api.h"

extern "C" {

void hvd_adasum_combine(const float* a, const float* b, float* out,
                        int64_t n) {
  double dot = 0.0, na = 0.0, nb = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    dot += static_cast<double>(a[i]) * b[i];
    na += static_cast<double>(a[i]) * a[i];
    nb += static_cast<double>(b[i]) * b[i];
  }
  const double eps = 1e-30;
  double ca = na > eps ? 1.0 - dot / (2.0 * na) : 1.0;
  double cb = nb > eps ? 1.0 - dot / (2.0 * nb) : 1.0;
  for (int64_t i = 0; i < n; ++i) {
    out[i] = static_cast<float>(ca * a[i] + cb * b[i]);
  }
}

}  // extern "C"
