"""ctypes loader for libhvdtpu, the native host-side runtime.

Builds the shared library on first import when a toolchain is present
(make + g++); everything degrades gracefully to the pure-Python paths when it
isn't — mirroring how the reference gates features on what was compiled in
(reference: horovod_*_built checks, operations.cc:1307-1449).
"""

import ctypes
import os
import subprocess
import threading

from horovod_tpu.common import logging as hvd_logging

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libhvdtpu.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _stale():
    """True when libhvdtpu.so predates any native source (or is absent).

    The .so is a gitignored build artifact, so a checkout that updates
    src/ keeps whatever binary an earlier build left behind — and ctypes
    would happily load it. That was the root of the long-tailed
    "escapes_json" timeline flake: a stale writer built before the
    JsonEscape backslash case shipped kept serving whichever session
    (and whichever test order) imported native first, until an unrelated
    missing-symbol AttributeError forced a rebuild. Compare mtimes like
    make would and rebuild eagerly instead."""
    try:
        built = os.path.getmtime(_LIB_PATH)
    except OSError:
        return True
    srcs = [os.path.join(_HERE, "Makefile")]
    src_dir = os.path.join(_HERE, "src")
    try:
        srcs += [os.path.join(src_dir, f) for f in os.listdir(src_dir)]
    except OSError:
        pass
    try:
        return any(os.path.getmtime(s) > built for s in srcs)
    except OSError:
        return True


def _build():
    # Build into a process-private target and publish with an atomic rename,
    # so concurrent first-use builds (multiple workers, shared NFS checkout)
    # can never leave a torn libhvdtpu.so behind.
    tmp = f"libhvdtpu.{os.getpid()}.so"
    try:
        subprocess.run(["make", "-C", _HERE, "-s", f"TARGET={tmp}"],
                       check=True, capture_output=True, timeout=120)
        os.replace(os.path.join(_HERE, tmp), _LIB_PATH)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError,
            subprocess.TimeoutExpired) as e:
        out = getattr(e, "stderr", b"") or b""
        hvd_logging.debug("native build unavailable: %s %s", e,
                          out.decode(errors="replace")[-500:])
        try:
            os.unlink(os.path.join(_HERE, tmp))
        except OSError:
            pass
        return False


def _bind(lib):
    lib.hvd_timeline_create.restype = ctypes.c_int64
    lib.hvd_timeline_create.argtypes = [ctypes.c_char_p]
    lib.hvd_timeline_record.restype = None
    lib.hvd_timeline_record.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char,
        ctypes.c_double, ctypes.c_double, ctypes.c_int64]
    lib.hvd_timeline_close.restype = None
    lib.hvd_timeline_close.argtypes = [ctypes.c_int64]
    lib.hvd_timeline_count.restype = ctypes.c_int64
    lib.hvd_timeline_count.argtypes = [ctypes.c_int64]
    u16p = ctypes.POINTER(ctypes.c_uint16)
    f32p = ctypes.POINTER(ctypes.c_float)
    for name, argtypes in [
        ("hvd_fp32_to_bf16", [f32p, u16p, ctypes.c_int64]),
        ("hvd_bf16_to_fp32", [u16p, f32p, ctypes.c_int64]),
        ("hvd_fp32_to_fp16", [f32p, u16p, ctypes.c_int64]),
        ("hvd_fp16_to_fp32", [u16p, f32p, ctypes.c_int64]),
        ("hvd_bf16_accumulate", [u16p, u16p, ctypes.c_int64]),
        ("hvd_adasum_combine", [f32p, f32p, f32p, ctypes.c_int64]),
    ]:
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = argtypes
    i64 = ctypes.c_int64
    i64p = ctypes.POINTER(ctypes.c_int64)
    for name, restype, argtypes in [
        ("hvd_sched_create", i64, [i64, i64]),
        ("hvd_sched_destroy", None, [i64]),
        ("hvd_sched_set_threshold", None, [i64, i64]),
        ("hvd_sched_enqueue", ctypes.c_int32, [i64, i64, i64, i64]),
        ("hvd_sched_pending", i64, [i64]),
        ("hvd_sched_flush", i64, [i64, i64p, i64p, i64]),
        ("hvd_cache_lookup", i64, [i64, i64]),
        ("hvd_cache_hits", i64, [i64]),
        ("hvd_cache_size", i64, [i64]),
        ("hvd_group_register", i64, [i64, i64p, i64]),
        ("hvd_group_of", i64, [i64, i64]),
        ("hvd_group_deregister", None, [i64, i64]),
    ]:
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
    return lib


def get_lib():
    """The loaded library, or None when native support is unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _stale() and not _build() and not os.path.exists(_LIB_PATH):
            return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
            hvd_logging.debug("loaded native runtime %s", _LIB_PATH)
        except (OSError, AttributeError) as e:
            # AttributeError: a stale prebuilt .so missing newer symbols.
            # Rebuild, then load under a unique path — dlopen caches by path
            # string, so reloading _LIB_PATH would return the stale handle.
            hvd_logging.debug("native runtime stale/unloadable (%s); "
                              "rebuilding", e)
            _lib = None
            if not _build():
                hvd_logging.warning(
                    "failed to load native runtime (%s) and rebuild is "
                    "unavailable; using Python fallbacks", e)
            else:
                import shutil
                import tempfile
                fd, tmppath = tempfile.mkstemp(suffix=".so",
                                               prefix="libhvdtpu.reload.")
                os.close(fd)
                try:
                    shutil.copy2(_LIB_PATH, tmppath)
                    _lib = _bind(ctypes.CDLL(tmppath))
                    hvd_logging.debug("reloaded native runtime via %s",
                                      tmppath)
                except (OSError, AttributeError) as e2:  # pragma: no cover
                    hvd_logging.warning(
                        "failed to load native runtime: %s", e2)
                    _lib = None
                finally:
                    try:
                        os.unlink(tmppath)  # handle stays valid on Linux
                    except OSError:
                        pass
        return _lib


def native_built():
    return get_lib() is not None


# ---- numpy-facing convenience wrappers ----

def _as_ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _require_lib():
    lib = get_lib()
    if lib is None:
        raise RuntimeError(
            "native runtime not built (no working make/g++ toolchain); "
            "check HOROVOD_LOG_LEVEL=debug for the build error")
    return lib


def fp32_to_bf16(src):
    import numpy as np
    lib = _require_lib()
    src = np.ascontiguousarray(src, np.float32)
    out = np.empty(src.shape, np.uint16)
    lib.hvd_fp32_to_bf16(_as_ptr(src, ctypes.c_float),
                         _as_ptr(out, ctypes.c_uint16), src.size)
    return out


def bf16_to_fp32(src):
    import numpy as np
    lib = _require_lib()
    src = np.ascontiguousarray(src, np.uint16)
    out = np.empty(src.shape, np.float32)
    lib.hvd_bf16_to_fp32(_as_ptr(src, ctypes.c_uint16),
                         _as_ptr(out, ctypes.c_float), src.size)
    return out


def fp32_to_fp16(src):
    import numpy as np
    lib = _require_lib()
    src = np.ascontiguousarray(src, np.float32)
    out = np.empty(src.shape, np.uint16)
    lib.hvd_fp32_to_fp16(_as_ptr(src, ctypes.c_float),
                         _as_ptr(out, ctypes.c_uint16), src.size)
    return out


def fp16_to_fp32(src):
    import numpy as np
    lib = _require_lib()
    src = np.ascontiguousarray(src, np.uint16)
    out = np.empty(src.shape, np.float32)
    lib.hvd_fp16_to_fp32(_as_ptr(src, ctypes.c_uint16),
                         _as_ptr(out, ctypes.c_float), src.size)
    return out


def bf16_accumulate(src, dst):
    """dst += src on bf16 (uint16-viewed) buffers, accumulating in fp32 —
    host-side wire-dtype accumulation (reference: half.cc fp16 sum ops).
    Returns the accumulated buffer (``dst`` itself when it was already a
    contiguous uint16 array, else a copy)."""
    import numpy as np
    lib = _require_lib()
    src = np.ascontiguousarray(src, np.uint16)
    dst = np.ascontiguousarray(dst, np.uint16)
    if src.size != dst.size:
        raise ValueError(
            f"bf16_accumulate: size mismatch src={src.size} dst={dst.size}")
    lib.hvd_bf16_accumulate(_as_ptr(src, ctypes.c_uint16),
                            _as_ptr(dst, ctypes.c_uint16), src.size)
    return dst


def adasum_combine(a, b):
    import numpy as np
    lib = _require_lib()
    a = np.ascontiguousarray(a, np.float32)
    b = np.ascontiguousarray(b, np.float32)
    if a.size != b.size:
        raise ValueError(
            f"adasum_combine: size mismatch a={a.size} b={b.size}")
    out = np.empty(a.shape, np.float32)
    lib.hvd_adasum_combine(_as_ptr(a, ctypes.c_float),
                           _as_ptr(b, ctypes.c_float),
                           _as_ptr(out, ctypes.c_float), a.size)
    return out


class NativeTimeline:
    """Chrome-trace writer backed by the C++ drain thread."""

    def __init__(self, path):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime not built")
        self._lib = lib
        self._handle = lib.hvd_timeline_create(path.encode())
        if self._handle == 0:
            raise OSError(f"cannot open timeline file {path}")

    def record(self, name, cat, ph, ts_us, dur_us=0.0, tid=0):
        self._lib.hvd_timeline_record(
            self._handle, name.encode(), cat.encode(),
            ph.encode() if isinstance(ph, str) else ph,
            float(ts_us), float(dur_us), int(tid))

    def count(self):
        return self._lib.hvd_timeline_count(self._handle)

    def close(self):
        if self._handle:
            self._lib.hvd_timeline_close(self._handle)
            self._handle = 0


class BucketScheduler:
    """Native bucketing scheduler + LRU response cache + group table
    (reference: the C++ cycle-loop bucket assembly operations.cc:747-853,
    response_cache.h:45, group_table.h). Raises when the native runtime is
    unavailable — callers fall back to the Python path."""

    def __init__(self, threshold_bytes, cache_capacity=1024):
        self._lib = _require_lib()
        self._h = self._lib.hvd_sched_create(int(threshold_bytes),
                                             int(cache_capacity))

    def set_threshold(self, threshold_bytes):
        self._lib.hvd_sched_set_threshold(self._h, int(threshold_bytes))

    def enqueue(self, tensor_id, key_hash, nbytes):
        """True when the accumulated bytes crossed the threshold."""
        return bool(self._lib.hvd_sched_enqueue(
            self._h, int(tensor_id), int(key_hash), int(nbytes)))

    def pending(self):
        return int(self._lib.hvd_sched_pending(self._h))

    def flush(self):
        """-> dict tensor_id -> bucket_id (enqueue order preserved)."""
        import numpy as np
        n = self.pending()
        if n == 0:
            return {}
        tids = np.empty(n, np.int64)
        bids = np.empty(n, np.int64)
        nb = self._lib.hvd_sched_flush(
            self._h, _as_ptr(tids, ctypes.c_int64),
            _as_ptr(bids, ctypes.c_int64), n)
        if nb < 0:  # pragma: no cover - cap == pending() by construction
            raise RuntimeError("scheduler flush capacity mismatch")
        return dict(zip(tids.tolist(), bids.tolist()))

    def cache_lookup(self, signature):
        """Stable slot id on hit, -1 on miss (inserted)."""
        return int(self._lib.hvd_cache_lookup(self._h, int(signature)))

    def cache_stats(self):
        return {"hits": int(self._lib.hvd_cache_hits(self._h)),
                "size": int(self._lib.hvd_cache_size(self._h))}

    def register_group(self, tensor_ids):
        import numpy as np
        ids = np.asarray(list(tensor_ids), np.int64)
        return int(self._lib.hvd_group_register(
            self._h, _as_ptr(ids, ctypes.c_int64), ids.size))

    def group_of(self, tensor_id):
        return int(self._lib.hvd_group_of(self._h, int(tensor_id)))

    def deregister_group(self, group_id):
        self._lib.hvd_group_deregister(self._h, int(group_id))

    def close(self):
        if getattr(self, "_h", 0):
            self._lib.hvd_sched_destroy(self._h)
            self._h = 0

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
