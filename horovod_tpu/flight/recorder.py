"""Always-armed collective flight recorder: a fixed-size ring of structured
events, dumped as JSONL exactly when today you would get nothing.

Reference motivation: the reference's observability is opt-in — the Chrome
timeline must be armed before the run, stall findings live in logs — so a
wedge, a desync or a straggler leaves no artifact unless it was predicted
(PAPER §5.1-5.2). "Collective Communication for 100k+ GPUs"
(PAPERS.md: arxiv 2510.20171) describes the tool that actually works at
scale: an always-on bounded event recorder whose per-rank dumps are merged
after the fact to localize which rank diverged. This module is that
recorder; :mod:`horovod_tpu.flight.analyze` is the merge/forensics half.

Design constraints (the hot path is the eager collective dispatch, the
same budget class as the metrics registry):

- one module-level bool gate (``recorder.armed``) — instrumentation sites
  read it and skip everything else when the recorder is off;
- preallocated slots: the ring is ``capacity`` fixed-length lists created
  up front; an append is one short lock, an index bump and field stores —
  no allocation, no I/O, nothing held across RPC or flush boundaries;
- the per-process-set collective sequence number is assigned under the
  same lock as the append (one acquisition per event).

Event kinds (dumped JSONL; schema in docs/observability.md):

- ``dispatch`` / ``complete`` / ``error`` — eager + plan collective
  dispatches (op, process set, monotonic per-set ``seq``, byte count,
  stable signature hash) and their completions (host latency) or failures
- ``fusion_enqueue`` / ``fusion_flush``   — fusion-runtime boundaries
- ``negotiation``                         — control-plane exchange rounds
- ``kv_retry`` / ``kv_error``             — HTTP-KV transport faults
- ``elastic``                             — reset/restore/host_update/
  abort/rendezvous/... transitions (mirrored from the metrics sites)
- ``stall``                               — stall-inspector findings
- ``chaos``                               — chaos-ledger injections
- ``step``                                — user/optimizer step markers

Knobs: ``HOROVOD_FLIGHT_RECORDER`` (default on), ``HOROVOD_FLIGHT_CAPACITY``
(default 4096 events), ``HOROVOD_FLIGHT_DIR`` (dump directory, default
``flight_dumps``; ``hvdrun --flight-dir`` propagates it).

Dump triggers (each writes one
``flight_<role>_r<rank>_p<pid>_b<boot>_<n>.jsonl``):
stall-inspector warning/shutdown, the membership watchdog abort, the
eager/plan dispatch failure epilogue (every ``HorovodInternalError``
translation), chaos ``crash`` (the victim's last words), SIGTERM/atexit
during an elastic launch, ``SIGUSR2`` on demand, and
``GET /debug/flight`` on the metrics scrape endpoint (no file — the ring
is served directly).

The ring is process-global and deliberately survives ``hvd.shutdown()`` /
re-``init()`` cycles: elastic recovery re-initializes in place, and the
events you need are the ones from BEFORE the failure.
"""

import json
import os
import threading
import time
import zlib

# Shared env parsing (no import cycle: config imports only stdlib) —
# import-time arming and init-time configure() must read
# HOROVOD_FLIGHT_RECORDER identically.
from horovod_tpu.common.config import _env_bool, _env_int
# Trace-context injection (no cycle: trace imports only common.config).
# Every ring event carries the ACTIVE trace ref, so flight.analyze can
# reconstruct one request/step across ranks keyed by the per-process-set
# collective seq.
from horovod_tpu import trace as _trace

DEFAULT_CAPACITY = 4096
DEFAULT_DUMP_DIR = "flight_dumps"
# Runaway guards: a recovery storm may hit one failure epilogue many
# times. The cap is PER REASON (a storm of dispatch_error dumps must not
# spend the budget of the one later membership_abort/stall_shutdown the
# post-mortem actually needs), with a global backstop; every reason is
# also throttled to one dump per second.
MAX_DUMPS_PER_REASON = 8
MAX_DUMPS = 64
_DUMP_MIN_INTERVAL_S = 1.0

# The one-word hot-path gate (the chaos-injector idiom): sites read
# ``recorder.armed`` and skip everything else when False.
armed = _env_bool("HOROVOD_FLIGHT_RECORDER", True)

# Per-process boot token: process identity for dumps. A pid alone can be
# recycled by the OS within one elastic run — the analyzer would then pin
# the replacement's events to the dead process's rank and drop them as
# ring-index duplicates, and a same-(rank, pid, n) dump file would
# OVERWRITE the victim's crash dump.
_BOOT = format(int(time.time() * 1e6) & 0xffffffff, "08x")

# Slot layout (fixed-length lists, preallocated):
_F_TS, _F_KIND, _F_OP, _F_PS, _F_SEQ, _F_BYTES, _F_SIG, _F_NAME, _F_DUR, \
    _F_WHAT, _F_TRACE = range(11)
_N_FIELDS = 11
_KEYS = ("t", "kind", "op", "ps", "seq", "bytes", "sig", "name", "dur",
         "what", "trace")


def _env_capacity():
    return _env_int("HOROVOD_FLIGHT_CAPACITY", DEFAULT_CAPACITY)


def _rank():
    return _env_int("HOROVOD_CROSS_RANK", 0)


def _host():
    """Host identity for the dump meta: pids are only unique per host, so
    the analyzer's process key is (host, pid). The launcher's host key
    beats gethostname — loopback multi-"host" launches alias one name."""
    h = os.environ.get("HOROVOD_HOST_KEY")
    if h:
        return h
    import socket
    try:
        return socket.gethostname()
    except OSError:
        return ""


_role = "worker"


def set_role(role):
    """Tag this process's dumps (``worker`` / ``driver``)."""
    global _role
    _role = role


class FlightRecorder:
    """The ring itself. Normally used through the module-level singleton
    (:func:`get`); tests construct small-capacity instances directly."""

    def __init__(self, capacity=None):
        self.capacity = max(int(capacity or _env_capacity()), 8)
        self._slots = [[None] * _N_FIELDS for _ in range(self.capacity)]
        self._idx = 0                   # total appends (monotonic)
        self._lock = threading.Lock()
        self._seq = {}                  # process-set label -> last seq
        self._auto_step = 0             # optimizer-wrapper step counter
        self.saw_explicit_step = False  # explicit marks suppress auto marks

    # --- recording (the hot path) --------------------------------------

    def record_dispatch(self, op, ps, nbytes, sig, name=None):
        """One collective dispatch; assigns and returns the per-process-set
        monotonic sequence number."""
        with self._lock:
            seq = self._seq.get(ps, 0) + 1
            self._seq[ps] = seq
            s = self._slots[self._idx % self.capacity]
            # kind is the slot's commit marker: cleared FIRST, stored
            # LAST. The post-timeout unlocked read in events() may observe
            # a mid-append slot; with kind=None it reads as torn and is
            # dropped — never as a hybrid of this event's leading fields
            # and the previous occupant's trailing ones.
            s[_F_KIND] = None
            self._idx += 1
            s[_F_TS] = time.time()
            s[_F_OP] = op
            s[_F_PS] = ps
            s[_F_SEQ] = seq
            s[_F_BYTES] = nbytes
            s[_F_SIG] = sig
            s[_F_NAME] = name
            s[_F_DUR] = None
            s[_F_WHAT] = None
            s[_F_TRACE] = _trace.get_active()
            s[_F_KIND] = "dispatch"
        return seq

    def record_complete(self, op, ps, seq, dur):
        """Successful completion of the dispatch that got ``seq``; ``dur``
        is the host-side dispatch latency in seconds."""
        self.record_event("complete", op=op, ps=ps, seq=seq, dur=dur)

    def record_event(self, kind, op=None, ps=None, seq=None, nbytes=None,
                     sig=None, name=None, dur=None, what=None, trace=None):
        with self._lock:
            s = self._slots[self._idx % self.capacity]
            s[_F_KIND] = None       # commit marker: see record_dispatch
            self._idx += 1
            s[_F_TS] = time.time()
            s[_F_OP] = op
            s[_F_PS] = ps
            s[_F_SEQ] = seq
            s[_F_BYTES] = nbytes
            s[_F_SIG] = sig
            s[_F_NAME] = name
            s[_F_DUR] = dur
            s[_F_WHAT] = what
            # Explicit ref (the serving engine passes its request's tid —
            # handler threads never hold the active ref) beats the
            # thread-local active trace.
            s[_F_TRACE] = trace if trace is not None \
                else _trace.get_active()
            s[_F_KIND] = kind

    # --- reading -------------------------------------------------------

    def events(self):
        """Ring contents oldest-first as dicts (None fields omitted), each
        carrying its global append index ``i`` so overlapping dumps from
        one process can be merged without double counting.

        Bounded, not blocking: dumps run from signal handlers, which the
        interpreter executes on the MAIN thread between bytecodes — if the
        signal landed while that same thread held ``_lock`` inside an
        append, a blocking acquire would self-deadlock. After the timeout
        the ring is read unlocked: a torn in-progress row is acceptable
        forensics, a wedged shutdown handler is not."""
        locked = self._lock.acquire(timeout=0.5)
        try:
            idx = self._idx
            count = min(idx, self.capacity)
            rows = [list(self._slots[(idx - count + j) % self.capacity])
                    for j in range(count)]
        finally:
            if locked:
                self._lock.release()
        out = []
        for j, row in enumerate(rows):
            e = {"i": idx - count + j}
            for k, v in zip(_KEYS, row):
                if v is not None:
                    e[k] = v
            out.append(e)
        return out

    def max_seq(self):
        # Same bounded-acquire discipline as events() (signal-handler
        # dumps); an unlocked dict copy can race a concurrent insert, so
        # retry once on the (rare) mutation error.
        locked = self._lock.acquire(timeout=0.5)
        try:
            for _ in range(2):
                try:
                    return dict(self._seq)
                except RuntimeError:
                    continue
            return {}
        finally:
            if locked:
                self._lock.release()

    def appended(self):  # hvdrace: disable=HVR203 -- deliberate torn-read-tolerant counter peek; dump()'s emptiness check runs from signal handlers and must not acquire
        return self._idx

    def dropped(self):
        return max(0, self._idx - self.capacity)

    def next_auto_step(self):
        with self._lock:
            self._auto_step += 1
            return self._auto_step

    def meta(self, reason=None):
        m = {"kind": "meta", "rank": _rank(), "pid": os.getpid(),
             "host": _host(), "boot": _BOOT, "role": _role,
             "capacity": self.capacity,
             "appended": self.appended(), "dropped": self.dropped(),
             "max_seq": self.max_seq(), "ts": round(time.time(), 6)}
        if reason is not None:
            m["reason"] = reason
        return m

    def summary(self):
        """Compact evidence dict for bench records / progress streams:
        event counts by kind, per-set max seq, step-span stats."""
        events = self.events()
        by_kind = {}
        step_ts = []
        for e in events:
            # .get: a torn (mid-append, lock-timeout) row may lack fields.
            kind = e.get("kind")
            if kind is None:
                continue
            by_kind[kind] = by_kind.get(kind, 0) + 1
            if kind == "step" and "t" in e:
                step_ts.append((e["t"], e.get("what") == "auto"))
        # Explicit marks win over auto ones (see step_marker) — mixing
        # the two counters would split spans.
        explicit = [t for t, auto in step_ts if not auto]
        step_ts = explicit if explicit else [t for t, _ in step_ts]
        spans = [b - a for a, b in zip(step_ts, step_ts[1:])]
        return {
            "enabled": armed,
            "appended": self.appended(),
            "dropped": self.dropped(),
            "capacity": self.capacity,
            "by_kind": by_kind,
            "max_seq": self.max_seq(),
            "steps": {"count": len(step_ts),
                      "mean_span_s": round(sum(spans) / len(spans), 6)
                      if spans else None},
        }


_recorder = None
_recorder_lock = threading.Lock()


def get():
    """The process-global recorder (created on first use so the capacity
    env is read when the process actually records)."""
    global _recorder
    r = _recorder
    if r is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
            r = _recorder
    return r


def set_enabled(value):
    global armed
    armed = bool(value)


def enabled():
    return armed


# --- module-level recording API (what the instrumented sites call) --------

def record_dispatch(op, ps, nbytes, sig, name=None):
    if not armed:
        return None
    return get().record_dispatch(op, ps, nbytes, sig, name)


def record_complete(op, ps, seq, dur):
    if not armed:
        return
    get().record_complete(op, ps, seq, dur)


def record_event(kind, **fields):
    if not armed:
        return
    get().record_event(kind, **fields)


# Step-boundary listener (the step profiler's ledger registers here):
# step markers are the shared step-clock call sites, and the profiler has
# its own arming switch independent of the forensics ring — so the
# listener fires BEFORE the `armed` gate. One slot, set at import by
# horovod_tpu.profile.ledger; a listener that raises is the listener's
# bug to contain (the ledger wraps itself in try/except).
_step_listener = None


def set_step_listener(fn):
    global _step_listener
    _step_listener = fn


def step_marker(step=None):
    """User/optimizer step annotation: ``hvd.step_marker(step)``. With no
    argument an internal monotonic counter supplies the step (the
    optimizer-wrapper auto-annotation path). Once any explicit step has
    been marked, auto marks are suppressed — under torch+elastic both the
    optimizer's ``step()`` and ``State.commit`` fire per training step,
    and interleaving two counters would halve every analyzed step span."""
    listener = _step_listener
    if listener is not None:
        listener(step)
    if not armed:
        return
    r = get()
    if step is None:
        if r.saw_explicit_step:
            return
        # Tagged so analyzers can drop auto marks when explicit ones
        # exist: under torch+elastic the optimizer's auto mark for step 1
        # lands BEFORE the first commit sets saw_explicit_step.
        seq = r.next_auto_step()
        # Rotate the per-step training trace BEFORE recording the marker,
        # so the step event itself (and every ops-layer span/dispatch
        # until the next marker) lands under the NEW step's trace.
        _trace.step_trace(seq)
        r.record_event("step", seq=seq, what="auto")
    else:
        try:
            step = int(step)
        except (TypeError, ValueError):
            return          # forensics must never fail the job
        r.saw_explicit_step = True
        _trace.step_trace(step)
        r.record_event("step", seq=step)


def signature(tensors):
    """Stable (cross-process, PYTHONHASHSEED-proof) signature hash of a
    tensor set: crc32 over the (shape, dtype) string. Cheap enough for the
    non-plan dispatch paths; dispatch plans precompute theirs once."""
    s = ";".join(f"{tuple(getattr(t, 'shape', ()))}:"
                 f"{getattr(t, 'dtype', '')}" for t in tensors)
    return format(zlib.crc32(s.encode()), "08x")


def events():
    return get().events()


def summary():
    return get().summary()


# Includes "chaos": a run that only saw injections still deserves an
# atexit dump. analyze.py keeps a narrower set under the same name —
# there, injections must not match themselves as downstream anomalies.
# "autopilot_remediate" likewise: a controller-initiated removal is
# post-mortem material, and the driver's ring may hold ONLY its ack
# trail (no crash/stall) — without this the runbook's rejected_*/applied
# evidence never reaches disk on a remediation-only run.
_ANOMALY_KINDS = ("error", "stall", "kv_error", "chaos",
                  "autopilot_remediate")
_ANOMALY_ELASTIC = ("abort", "restore")


def has_anomaly():
    """Does the ring hold anything a post-mortem would care about?
    Gates the atexit dump: a CLEAN elastic teardown needs no forensics
    file, a teardown after an abort/restore/error/stall does."""
    r = _recorder
    if r is None:
        return False
    for e in r.events():
        # .get: events() may surface a torn (mid-append, lock-timeout)
        # row with no "kind" — the atexit gate must never raise.
        kind = e.get("kind")
        if kind in _ANOMALY_KINDS:
            return True
        if kind == "elastic" and e.get("what") in _ANOMALY_ELASTIC:
            return True
    return False


def digest():
    """Compact beacon fields for the telemetry plane
    (:mod:`horovod_tpu.telemetry.digest`): anomaly counts by kind plus the
    per-process-set max collective seq — the cross-rank desync key the
    job health model compares against the fleet median. Reads the live
    ring off the hot path (the beacon thread), never raises."""
    r = _recorder
    if r is None or not armed:
        return {"enabled": armed, "anomalies": 0}
    anomalies = 0
    by_kind = {}
    for e in r.events():
        kind = e.get("kind")
        if kind in _ANOMALY_KINDS:
            key = kind
        elif kind == "elastic" and e.get("what") in _ANOMALY_ELASTIC:
            key = f"elastic_{e.get('what')}"
        else:
            continue
        anomalies += 1
        by_kind[key] = by_kind.get(key, 0) + 1
    return {"enabled": True, "anomalies": anomalies, "by_kind": by_kind,
            "max_seq": r.max_seq(), "dropped": r.dropped()}


def render_jsonl(reason=None):
    """Meta line + every ring event as JSONL (the ``/debug/flight``
    payload and the dump file body)."""
    return _render(get(), reason)


def _render(r, reason=None):
    # Renders from a recorder reference the caller already holds: dump()
    # runs from signal handlers, and routing it through get() would reach
    # the unbounded `with _recorder_lock:` in the create-on-first-use
    # path — a self-deadlock if the signal lands while the main thread
    # holds _recorder_lock inside configure().
    lines = [json.dumps(r.meta(reason))]
    lines.extend(json.dumps(e) for e in r.events())
    return "\n".join(lines) + "\n"


# --- dumps ----------------------------------------------------------------

_dump_lock = threading.Lock()
_dump_count = 0                 # BUDGET: non-forced dumps charged vs MAX_DUMPS
_dump_seq = 0                   # FILENAME ordinal: monotonic, never reused
_dump_counts = {}               # reason -> dumps written for it
_last_dump = {}                 # reason -> monotonic time of last dump


def dump_dir():
    return os.environ.get("HOROVOD_FLIGHT_DIR") or DEFAULT_DUMP_DIR


def default_collection_dir(output_filename=None):
    """The defaulted elastic collection directory. ONE definition: the
    driver's disruption markers and every worker's dumps must resolve the
    same directory (launch.build_worker_env and the elastic driver both
    call this) or the analyzer loses the kill-to-membership-change
    correlation."""
    return os.path.join(output_filename or ".", DEFAULT_DUMP_DIR)


def dump(reason, directory=None, force=False):
    """Write the ring to
    ``<dir>/flight_<role>_r<rank>_p<pid>_b<boot>_<n>.jsonl``.
    Returns the path, or None when skipped (recorder off, empty ring,
    per-reason throttle, or the MAX_DUMPS runaway guard). Never raises —
    a dump is forensics, not a failure path of its own."""
    global _dump_count, _dump_seq
    r = _recorder
    if r is None or r.appended() == 0:
        return None
    if not armed and not force:
        # The off switch covers the dump sites too (stall/abort/error
        # paths call this unconditionally); an explicit force (SIGUSR2,
        # the chaos-crash last words, tests) still writes.
        return None
    try:
        now = time.monotonic()
        # Bounded for the same signal-handler reason as events(): a
        # SIGTERM landing inside another dump must not self-deadlock —
        # losing one overlapping dump is fine.
        if not _dump_lock.acquire(timeout=0.5):
            return None
        try:
            prev_last = _last_dump.get(reason)
            if not force:
                # Forced dumps (SIGUSR2, chaos-crash last words, tests)
                # are operator/crash-driven and never charged: a runbook
                # kill -USR2 loop must not exhaust MAX_DUMPS and starve
                # the one later membership_abort the post-mortem needs.
                if _dump_count >= MAX_DUMPS \
                        or _dump_counts.get(reason, 0) \
                        >= MAX_DUMPS_PER_REASON:
                    return None
                if prev_last is not None \
                        and now - prev_last < _DUMP_MIN_INTERVAL_S:
                    return None
                _dump_counts[reason] = _dump_counts.get(reason, 0) + 1
                _dump_count += 1
            _last_dump[reason] = now
            # Filename ordinal is separate from the budget counter and is
            # never rolled back: reusing an index after a failed write
            # would open(path, "w") over a concurrent dump's file.
            n = _dump_seq
            _dump_seq += 1
        finally:
            _dump_lock.release()
        try:
            d = directory or dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{_role}_r{_rank()}_p{os.getpid()}"
                   f"_b{_BOOT}_{n:02d}.jsonl")
            with open(path, "w") as f:
                f.write(_render(r, reason))
            return path
        except Exception:  # noqa: BLE001
            # Failed writes must not burn the dump budget or the 1s
            # throttle window: a temporarily full/unwritable volume would
            # otherwise silence the one later dump (membership_abort,
            # stall_shutdown) the post-mortem needs. Bounded acquire,
            # same rationale as above.
            if _dump_lock.acquire(timeout=0.5):
                try:
                    if not force:
                        _dump_count = max(0, _dump_count - 1)
                        _dump_counts[reason] = max(
                            0, _dump_counts.get(reason, 1) - 1)
                    if prev_last is None:
                        _last_dump.pop(reason, None)
                    else:
                        _last_dump[reason] = prev_last
                finally:
                    _dump_lock.release()
            return None
    except Exception:  # noqa: BLE001 — forensics must never fail the job
        return None


def driver_mark(version, removed, hosts, directory=None):
    """Driver-side disruption marker appended to ``driver_events.jsonl``
    in the collection directory: the analyzer correlates worker dumps with
    the membership change that triggered them (which hosts left, when)."""
    if not armed:
        # --no-flight-recorder must not leave a flight_dumps/ directory
        # behind: workers record nothing, so the marker has nothing to
        # correlate anyway.
        return
    try:
        d = directory or dump_dir()
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "driver_events.jsonl"), "a") as f:
            f.write(json.dumps({
                "kind": "driver_disruption", "version": int(version),
                "removed": sorted(removed), "hosts": sorted(hosts),
                "t": round(time.time(), 6)}) + "\n")
    except OSError:
        pass


# --- configuration + crash hooks ------------------------------------------

def configure(config):
    """Apply a :class:`horovod_tpu.common.config.Config`'s flight knobs
    (called by ``basics.init``). The ring itself is never cleared here —
    elastic in-place re-init must keep pre-failure events; a CHANGED
    capacity reallocates (fresh ring)."""
    global _recorder
    set_enabled(config.flight)
    if config.flight_dir:
        os.environ["HOROVOD_FLIGHT_DIR"] = config.flight_dir
    with _recorder_lock:
        cap = max(int(config.flight_capacity), 8)
        if _recorder is not None and _recorder.capacity != cap:
            _recorder = FlightRecorder(capacity=cap)
        elif _recorder is None:
            _recorder = FlightRecorder(capacity=cap)
    if config.flight:
        install_crash_hooks(elastic=bool(os.environ.get("HOROVOD_ELASTIC")))


_hooks_installed = False


def install_crash_hooks(elastic=False):
    """SIGUSR2 → on-demand dump (always). Under an elastic launch also
    SIGTERM (the driver terminates removed-host workers with it — today
    they die silent) and atexit (elastic teardown paths that do reach
    interpreter finalization). Idempotent; signal installation is
    best-effort — only the main thread may install handlers, and an
    embedded interpreter may refuse."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    import signal

    try:
        def _on_usr2(signum, frame):
            dump("sigusr2", force=True)

        signal.signal(signal.SIGUSR2, _on_usr2)
    except (ValueError, OSError, AttributeError):
        pass
    if not elastic:
        return
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            dump("sigterm")
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_IGN:
                # A supervisor that deliberately ignored SIGTERM must
                # keep that behavior — dump, swallow, survive.
                return
            else:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass
    import atexit
    atexit.register(lambda: dump("atexit") if has_anomaly() else None)
