"""Collective flight recorder: always-armed crash forensics.

Two halves (see the module docstrings for the full story):

- :mod:`horovod_tpu.flight.recorder` — a per-rank, default-on, fixed-size
  ring buffer of structured events (collective dispatches/completions with
  per-process-set sequence numbers, fusion boundaries, negotiation rounds,
  KV faults, elastic transitions, chaos injections, step markers),
  recorded lock-cheap and dumped as JSONL on exactly the paths where today
  you get nothing: stall findings, the membership watchdog abort, the
  dispatch failure epilogue, chaos crashes, SIGTERM/atexit during elastic
  teardown, ``SIGUSR2``, and on demand via ``GET /debug/flight`` on the
  metrics endpoint.
- :mod:`horovod_tpu.flight.analyze` — the post-mortem CLI
  (``python -m horovod_tpu.flight.analyze <dir>``): merge per-rank dumps,
  detect cross-rank desync (naming the first diverging collective), rank
  stragglers by host-latency skew, reconstruct per-step time breakdowns,
  correlate chaos injections with their first downstream anomaly, and
  emit a merged Perfetto-loadable Chrome trace.

Knobs: ``HOROVOD_FLIGHT_RECORDER`` (default 1), ``HOROVOD_FLIGHT_CAPACITY``
(default 4096), ``HOROVOD_FLIGHT_DIR`` / ``hvdrun --flight-dir``.
Runbook: docs/observability.md (schema/knobs) + docs/troubleshooting.md
(desync/straggler post-mortem).
"""

from horovod_tpu.flight import recorder  # noqa: F401
from horovod_tpu.flight.recorder import (  # noqa: F401
    FlightRecorder, configure, dump, dump_dir, driver_mark, enabled, events,
    record_dispatch, record_complete, record_event, render_jsonl, set_enabled,
    set_role, signature, step_marker, summary,
)
# NOT imported eagerly: `python -m horovod_tpu.flight.analyze` would then
# find the module pre-imported by its own package and warn (runpy); the
# analyzer is import-on-use (`from horovod_tpu.flight import analyze`).
