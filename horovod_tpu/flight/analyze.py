"""Flight-dump forensics: merge per-rank rings, localize the failure.

``python -m horovod_tpu.flight.analyze <dir>`` merges every per-rank dump
(``flight_*.jsonl``, written by :mod:`horovod_tpu.flight.recorder`) plus any
chaos ledgers / driver disruption markers in the directory, and reports:

- **desync**: per process set, each rank's max collective sequence number;
  when they differ, the lagging ranks, the first unmatched sequence number
  and the first diverging collective (op/name/signature) are named — the
  per-rank-merge analysis arxiv 2510.20171 describes as the load-bearing
  tool at scale;
- **killed**: ranks whose dumps end in a chaos ``crash`` (the victim dumps
  its ring as its last act) or that a driver disruption marker removed;
- **stragglers**: per-op host-latency skew across ranks (mean dispatch
  latency vs the cross-rank median), ranked;
- **steps**: per-step time breakdown reconstructed from step markers
  (wall span, collective count/bytes/host-latency within each span);
- **chaos**: every injection correlated with the first downstream anomaly
  (dispatch error, stall finding, elastic abort/restore) across all ranks.

``--trace out.json`` additionally writes a merged Chrome trace — one track
per rank — loadable in Perfetto / chrome://tracing.

The module is dependency-free and importable: the soak harness and
``tests/test_flight.py`` call :func:`load_dir` / :func:`analyze` /
:func:`write_trace` directly.
"""

import argparse
import json
import os
import re
import sys

# Deliberately NARROWER than recorder._ANOMALY_KINDS: "chaos" is excluded
# here because causation matching scans for the first anomaly AFTER an
# injection — if injections themselves counted as anomalies, each one would
# match itself (or a sibling injection) as its own downstream effect.
_ANOMALY_KINDS = ("error", "stall", "kv_error")
_ANOMALY_ELASTIC = ("abort", "restore")


def _read_jsonl(path):
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue            # truncated mid-crash line
    except OSError:
        pass
    return out


def _dump_ordinal(name, meta):
    """Per-process dump order: the trailing ``_<n>`` of the file name
    (recorder.dump numbers a process's dumps), falling back to the meta
    timestamp for hand-built dumps."""
    stem = name[:-len(".jsonl")]
    tail = stem.rsplit("_", 1)[-1]
    if tail.isdigit():
        return int(tail)
    return meta.get("ts", 0)


def load_dir(directory, ledger_dir=None):
    """Load every flight dump (merged + deduped), chaos-ledger entry and
    driver marker under ``directory``. Returns
    ``(events, metas, driver_marks)`` where each event carries ``rank`` /
    ``role`` / ``pid`` and events overlapping between a process's
    successive dumps are deduplicated by (pid, ring index).

    Rank identity is pinned per pid to the process's EARLIEST dump:
    elastic recovery renumbers surviving ranks (refresh_assignment_env),
    so a later atexit dump may carry a different rank than the same
    process's failure-time dump — the failure-time rank is the one the
    post-mortem is about."""
    events, metas, driver_marks = [], [], []
    seen = set()
    names = sorted(os.listdir(directory)) if os.path.isdir(directory) else []
    dumps = []
    for name in names:
        path = os.path.join(directory, name)
        if name == "driver_events.jsonl":
            driver_marks.extend(_read_jsonl(path))
            continue
        if name.startswith("flight_") and name.endswith(".jsonl"):
            rows = _read_jsonl(path)
            if not rows:
                continue
            meta = rows[0] if rows[0].get("kind") == "meta" else {}
            if meta:
                meta = dict(meta, file=name)
                rows = rows[1:]
            else:
                # Torn/missing meta line (mid-crash truncation): synthesize
                # identity from the filename — flight_<role>_r<rank>_
                # p<pid>_b<boot>_<n>.jsonl. A shared empty identity would
                # pin every meta-less dump to one process and silently
                # drop all but the first as ring-index duplicates.
                meta = {"file": name, "meta_torn": True, "boot": name}
                m = re.search(r"_r(\d+)_p(\d+)", name)
                if m:
                    meta["rank"] = int(m.group(1))
                    meta["pid"] = int(m.group(2))
            metas.append(meta)
            dumps.append((name, meta, rows))
    # Process identity is (host, pid, boot): pids are only unique per host
    # AND get recycled across an elastic run's lifetime (the boot token in
    # the dump meta disambiguates) — attributing one process's events to
    # another would name the wrong rank, the one answer the post-mortem
    # must get right.
    proc_rank = {}
    for name, meta, _ in sorted(
            dumps, key=lambda d: _dump_ordinal(d[0], d[1])):
        proc = (meta.get("host", ""), meta.get("pid", 0),
                meta.get("boot", ""))
        proc_rank.setdefault(proc, meta.get("rank", 0))
    # Metas carry the canonical (earliest-dump) rank too: killed_ranks is
    # derived from events and crash_dump_ranks from metas, and one process
    # reported under two rank labels after an elastic renumbering would
    # give the post-mortem two conflicting answers.
    for meta in metas:
        proc = (meta.get("host", ""), meta.get("pid", 0),
                meta.get("boot", ""))
        canon = proc_rank.get(proc, meta.get("rank", 0))
        if canon != meta.get("rank"):
            meta["rank_at_dump"] = meta.get("rank")
            meta["rank"] = canon
    for name, meta, rows in dumps:
        pid = meta.get("pid", 0)
        proc = (meta.get("host", ""), pid, meta.get("boot", ""))
        rank = proc_rank.get(proc, meta.get("rank", 0))
        role = meta.get("role", "worker")
        for e in rows:
            if "kind" not in e:
                # Torn row: a signal-handler dump that timed out the ring
                # lock read a slot mid-append ({"i": N}, fields omitted).
                # Nothing to analyze — and every consumer keys on "kind".
                continue
            key = (proc, e.get("i"))
            if e.get("i") is not None and key in seen:
                continue
            seen.add(key)
            e = dict(e, rank=rank, role=role, pid=pid)
            events.append(e)
    # Chaos ledgers double as flight evidence: the injector writes them
    # before applying the effect, so even a rank killed with os._exit
    # leaves its injection on disk twice (ledger + pre-crash dump). The
    # ring already mirrors every firing as a ``chaos`` event, so a ledger
    # entry is merged only when no ring event evidences the same firing
    # (same rank/site/kind within 2s) — rings may wrap, ledgers don't.
    ring_chaos = [e for e in events if e["kind"] == "chaos"]
    matched_ring = set()

    def _ledger_rank(fname, entry):
        # Ledger files are ``{role}_r{rank}_p{pid}.jsonl``: resolve the
        # writing process's canonical rank the way dumps do (earliest-dump
        # pinning via its pid) so an elastic renumbering between a dump and
        # an injection can't split one process across two rank labels —
        # that mismatch would fail the twin-match below and duplicate the
        # injection under a second rank.
        stem = fname[:-len(".jsonl")]
        head, _, tail = stem.rpartition("_p")
        if head and tail.isdigit():
            hits = {r for (h, p, b), r in proc_rank.items()
                    if p == int(tail)}
            if len(hits) == 1:
                return hits.pop()
        return entry.get("rank", 0)

    for d in {directory, ledger_dir} - {None}:
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if not name.endswith(".jsonl") or name.startswith("flight_") \
                    or name == "driver_events.jsonl":
                continue
            for entry in _read_jsonl(os.path.join(d, name)):
                if "site" not in entry or "kind" not in entry:
                    continue
                key = ("ledger", entry.get("rank"), entry.get("site"),
                       entry.get("spec"), entry.get("fire"))
                if key in seen:
                    continue
                seen.add(key)
                ts = entry.get("ts")
                lrank = _ledger_rank(name, entry)
                twin = next(
                    (i for i, e in enumerate(ring_chaos)
                     if i not in matched_ring
                     and e.get("rank") == lrank
                     and e.get("name") == entry["site"]
                     and e.get("what") == entry["kind"]
                     and (ts is None or e.get("t") is None
                          or abs(e["t"] - ts) < 2.0)), None)
                if twin is not None:
                    matched_ring.add(twin)
                    continue
                events.append({"kind": "chaos", "what": entry["kind"],
                               "name": entry["site"],
                               "seq": entry.get("step"),
                               "t": entry.get("ts"),
                               "rank": lrank,
                               "role": entry.get("role", "worker"),
                               "from_ledger": True})
    events.sort(key=lambda e: (e.get("t") or 0.0, e.get("rank", 0)))
    return events, metas, driver_marks


def _is_anomaly(e):
    return e["kind"] in _ANOMALY_KINDS or (
        e["kind"] == "elastic" and e.get("what") in _ANOMALY_ELASTIC)


def analyze_desync(events):
    """Per process set: each rank's max dispatch seq; lagging ranks; the
    first unmatched sequence number and the collective dispatched there."""
    per_ps = {}
    for e in events:
        if e["kind"] != "dispatch" or "seq" not in e or "ps" not in e:
            continue
        by_rank = per_ps.setdefault(e["ps"], {})
        r = e["rank"]
        if e["seq"] > by_rank.get(r, 0):
            by_rank[r] = e["seq"]
    # Every worker belongs to the global set: a rank that wedged before
    # its FIRST dispatch (killed in rendezvous — its dump holds only
    # negotiation/kv/elastic events) must show up lagging at seq 0, not
    # silently vanish from the one report meant to name it. Named subset
    # membership is unknowable from dumps, so only "global" gets this.
    if "global" in per_ps:
        for e in events:
            if e.get("role") != "driver" and not e.get("from_ledger"):
                per_ps["global"].setdefault(e["rank"], 0)
    report = {}
    for ps, by_rank in per_ps.items():
        mx = max(by_rank.values())
        lagging = {r: s for r, s in by_rank.items() if s < mx}
        entry = {"max_seq_by_rank": {str(r): s for r, s
                                     in sorted(by_rank.items())},
                 "desynced": bool(lagging)}
        if lagging:
            first_unmatched = min(lagging.values()) + 1
            entry["lagging_ranks"] = sorted(lagging)
            entry["first_unmatched_seq"] = first_unmatched
            # seq is arrival-ordered per rank, and an async fusion flush
            # can interleave differently against eager dispatches on
            # different ranks — corroborate by taking the MAJORITY
            # (op, sig) among the ranks that did reach this seq, and
            # report how many agree.
            at_seq = [e for e in events
                      if e["kind"] == "dispatch" and e.get("ps") == ps
                      and e.get("seq") == first_unmatched]
            if at_seq:
                tally = {}
                for e in at_seq:
                    tally.setdefault((e.get("op"), e.get("sig")),
                                     []).append(e)
                majority = max(tally.values(), key=len)
                diverging = majority[0]
                entry["first_diverging"] = {
                    k: diverging.get(k)
                    for k in ("op", "name", "sig", "rank", "t")
                    if diverging.get(k) is not None}
                entry["first_diverging"]["agreeing_ranks"] = \
                    len({e["rank"] for e in majority})
        report[ps] = entry
    return report


def analyze_stragglers(events, min_events=3):
    """Per op: mean host dispatch latency per rank vs the cross-rank
    median — the rank whose enqueues consistently take longest is the
    straggler (chaos ``delay`` on one rank shows up exactly here)."""
    lat = {}
    for e in events:
        if e["kind"] != "complete" or e.get("dur") is None \
                or e.get("op") is None:
            continue
        lat.setdefault(e["op"], {}).setdefault(e["rank"], []).append(e["dur"])
    report = {}
    for op, by_rank in lat.items():
        means = {r: sum(v) / len(v) for r, v in by_rank.items()
                 if len(v) >= min_events}
        if len(means) < 2:
            continue
        ordered = sorted(means.values())
        # True median (mean of middles when even): the upper-middle pick
        # would BE the slow rank whenever half or more ranks are slow —
        # e.g. a 2-rank run with one chaos-delayed rank, the exact case
        # this report exists for — giving it skew 1.0 and hiding it.
        mid = len(ordered) // 2
        median = ordered[mid] if len(ordered) % 2 \
            else (ordered[mid - 1] + ordered[mid]) / 2.0
        rows = sorted(
            ({"rank": r, "mean_s": round(m, 6), "n": len(by_rank[r]),
              "skew": round(m / median, 3) if median > 0 else None}
             for r, m in means.items()),
            key=lambda row: -(row["skew"] or 0))
        report[op] = {"ranked": rows,
                      "top_straggler": rows[0]["rank"]
                      if rows and (rows[0]["skew"] or 0) > 1.5 else None}
    return report


def analyze_steps(events):
    """Per rank: spans between consecutive step markers, with the
    collective work inside each span (count, bytes, summed host latency)."""
    by_rank = {}
    for e in events:
        by_rank.setdefault(e["rank"], []).append(e)
    report = {}
    for rank, evs in by_rank.items():
        marks = [e for e in evs if e["kind"] == "step" and "t" in e]
        # Explicit marks win: auto marks (what=auto) only exist before a
        # rank's first explicit mark (torch optimizer.step() firing ahead
        # of the first elastic commit) — mixing the two counters would
        # split that first span in two.
        explicit = [e for e in marks if e.get("what") != "auto"]
        if explicit and len(explicit) != len(marks):
            marks = explicit
        spans = []
        for a, b in zip(marks, marks[1:]):
            inside = [e for e in evs if a["t"] <= e.get("t", 0) < b["t"]]
            disp = [e for e in inside if e["kind"] == "dispatch"]
            comp = [e for e in inside if e["kind"] == "complete"]
            spans.append({
                "step": a.get("seq"),
                "span_s": round(b["t"] - a["t"], 6),
                "collectives": len(disp),
                "bytes": sum(e.get("bytes") or 0 for e in disp),
                "dispatch_s": round(sum(e.get("dur") or 0.0
                                        for e in comp), 6),
            })
        if marks:
            report[str(rank)] = {"steps_marked": len(marks), "spans": spans}
    return report


def analyze_chaos(events):
    """Correlate each injection with the first downstream anomaly (any
    rank): the "this fault caused that failure" line of the post-mortem."""
    injections = [e for e in events if e["kind"] == "chaos"]
    out = []
    for c in injections:
        t0 = c.get("t") or 0.0
        downstream = next(
            (e for e in events
             if (e.get("t") or 0.0) >= t0 and _is_anomaly(e)), None)
        row = {"site": c.get("name"), "what": c.get("what"),
               "rank": c.get("rank"), "step": c.get("seq"), "t": c.get("t")}
        if downstream is not None:
            row["first_anomaly"] = {
                "kind": downstream["kind"],
                "what": downstream.get("what"),
                "op": downstream.get("op"),
                "rank": downstream["rank"],
                "gap_s": round((downstream.get("t") or t0) - t0, 6)}
        out.append(row)
    return out


def analyze_autopilot(events, driver_marks=()):
    """The autopilot's decision trail: tuning decisions summarized per
    (lever, outcome), remediations listed with the rank/cause the
    controller named — and, when the driver's disruption markers are
    available, each remediation correlated with the membership change
    that executed it (the "the controller removed my rank — why?"
    runbook's evidence, docs/troubleshooting.md)."""
    from horovod_tpu.autopilot.remediate import CAUSES
    decisions = [e for e in events if e["kind"] == "autopilot_decision"]
    remediations = [e for e in events
                    if e["kind"] == "autopilot_remediate"]
    by_lever = {}
    for e in decisions:
        key = f"{e.get('name')}:{e.get('what')}"
        by_lever[key] = by_lever.get(key, 0) + 1

    def _rank_of(e):
        name = e.get("name") or ""
        if name.startswith("rank"):
            try:
                return int(name[4:])
            except ValueError:
                pass
        return None

    # Two event flavors share the kind: coordinator REQUESTS carry the
    # cause in `what`; driver-arm ACKS carry the outcome. One removal =
    # one row — the ack attaches to the newest outcome-less request for
    # the same rank instead of fabricating a second remediation. Sorted
    # by wall time first: load_dir emits events grouped per dump FILE
    # (a driver dump sorts before the worker dumps), which would
    # otherwise iterate acks before their requests.
    rows = []
    for e in sorted(remediations, key=lambda e: e.get("t") or 0.0):
        what = e.get("what")
        rank = _rank_of(e)
        if what in CAUSES:
            row = {"rank": rank, "cause": what,
                   "host": e.get("op"), "epoch": e.get("seq"),
                   "observer": e.get("rank"), "t": e.get("t")}
            # The membership change that executed this request: the
            # first driver disruption marker at or after it.
            t0 = e.get("t") or 0.0
            mark = next((m for m in driver_marks
                         if (m.get("t") or 0.0) >= t0), None)
            if mark is not None:
                row["disruption"] = {
                    "version": mark.get("version"),
                    "removed_hosts": mark.get("removed"),
                    "gap_s": round((mark.get("t") or t0) - t0, 3)}
            rows.append(row)
            continue
        target = next((r for r in reversed(rows)
                       if r["rank"] == rank and "outcome" not in r), None)
        if target is not None:
            target["outcome"] = what
        else:
            # an ack whose request event was lost (ring wrap): still a
            # row, explicitly outcome-only
            rows.append({"rank": rank, "cause": None, "outcome": what,
                         "host": e.get("op"), "t": e.get("t")})
    return {"decisions": len(decisions), "by_lever": by_lever,
            "frozen": any(e.get("name") == "tuner"
                          and e.get("what") == "frozen"
                          for e in decisions),
            "remediations": rows}


def analyze(events, metas=(), driver_marks=()):
    killed = sorted({e["rank"] for e in events
                     if e["kind"] == "chaos" and e.get("what") == "crash"})
    crash_dumped = sorted({m.get("rank") for m in metas
                           if m.get("reason") == "chaos_crash"})
    report = {
        "ranks": sorted({e["rank"] for e in events}),
        "dumps": [{k: m.get(k) for k in ("file", "rank", "pid", "role",
                                         "reason", "appended", "dropped")}
                  for m in metas],
        "desync": analyze_desync(events),
        "killed_ranks": killed,
        "crash_dump_ranks": crash_dumped,
        "stragglers": analyze_stragglers(events),
        "steps": analyze_steps(events),
        "chaos": analyze_chaos(events),
        "driver_disruptions": list(driver_marks),
        "autopilot": analyze_autopilot(events, driver_marks),
        "traces": analyze_traces(events),
    }
    return report


def analyze_traces(events):
    """Reconstruct one request/step per trace ref across ranks: every
    ring event now carries the ACTIVE trace id (``trace``), so grouping
    by it recovers which ranks touched a request (or which collectives a
    training step dispatched) without any per-request logging. Keyed
    summaries only — the span trees themselves live in the trace shards
    (``python -m horovod_tpu.trace.analyze``)."""
    by_trace = {}
    for e in events:
        tid = e.get("trace")
        if tid is None:
            continue
        rec = by_trace.setdefault(tid, {
            "trace": tid, "ranks": set(), "events": 0, "kinds": {},
            "t_first": e.get("t"), "t_last": e.get("t"), "seq_span": {}})
        rec["ranks"].add(e.get("rank"))
        rec["events"] += 1
        rec["kinds"][e["kind"]] = rec["kinds"].get(e["kind"], 0) + 1
        t = e.get("t")
        if t is not None:
            rec["t_first"] = t if rec["t_first"] is None \
                else min(rec["t_first"], t)
            rec["t_last"] = t if rec["t_last"] is None \
                else max(rec["t_last"], t)
        # Per-process-set collective seq window: the cross-rank join key
        # (a desync inside one request shows as unequal windows).
        if e["kind"] in ("dispatch", "complete") and e.get("seq") \
                is not None:
            ps = e.get("ps")
            lo, hi = rec["seq_span"].get(ps, (e["seq"], e["seq"]))
            rec["seq_span"][ps] = (min(lo, e["seq"]), max(hi, e["seq"]))
    out = []
    for tid in sorted(by_trace):
        rec = by_trace[tid]
        rec["ranks"] = sorted(r for r in rec["ranks"] if r is not None)
        rec["seq_span"] = {str(ps): list(span)
                           for ps, span in rec["seq_span"].items()}
        if rec["t_first"] is not None and rec["t_last"] is not None:
            rec["span_s"] = round(rec["t_last"] - rec["t_first"], 6)
        out.append(rec)
    return out


def write_trace(events, path):
    """Merged Chrome trace, one track (pid) per rank, loadable in
    Perfetto: completes render as duration spans (anchored at dispatch
    time = completion minus host latency), everything else as instants."""
    ts0 = min((e["t"] for e in events if "t" in e), default=0.0)
    completed = {(e["rank"], e.get("ps"), e.get("seq"))
                 for e in events if e["kind"] == "complete"}
    trace_events = []
    # Wall-clock anchor of ts=0 (event times are time.time() seconds):
    # the same convention as the Timeline's clock_sync metadata, so a
    # Chrome timeline and this trace can be rebased onto one axis
    # (--merge-timeline does exactly that).
    trace_events.append({"ph": "M", "name": "clock_sync", "pid": 0,
                         "args": {"wall_t0_us": ts0 * 1e6}})
    for rank in sorted({e["rank"] for e in events}):
        trace_events.append({"ph": "M", "name": "process_name", "pid": rank,
                             "args": {"name": f"rank {rank}"}})
    for e in events:
        if "t" not in e:
            continue
        rank = e["rank"]
        ts_us = (e["t"] - ts0) * 1e6
        if e["kind"] == "complete" and e.get("dur"):
            dur_us = e["dur"] * 1e6
            trace_events.append({
                "ph": "X", "pid": rank, "tid": 0, "cat": "collective",
                "name": f"{e.get('op', '?')}#{e.get('seq', '?')}",
                "ts": ts_us - dur_us, "dur": dur_us,
                "args": {k: e[k] for k in ("ps", "sig", "trace")
                         if k in e}})
        elif e["kind"] == "dispatch":
            # Matched dispatches ride their complete's span; an UNMATCHED
            # one is the wedged collective the post-mortem is after —
            # render it so the victim's track shows the op it died in.
            if (rank, e.get("ps"), e.get("seq")) in completed:
                continue
            trace_events.append({
                "ph": "i", "s": "p", "pid": rank, "tid": 0,
                "cat": "collective",
                "name": f"unfinished:{e.get('op', '?')}#{e.get('seq', '?')}",
                "ts": ts_us,
                "args": {k: e[k] for k in ("ps", "sig", "trace")
                         if k in e}})
        else:
            trace_events.append({
                "ph": "i", "s": "p", "pid": rank, "tid": 0,
                "cat": e["kind"], "ts": ts_us,
                "name": f"{e['kind']}:"
                        f"{e.get('what') or e.get('name') or e.get('seq')}",
                "args": {k: e[k] for k in ("op", "seq", "what", "name",
                                           "trace") if k in e}})
    with open(path, "w") as f:
        json.dump({"traceEvents": trace_events, "displayTimeUnit": "ms"}, f)
    return len(trace_events)


def merge_timeline(trace_path, timeline_path):
    """Merge a :mod:`horovod_tpu.timeline` Chrome trace into a trace
    written by :func:`write_trace`, rebasing the timeline's
    perf_counter-based timestamps onto the flight trace's wall-clock axis
    via both files' ``clock_sync`` metadata. Timeline tracks land under a
    distinct pid block (10000 + original pid) so rank tracks stay
    separate. Returns the number of events merged; 0 when either side
    lacks its clock_sync anchor (pre-alignment trace files)."""
    with open(trace_path) as f:
        trace = json.load(f)
    with open(timeline_path) as f:
        tl = json.load(f)

    def _anchor(events):
        for e in events:
            name = str(e.get("name", ""))
            if name == "clock_sync" and e.get("ph") == "M":
                return float(e.get("args", {}).get("wall_t0_us", 0.0))
            if name.startswith("clock_sync="):
                # native-writer form: the fixed record signature carries
                # no args, so the anchor is folded into the name.
                try:
                    return float(name.split("=", 1)[1])
                except ValueError:
                    continue
        return None

    flight_t0 = _anchor(trace.get("traceEvents", []))
    tl_t0 = _anchor(tl.get("traceEvents", []))
    if flight_t0 is None or tl_t0 is None:
        return 0
    offset = tl_t0 - flight_t0
    merged = 0
    for e in tl.get("traceEvents", []):
        name = str(e.get("name", ""))
        if name == "clock_sync" or name.startswith("clock_sync="):
            # both anchor forms (python metadata / native folded-name
            # instant) are consumed, never copied — a stale rebased
            # anchor in the merged file would poison a later merge pass.
            continue
        e = dict(e)
        e["ts"] = float(e.get("ts", 0.0)) + offset
        e["pid"] = 10000 + int(e.get("pid", 0))
        trace["traceEvents"].append(e)
        merged += 1
    trace["traceEvents"].append({
        "ph": "M", "name": "process_name", "pid": 10000,
        "args": {"name": f"timeline {timeline_path}"}})
    with open(trace_path, "w") as f:
        json.dump(trace, f)
    return merged


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.flight.analyze",
        description="Merge per-rank flight-recorder dumps and localize "
                    "desyncs, stragglers and fault causation.")
    p.add_argument("directory", help="dump directory (per-rank "
                                     "flight_*.jsonl files)")
    p.add_argument("--ledger", help="chaos-ledger directory to correlate "
                                    "(defaults to the dump directory)")
    p.add_argument("--trace", help="also write a merged Chrome trace "
                                   "(Perfetto-loadable) to this path")
    p.add_argument("--merge-timeline",
                   help="a horovod_tpu.timeline Chrome-trace file to merge "
                        "into --trace, rebased via both files' clock_sync "
                        "anchors (one view: timeline spans + flight "
                        "forensics)")
    args = p.parse_args(argv)
    events, metas, driver_marks = load_dir(args.directory,
                                           ledger_dir=args.ledger)
    if not events:
        print(json.dumps({"error": f"no flight dumps under "
                                   f"{args.directory}"}))
        return 1
    report = analyze(events, metas, driver_marks)
    if args.trace:
        report["trace_events_written"] = write_trace(events, args.trace)
        report["trace_path"] = args.trace
        if args.merge_timeline:
            report["timeline_events_merged"] = merge_timeline(
                args.trace, args.merge_timeline)
    json.dump(report, sys.stdout, indent=1)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
