"""The framework's series catalogue + recording helpers.

Every instrumentation point in the stack goes through one of the
``record_*`` functions here, so the catalogue below is the single source
of truth for series names, labels and units (documented in
docs/observability.md). All helpers are no-ops when metrics are disabled
(``HOROVOD_METRICS=0``) and never raise into the hot path.

Catalogue (names shown without the ``HOROVOD_METRICS_PREFIX``, default
``horovod``):

- ``collective_ops_total{op,process_set}``          dispatches (counter)
- ``collective_bytes_total{op,process_set}``        payload bytes (counter)
- ``collective_latency_seconds{op}``                dispatch latency (histogram)
- ``collective_errors_total{op}``                   failed dispatches (counter)
- ``fusion_flushes_total``                          bucket flushes (counter)
- ``fusion_flush_tensors``                          tensors per flush (histogram)
- ``fusion_flush_bytes``                            bytes per flush (histogram)
- ``fusion_fill_ratio``                             flushed/threshold (histogram)
- ``fusion_boundary_outcomes_total{outcome}``       applied|deferred (counter)
- ``fusion_kv_rpcs_total{kind}``                    boundary KV set/get (counter)
- ``dispatch_plan_events_total{event}``             plan cache hit|miss (counter)
- ``compile_cache_events_total{event}``             XLA persistent-cache
  request|hit (counter; armed by ``HOROVOD_COMPILE_CACHE_DIR``)
- ``negotiation_rounds_total``                      exchange() rounds (counter)
- ``control_plane_rpcs_total{transport,kind}``      every KV RPC (counter)
- ``control_plane_payload_bytes_total{transport}``  KV payload bytes (counter)
- ``elastic_events_total{event}``                   rendezvous/reset/... (counter)
- ``elastic_recovery_seconds{cause}``               failure detection →
  training re-entry (histogram; cause=failure|host_update)
- ``stall_events_total{kind}``                      warning|shutdown (counter)
- ``kv_client_retries_total``                       HTTP-KV client retries (counter)
- ``chaos_injections_total{site,kind}``             chaos faults fired (counter)
- ``step_time_seconds``                             step wall time from the
  step profiler's marker-to-marker windows (histogram)
- ``step_profiler_events_total{kind}``              watchdog findings:
  straggler|regression (counter; horovod_tpu/profile)
- ``wire_bytes_total{dtype,tier}``                  estimated bytes on the
  wire per collective at the effective wire dtype, split per link tier
  (tier=ici|dcn — ops/wire.py accounting; allreduces count both RS+AG
  legs; the flat default split books the ring/a2a slice-boundary
  fraction to dcn, hierarchical dispatches book each leg's tier exactly)
- ``wire_compression_events_total{path,dtype}``     dispatches that
  actually compressed the wire (path=eager|fused|jit; counter)
- ``serving_requests_total{event}``                 request lifecycle
  (event=submitted|admitted|completed|requeued|rejected; counter)
- ``serving_ttft_seconds``                          submit → first
  generated token (histogram; the SLO p50/p99 source)
- ``serving_token_latency_seconds``                 decode-step wall time
  = inter-token latency for every active request (histogram)
- ``serving_tokens_total``                          generated tokens
  (counter; rate() = tokens/sec)
- ``serving_queue_depth``                           admission queue depth
  (gauge)
- ``serving_batch_fill_ratio``                      active slots / total
  slots per decode step (histogram; low values mean the fleet is
  over-provisioned or admission is starved)
- ``slo_burn_rate{objective}``                      rolling-window SLO
  error-budget burn (objective=ttft_p99|tps; gauge — 0 inside the SLO,
  1.0 = consuming the budget exactly, > 1 = burning; computed by
  horovod_tpu/telemetry/slo.py from the declared
  HOROVOD_SLO_TTFT_P99_MS / HOROVOD_SLO_TPS objectives)
- ``autopilot_decisions_total{lever,outcome}``      autopilot control
  decisions (lever=tuner|overlap|cross_wire|remediate; counter)
- ``autopilot_remediations_total{cause,outcome}``   autopilot-initiated
  removals (cause=dead|stalled|straggler; outcome=requested|applied|
  rejected_*; counter)
"""

import os
import threading
import time

from horovod_tpu.flight import recorder as _flight
from horovod_tpu.metrics.registry import MetricsRegistry, exponential_buckets

_enabled = os.environ.get("HOROVOD_METRICS", "1").lower() \
    not in ("0", "false", "no", "off")

REGISTRY = MetricsRegistry(
    prefix=os.environ.get("HOROVOD_METRICS_PREFIX", "horovod"))


def get_registry():
    return REGISTRY


def enabled():
    return _enabled


def set_enabled(value):
    global _enabled
    _enabled = bool(value)


def set_prefix(prefix):
    REGISTRY.prefix = prefix


# --- the catalogue (created eagerly so HELP/TYPE lines are always part of
# the exposition, observed or not) --------------------------------------

_LAT_BUCKETS = exponential_buckets(1e-5, 2.0, 22)          # 10us .. ~21s
_BYTE_BUCKETS = exponential_buckets(1024, 4.0, 14)         # 1KiB .. 64GiB
_COUNT_BUCKETS = exponential_buckets(1, 2.0, 13)           # 1 .. 4096
_RATIO_BUCKETS = exponential_buckets(1.0 / 64, 2.0, 9)     # ~0.016 .. 4

COLLECTIVE_OPS = REGISTRY.counter(
    "collective_ops_total",
    "Eager collective dispatches (sync ops and fused async flush buckets).",
    ("op", "process_set"))
COLLECTIVE_BYTES = REGISTRY.counter(
    "collective_bytes_total",
    "Bytes moved by eager collectives (global rank-major stacked layout).",
    ("op", "process_set"))
COLLECTIVE_LATENCY = REGISTRY.histogram(
    "collective_latency_seconds",
    "Host-side dispatch latency of eager collectives (enqueue to program "
    "return; device execution is async beyond it).",
    ("op",), buckets=_LAT_BUCKETS)
COLLECTIVE_ERRORS = REGISTRY.counter(
    "collective_errors_total",
    "Eager collective dispatches that raised.",
    ("op",))
FUSION_FLUSHES = REGISTRY.counter(
    "fusion_flushes_total",
    "Fusion-runtime bucket flushes dispatched by this process.")
FUSION_FLUSH_TENSORS = REGISTRY.histogram(
    "fusion_flush_tensors",
    "Tensors per fusion flush (bucket size).",
    buckets=_COUNT_BUCKETS)
FUSION_FLUSH_BYTES = REGISTRY.histogram(
    "fusion_flush_bytes",
    "Bytes per fusion flush.",
    buckets=_BYTE_BUCKETS)
FUSION_FILL_RATIO = REGISTRY.histogram(
    "fusion_fill_ratio",
    "Flushed bytes / fusion threshold (1.0 = a full bucket; small values "
    "mean cycle/explicit flushes dominate threshold flushes).",
    buckets=_RATIO_BUCKETS)
FUSION_BOUNDARY_OUTCOMES = REGISTRY.counter(
    "fusion_boundary_outcomes_total",
    "Follower handling of coordinator flush boundaries: applied "
    "immediately vs deferred (boundary ahead of the local enqueue stream).",
    ("outcome",))
FUSION_KV_RPCS = REGISTRY.counter(
    "fusion_kv_rpcs_total",
    "Coordination-service KV RPCs issued by the fusion boundary "
    "publish/consume path (the ADVICE.md hot-poll class shows up here).",
    ("kind",))
DISPATCH_PLAN_EVENTS = REGISTRY.counter(
    "dispatch_plan_events_total",
    "Eager dispatch-plan cache outcomes (event=hit|miss|invalidate). A "
    "steady-state training loop is all hits; misses mean new signatures "
    "(or churn past the plan-cache cap).",
    ("event",))
COMPILE_CACHE_EVENTS = REGISTRY.counter(
    "compile_cache_events_total",
    "JAX persistent-compilation-cache outcomes (event=request|hit). "
    "Armed when HOROVOD_COMPILE_CACHE_DIR wires the cache up; "
    "request-minus-hit is the fresh-XLA-compile count.",
    ("event",))
NEGOTIATION_ROUNDS = REGISTRY.counter(
    "negotiation_rounds_total",
    "Host-side negotiation.exchange() rounds (dynamic-shape collectives, "
    "join mode, order checks).")
CONTROL_PLANE_RPCS = REGISTRY.counter(
    "control_plane_rpcs_total",
    "Control-plane KV RPCs by transport (coord = jax.distributed "
    "coordination service, http = runner HTTP KV store) and verb.",
    ("transport", "kind"))
CONTROL_PLANE_PAYLOAD = REGISTRY.counter(
    "control_plane_payload_bytes_total",
    "Serialized payload bytes written to the control plane.",
    ("transport",))
ELASTIC_EVENTS = REGISTRY.counter(
    "elastic_events_total",
    "Elastic lifecycle events: rank_ready, rendezvous, reset, restore, "
    "host_update, sync, abort (watchdog severed in-flight collectives).",
    ("event",))
ELASTIC_RECOVERY = REGISTRY.histogram(
    "elastic_recovery_seconds",
    "Elastic recovery latency: failure detection (HorovodInternalError / "
    "HostsUpdatedInterrupt caught by the @elastic.run wrapper) to re-entry "
    "into the training function at the new membership "
    "(cause=failure|host_update).",
    ("cause",), buckets=exponential_buckets(0.01, 2.0, 16))  # 10ms..~5min
STALL_EVENTS = REGISTRY.counter(
    "stall_events_total",
    "Stall-inspector findings (kind=warning|shutdown).",
    ("kind",))
GOODPUT_SECONDS = REGISTRY.counter(
    "goodput_seconds_total",
    "Wall-clock seconds decomposed by the goodput ledger "
    "(horovod_tpu/goodput): category=productive_compute plus the named "
    "badput categories (init_compile, rendezvous_recovery, "
    "checkpoint_commit, straggler_wait, cross_wait_comm, autopilot_trial, "
    "wedge_idle). Conservation contract: the categories sum to the "
    "rank's measured wall time within 1%, so "
    "rate(goodput_seconds_total{category='productive_compute'}) over the "
    "sum of all categories IS the job's goodput ratio.",
    ("category",))
KV_CLIENT_RETRIES = REGISTRY.counter(
    "kv_client_retries_total",
    "Runner HTTP-KV client attempts that failed transiently and were "
    "retried (bounded, jittered exponential backoff — HOROVOD_KV_RETRIES).")
CHAOS_INJECTIONS = REGISTRY.counter(
    "chaos_injections_total",
    "Faults fired by the chaos injection runtime (horovod_tpu/chaos; "
    "always zero unless a HOROVOD_CHAOS_PLAN is armed).",
    ("site", "kind"))
STEP_TIME = REGISTRY.histogram(
    "step_time_seconds",
    "Training-step wall time measured by the step profiler's "
    "marker-to-marker windows (hvd.step_marker / optimizer wrapper / "
    "elastic State.commit).",
    buckets=exponential_buckets(1e-4, 2.0, 22))        # 100us .. ~3.5min
STEP_PROFILER_EVENTS = REGISTRY.counter(
    "step_profiler_events_total",
    "Online watchdog findings from the step profiler "
    "(kind=straggler|regression; horovod_tpu/profile/watchdog.py).",
    ("kind",))
WIRE_BYTES = REGISTRY.counter(
    "wire_bytes_total",
    "Estimated bytes-on-wire per collective at the effective wire dtype, "
    "split per link tier (tier=ici|dcn). ops/wire.py accounting: "
    "allreduce counts both internal legs — reduce-scatter + all-gather — "
    "at the wire width; quantized wires count both 1-byte legs plus fp32 "
    "block scales and padding. Flat dispatches book the slice-boundary "
    "ring/a2a fraction of their bytes to dcn (the static cost model's "
    "tier-split rule, shared via wire.ring_dcn_fraction); hierarchical "
    "dispatches book each decomposed leg's tier exactly. The "
    "int8-vs-float32 ratio here is the provable off-chip savings; the "
    "dcn-vs-flat ratio is the hierarchical tier's.",
    ("dtype", "tier"))
WIRE_COMPRESSION_EVENTS = REGISTRY.counter(
    "wire_compression_events_total",
    "Collective dispatches whose wire was actually compressed "
    "(path=eager|fused|jit, dtype=int8|fp8|float16|bfloat16). jit-path "
    "events are recorded at trace time: once per compiled program, not "
    "per execution.",
    ("path", "dtype"))
SERVING_REQUESTS = REGISTRY.counter(
    "serving_requests_total",
    "Serving-engine request lifecycle events (horovod_tpu/serving): "
    "submitted|admitted|completed|requeued (re-queued from the last "
    "committed token after an elastic disruption)|rejected (queue full).",
    ("event",))
SERVING_TTFT = REGISTRY.histogram(
    "serving_ttft_seconds",
    "Time-to-first-token per request: submit() to the first generated "
    "token's commit (includes queue wait + prefill — the user-facing "
    "p50/p99 SLO).",
    buckets=exponential_buckets(1e-4, 2.0, 22))        # 100us .. ~3.5min
SERVING_TOKEN_LATENCY = REGISTRY.histogram(
    "serving_token_latency_seconds",
    "Decode-step wall time — the inter-token latency every active "
    "request observed on that step (admission/prefill excluded; they "
    "land in serving_ttft_seconds).",
    buckets=exponential_buckets(1e-5, 2.0, 22))        # 10us .. ~21s
SERVING_TOKENS = REGISTRY.counter(
    "serving_tokens_total",
    "Generated tokens committed by the serving engine (rate() is the "
    "fleet tokens/sec).")
SERVING_QUEUE_DEPTH = REGISTRY.gauge(
    "serving_queue_depth",
    "Requests waiting for a slot in the serving admission queue "
    "(sampled at every submit/admit; the first thing to read when "
    "requests time out — docs/troubleshooting.md).")
SLO_BURN = REGISTRY.gauge(
    "slo_burn_rate",
    "Rolling-window SLO error-budget burn per declared objective "
    "(objective=ttft_p99|tps; horovod_tpu/telemetry/slo.py). 0 = inside "
    "the SLO, 1.0 = consuming the error budget exactly, > 1 = burning "
    "it — the admission/scale-up signal the autopilot SignalFrame and "
    "`telemetry top --serving` read.",
    ("objective",))
SERVING_FILL = REGISTRY.histogram(
    "serving_batch_fill_ratio",
    "Active slots / total slots at each decode step (1.0 = the "
    "continuous batch is full; persistently low fill under a deep queue "
    "means admission is starved — a scheduler bug).",
    buckets=_RATIO_BUCKETS)
AUTOPILOT_DECISIONS = REGISTRY.counter(
    "autopilot_decisions_total",
    "Autopilot controller decisions per lever and outcome "
    "(horovod_tpu/autopilot: lever=tuner|overlap|cross_wire|remediate; "
    "outcome=adopt|hold|frozen|reverted|no_signal|baseline|"
    "drift_detected|trial|adopted|requested|unreachable). Every decision "
    "also lands in the flight ring as an autopilot_decision event.",
    ("lever", "outcome"))
AUTOPILOT_REMEDIATIONS = REGISTRY.counter(
    "autopilot_remediations_total",
    "Autopilot remediation requests and their driver-side outcomes "
    "(cause=dead|stalled|straggler; outcome=requested|no_driver|"
    "publish_failed on the coordinator, applied|rejected_floor|"
    "rejected_rate|rejected_unknown_host on the driver arm).",
    ("cause", "outcome"))
TELEMETRY_RPCS = REGISTRY.counter(
    "telemetry_rpcs_total",
    "Telemetry-plane KV RPCs by phase (horovod_tpu/telemetry): the "
    "aggregation round's beacon_put|probe_get|slice_get|slice_put|"
    "job_get|job_put — whose scaling contract is job_get per round == "
    "slice count, not world size (TestTelemetryScaling) — plus read_get, "
    "the demand-driven /cluster/* endpoint reads that scale with scrape "
    "rate instead.",
    ("phase",))


# --- recording helpers (the stack's API) --------------------------------

def record_collective(op, nbytes, process_set="global"):
    """One eager collective dispatch attempt: count + bytes (recorded at
    entry; failures still count as attempts)."""
    if not _enabled:
        return
    COLLECTIVE_OPS.labels(op, process_set).inc()
    if nbytes:
        COLLECTIVE_BYTES.labels(op, process_set).inc(float(nbytes))


def record_collective_latency(op, seconds):
    """Dispatch latency of one SUCCESSFUL eager collective."""
    if not _enabled:
        return
    COLLECTIVE_LATENCY.labels(op).observe(seconds)


def record_collective_error(op):
    if not _enabled:
        return
    COLLECTIVE_ERRORS.labels(op).inc()


def record_fusion_flush(n_tensors, nbytes, threshold):
    if not _enabled:
        return
    FUSION_FLUSHES.inc()
    FUSION_FLUSH_TENSORS.observe(n_tensors)
    FUSION_FLUSH_BYTES.observe(nbytes)
    if threshold:
        FUSION_FILL_RATIO.observe(nbytes / float(threshold))


def record_boundary(outcome):
    if not _enabled:
        return
    FUSION_BOUNDARY_OUTCOMES.labels(outcome).inc()


def record_fusion_kv(sets=0, gets=0, payload_bytes=0):
    if not _enabled:
        return
    if sets:
        FUSION_KV_RPCS.labels("set").inc(sets)
        CONTROL_PLANE_RPCS.labels("coord", "set").inc(sets)
    if gets:
        FUSION_KV_RPCS.labels("get").inc(gets)
        CONTROL_PLANE_RPCS.labels("coord", "get").inc(gets)
    if payload_bytes:
        CONTROL_PLANE_PAYLOAD.labels("coord").inc(payload_bytes)


# Default flat-schedule DCN fractions (per schedule: ring / a2a),
# resolved lazily from the live slice layout and cached (reset by
# collective_ops.clear_program_caches — an elastic resize must never
# replay a stale slice split). None = not yet resolved.
_tier_frac = None


def _default_dcn_fraction(sched="ring"):
    """Slice-boundary DCN fraction of the live topology for one flat
    schedule — the static cost model's rules for flat dispatches, shared
    via wire.ring_dcn_fraction / a2a_dcn_fraction: ``S/n`` for ring legs,
    ``1 - L/n`` for all-to-all legs when a slice hierarchy exists, else 0
    (single-slice worlds book everything to ici)."""
    global _tier_frac
    if _tier_frac is None:
        fracs = {"ring": 0.0, "a2a": 0.0}
        try:
            from horovod_tpu.common import basics, topology
            from horovod_tpu.ops import wire as _wire
            if basics.is_initialized():
                topo = basics.topology()
                size = topo.size
                k = topology.forced_slices()
                slices, slice_size = topology.slice_layout(
                    size, k or (topo.num_slices if topo.num_slices > 1
                                else None))
                if slices > 1:
                    members = list(range(size))
                    fracs = {
                        "ring": _wire.ring_dcn_fraction(members,
                                                        slice_size),
                        "a2a": _wire.a2a_dcn_fraction(members,
                                                      slice_size)}
        except Exception:  # noqa: BLE001 — accounting must never break
            fracs = {"ring": 0.0, "a2a": 0.0}   # a dispatch
        _tier_frac = fracs
    return _tier_frac.get(sched, _tier_frac["ring"])


def reset_tier_split():
    """Forget the cached flat-schedule tier split (topology changed)."""
    global _tier_frac
    _tier_frac = None


def record_wire(path, dtype, nbytes, compressed=False, tiers=None,
                sched="ring"):
    """Wire accounting for one collective dispatch: bytes at the effective
    wire dtype, plus a compression event when the wire was actually
    narrowed (quantized exchange or 16-bit cast). ``tiers`` (a
    ``{"ici": b, "dcn": b}`` dict) books an explicit per-tier split (the
    hierarchical dispatch paths and the quantized exchange's per-leg
    schedules pass it); without it the flat split of the live slice
    layout at this leg's ``sched`` (``"ring"``/``"a2a"``) applies —
    all-ici on single-slice worlds."""
    if not _enabled or not dtype:
        return
    if nbytes and tiers is None:
        from horovod_tpu.ops import wire as _wire
        tiers = _wire.split_tiers(nbytes, _default_dcn_fraction(sched))
    if tiers:
        for tier, b in tiers.items():
            if b:
                WIRE_BYTES.labels(str(dtype), tier).inc(float(b))
    if compressed:
        WIRE_COMPRESSION_EVENTS.labels(path, str(dtype)).inc()


def record_plan_cache(event):
    """One dispatch-plan cache outcome (event=hit|miss|invalidate)."""
    if not _enabled:
        return
    DISPATCH_PLAN_EVENTS.labels(event).inc()


def record_compile_cache(event):
    """One persistent-compilation-cache outcome (event=request|hit)."""
    if not _enabled:
        return
    COMPILE_CACHE_EVENTS.labels(event).inc()


_compile_listener_installed = False


def install_compile_cache_listener():
    """Mirror JAX's persistent-compilation-cache monitoring events into
    the registry, so cache effectiveness (and the zero-fresh-compiles
    restart guarantee) is assertable from metrics. Idempotent; installed
    when ``HOROVOD_COMPILE_CACHE_DIR`` arms the cache (basics.init)."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    from jax._src import monitoring as _jax_monitoring

    def _on_event(event, **kwargs):
        if event == "/jax/compilation_cache/cache_hits":
            record_compile_cache("hit")
        elif event == "/jax/compilation_cache/compile_requests_use_cache":
            record_compile_cache("request")

    _jax_monitoring.register_event_listener(_on_event)
    _compile_listener_installed = True


def record_negotiation(gets, payload_bytes, sets=1, tier_gets=None):
    """One negotiation.exchange() round: ``sets`` publishes + ``gets``
    peer-read RPC attempts. Hierarchical rounds additionally pass
    ``tier_gets`` ({"local","cross","fanback"}) so the scrape shows the
    per-tier fan-out the slice-leader decomposition is supposed to bound
    (kind=neg_get_<tier>)."""
    if not _enabled:
        return
    NEGOTIATION_ROUNDS.inc()
    CONTROL_PLANE_RPCS.labels("coord", "set").inc(max(int(sets), 1))
    if gets:
        CONTROL_PLANE_RPCS.labels("coord", "get").inc(gets)
    for tier, n in (tier_gets or {}).items():
        if n:
            CONTROL_PLANE_RPCS.labels("coord", f"neg_get_{tier}").inc(n)
    if payload_bytes:
        CONTROL_PLANE_PAYLOAD.labels("coord").inc(payload_bytes)


def record_http_kv(kind, payload_bytes=0):
    """One runner HTTP-KV client RPC (kind=get|put|delete|wait)."""
    if not _enabled:
        return
    CONTROL_PLANE_RPCS.labels("http", kind).inc()
    if payload_bytes:
        CONTROL_PLANE_PAYLOAD.labels("http").inc(payload_bytes)


def record_elastic_event(event):
    # Flight ring BEFORE the metrics gate: elastic transitions are exactly
    # the events a post-mortem needs, and the recorder has its own switch.
    if _flight.armed:
        _flight.record_event("elastic", what=event)
    if not _enabled:
        return
    ELASTIC_EVENTS.labels(event).inc()


def record_elastic_recovery(cause, seconds):
    """One completed elastic recovery: detection → training re-entry."""
    if _flight.armed:
        _flight.record_event("elastic", what=f"recovered_{cause}",
                             dur=seconds)
    if not _enabled:
        return
    ELASTIC_RECOVERY.labels(cause).observe(seconds)


def record_goodput_seconds(category, seconds):
    """Delta export from the goodput ledger (horovod_tpu/goodput/ledger
    throttles and computes the per-category deltas; counters only grow)."""
    if not _enabled:
        return
    GOODPUT_SECONDS.labels(category).inc(float(seconds))


def record_kv_retry():
    if not _enabled:
        return
    KV_CLIENT_RETRIES.inc()


def record_chaos(site, kind):
    """One chaos fault fired. Recorded even though injections are test
    machinery: the counter is how a soak (or an operator reading a scrape)
    correlates observed symptoms with injected causes."""
    if not _enabled:
        return
    CHAOS_INJECTIONS.labels(site, kind).inc()


def record_step(seconds):
    """One completed step-profiler window (wall seconds)."""
    if not _enabled:
        return
    STEP_TIME.observe(seconds)


def record_profiler_event(kind):
    """One watchdog finding (kind=straggler|regression)."""
    if not _enabled:
        return
    STEP_PROFILER_EVENTS.labels(kind).inc()


def record_profiler_kv(sets=0, gets=0):
    """Watchdog cross-rank publish traffic on the coordination service —
    reported under control_plane_rpcs_total like every other KV user."""
    if not _enabled:
        return
    if sets:
        CONTROL_PLANE_RPCS.labels("coord", "prof_set").inc(sets)
    if gets:
        CONTROL_PLANE_RPCS.labels("coord", "prof_get").inc(gets)


def record_serving_request(event):
    """One serving request lifecycle event (event=submitted|admitted|
    completed|requeued|rejected). Requeues also land in the flight ring:
    they are exactly the events a zero-drop post-mortem needs."""
    if _flight.armed and event in ("requeued", "rejected"):
        _flight.record_event("serving", what=event)
    if not _enabled:
        return
    SERVING_REQUESTS.labels(event).inc()


def record_serving_ttft(seconds):
    if not _enabled:
        return
    SERVING_TTFT.observe(seconds)


def record_serving_step(seconds, active, slots, tokens=0):
    """One serving decode step: inter-token latency, fill ratio, and the
    tokens it committed."""
    if not _enabled:
        return
    SERVING_TOKEN_LATENCY.observe(seconds)
    if slots:
        SERVING_FILL.observe(active / float(slots))
    if tokens:
        SERVING_TOKENS.inc(tokens)


def record_serving_queue(depth):
    if not _enabled:
        return
    SERVING_QUEUE_DEPTH.set(depth)


def record_slo_burn(objective, burn):
    """Current burn rate of one declared SLO objective (set by
    telemetry/slo.py whenever a window is recomputed)."""
    if not _enabled:
        return
    SLO_BURN.labels(objective).set(float(burn))


def record_autopilot_decision(lever, outcome):
    """One autopilot controller decision (horovod_tpu/autopilot)."""
    if not _enabled:
        return
    AUTOPILOT_DECISIONS.labels(lever, outcome).inc()


def record_autopilot_remediation(cause, outcome):
    """One autopilot remediation lifecycle event (request or driver-arm
    outcome). The flight-ring mirror is recorded at the call sites —
    they carry the rank/host detail this counter aggregates away."""
    if not _enabled:
        return
    AUTOPILOT_REMEDIATIONS.labels(cause, outcome).inc()


def record_telemetry_rpc(phase, n=1):
    """One (or ``n``) telemetry-plane KV RPCs in the given aggregation
    phase (horovod_tpu/telemetry/aggregator.py)."""
    if not _enabled:
        return
    TELEMETRY_RPCS.labels(phase).inc(n)


def record_stall(kind):
    if _flight.armed:
        _flight.record_event("stall", what=kind)
    if not _enabled:
        return
    STALL_EVENTS.labels(kind).inc()


# --- timeline integration ----------------------------------------------
#
# Registry values double as Chrome-trace COUNTER events ("ph": "C") so the
# aggregate series land in the same chrome://tracing file as the op spans
# (the reference's timeline has no counter tracks at all). Emission is
# throttled: the fusion runtime calls maybe_emit_timeline_counters() after
# every flush, which at kHz flush rates would otherwise snapshot the whole
# registry per flush.

_TL_MIN_INTERVAL_S = 0.1
_tl_last = 0.0
_tl_lock = threading.Lock()


def emit_timeline_counters(timeline):
    """Dump every series' current value into ``timeline`` as counter
    events. Histograms emit their _count and _sum. No-op when metrics are
    disabled — an all-zero dump would pollute the trace of a user who
    explicitly turned the registry off."""
    if timeline is None or not _enabled:
        return 0
    n = 0
    for name, fam in REGISTRY.snapshot().items():
        for s in fam["series"]:
            lab = ",".join(f"{k}={v}" for k, v in s["labels"].items())
            label = f"{name}{{{lab}}}" if lab else name
            if fam["type"] == "histogram":
                timeline.record_counter(label + "_count", s["count"])
                timeline.record_counter(label + "_sum", s["sum"])
                n += 2
            else:
                timeline.record_counter(label, s["value"])
                n += 1
    return n


def maybe_emit_timeline_counters(timeline):
    """Throttled emit_timeline_counters (at most once per 100 ms)."""
    global _tl_last
    if timeline is None or not _enabled:
        return 0
    now = time.monotonic()
    with _tl_lock:
        if now - _tl_last < _TL_MIN_INTERVAL_S:
            return 0
        _tl_last = now
    return emit_timeline_counters(timeline)
