"""Mergeable metrics snapshots: the serialization half of the telemetry
plane (:mod:`horovod_tpu.telemetry`).

``MetricsRegistry.snapshot()`` is a per-process view. The cluster view
needs those snapshots combined across ranks and slices without shipping
the registry objects themselves, so this module works on the snapshot
*dicts* (JSON round-trippable by construction):

- counters merge by summing per labelled series,
- gauges merge by max (a gauge is a level, not a flow — summing
  ``metrics_port``-style values would be nonsense),
- histograms merge bucket-wise when the bucket edges agree (they do: the
  whole fleet shares one catalogue, :mod:`horovod_tpu.metrics.instruments`);
  on an edge mismatch (mixed framework versions mid-elastic-upgrade) the
  merge degrades to sum/count only rather than fabricating a distribution.

``compact()`` strips the HELP text for the wire (digests are published
every beacon interval); ``render_text()`` turns any snapshot — merged or
not — back into Prometheus exposition format, optionally stamping an
extra label set (the per-slice labels on ``GET /cluster/metrics``).
"""

from horovod_tpu.metrics.registry import _escape_label_value, _fmt


def compact(snapshot, drop_empty=True):
    """Wire form of a ``registry.snapshot()``: HELP text removed and
    (by default) never-observed series dropped — the catalogue registers
    every family eagerly, so a fresh process would otherwise beacon ~40
    empty families every interval. Gauge series are always kept: a gauge
    AT zero is an observed level (its child only exists because someone
    set it), not an unobserved series."""
    out = {}
    for name, fam in snapshot.items():
        series = fam["series"]
        if drop_empty and fam["type"] != "gauge":
            series = [s for s in series
                      if s.get("count") or s.get("value")]
        if not series:
            continue
        out[name] = {"type": fam["type"], "series": series}
    return out


def _series_key(labels):
    return tuple(sorted(labels.items()))


def _merge_histogram(acc, s):
    acc["sum"] = acc.get("sum", 0.0) + s.get("sum", 0.0)
    acc["count"] = acc.get("count", 0) + s.get("count", 0)
    a, b = acc.get("buckets"), s.get("buckets")
    if a is None or b is None:
        acc.pop("buckets", None)
        return
    if [le for le, _ in a] != [le for le, _ in b]:
        # Edge mismatch: a summed distribution over different boundaries
        # would be fiction; keep sum/count (still a valid mean).
        acc.pop("buckets", None)
        return
    acc["buckets"] = [[le, na + nb] for (le, na), (_, nb) in zip(a, b)]


def merge_snapshots(snapshots):
    """Merge snapshot dicts (``registry.snapshot()`` / ``compact()`` /
    prior merges — the operation is associative) into one. Series align
    on (family name, label set); families whose *type* disagrees across
    inputs keep the first-seen type and skip conflicting inputs."""
    merged = {}
    for snap in snapshots:
        if not snap:
            continue
        for name, fam in snap.items():
            entry = merged.get(name)
            if entry is None:
                entry = merged[name] = {"type": fam["type"], "series": {}}
            elif entry["type"] != fam["type"]:
                continue
            for s in fam["series"]:
                key = _series_key(s.get("labels", {}))
                acc = entry["series"].get(key)
                if acc is None:
                    acc = entry["series"][key] = \
                        {"labels": dict(s.get("labels", {}))}
                    if entry["type"] == "histogram":
                        acc["sum"] = 0.0
                        acc["count"] = 0
                        if s.get("buckets") is not None:
                            acc["buckets"] = [[le, 0]
                                              for le, _ in s["buckets"]]
                    elif entry["type"] == "gauge":
                        # Seed from the first observation, NOT 0.0 — an
                        # all-negative gauge (skew, drift) must not merge
                        # to a fabricated 0.
                        acc["value"] = s.get("value", 0.0)
                        continue
                    else:
                        acc["value"] = 0.0
                if entry["type"] == "histogram":
                    _merge_histogram(acc, s)
                elif entry["type"] == "gauge":
                    acc["value"] = max(acc["value"], s.get("value", 0.0))
                else:
                    acc["value"] += s.get("value", 0.0)
    return {name: {"type": fam["type"],
                   "series": [fam["series"][k]
                              for k in sorted(fam["series"])]}
            for name, fam in merged.items()}


def add_labels(snapshot, **labels):
    """A copy of ``snapshot`` with ``labels`` stamped onto every series
    (e.g. ``slice="1"`` before a cross-slice merge, so the job-level
    exposition keeps per-slice series distinct)."""
    out = {}
    for name, fam in snapshot.items():
        series = []
        for s in fam["series"]:
            s2 = dict(s)
            s2["labels"] = {**s.get("labels", {}),
                            **{k: str(v) for k, v in labels.items()}}
            series.append(s2)
        out[name] = {"type": fam["type"], "series": series}
    return out


def render_text(snapshot, prefix="horovod", help_map=None):
    """Prometheus text exposition 0.0.4 from a snapshot dict (the
    registry-free twin of ``MetricsRegistry.render_text`` — the job
    aggregator renders views merged from OTHER processes' registries).
    ``help_map``: optional {family: help text} (defaults to a generic
    aggregation note)."""
    lines = []
    pfx = f"{prefix}_" if prefix else ""
    for name in sorted(snapshot):
        fam = snapshot[name]
        full = pfx + name
        doc = (help_map or {}).get(
            name, fam.get("help", "aggregated across ranks"))
        lines.append(f"# HELP {full} {doc}")
        lines.append(f"# TYPE {full} {fam['type']}")
        for s in fam["series"]:
            base_lab = ",".join(
                f'{k}="{_escape_label_value(v)}"'
                for k, v in s.get("labels", {}).items())
            if fam["type"] == "histogram":
                for le, n in s.get("buckets") or ():
                    le_s = "+Inf" if le == "+Inf" else _fmt(le)
                    lab = (base_lab + "," if base_lab else "") \
                        + f'le="{le_s}"'
                    lines.append(f"{full}_bucket{{{lab}}} {n}")
                suffix = f"{{{base_lab}}}" if base_lab else ""
                lines.append(f"{full}_sum{suffix} {_fmt(s.get('sum', 0))}")
                lines.append(f"{full}_count{suffix} {s.get('count', 0)}")
            else:
                suffix = f"{{{base_lab}}}" if base_lab else ""
                lines.append(f"{full}{suffix} {_fmt(s.get('value', 0))}")
    return "\n".join(lines) + "\n"
