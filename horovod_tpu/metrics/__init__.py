"""Metrics & telemetry: per-collective counters/histograms, control-plane
RPC accounting, and a Prometheus-style scrape endpoint.

The always-on observability layer the reference lacks (its story stops at
the on-demand timeline + stall inspector): every eager collective, fusion
flush, control-plane KV RPC, elastic lifecycle event and stall finding is
counted into a process-local registry, exposed three ways —

- ``hvd.metrics_snapshot()`` / ``hvd.metrics_text()`` (Prometheus text
  exposition format),
- an optional background HTTP scrape endpoint
  (``HOROVOD_METRICS_PORT`` / :func:`start_http_server`),
- Chrome-trace counter events merged into the timeline
  (:func:`emit_timeline_counters`).

Knobs: ``HOROVOD_METRICS`` (default on), ``HOROVOD_METRICS_PORT``
(default off), ``HOROVOD_METRICS_PREFIX`` (default ``horovod``). Series
catalogue: :mod:`horovod_tpu.metrics.instruments` / docs/observability.md.
"""

from horovod_tpu.metrics.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, exponential_buckets,
)
from horovod_tpu.metrics.instruments import (  # noqa: F401
    REGISTRY, enabled, set_enabled, set_prefix, get_registry,
    emit_timeline_counters, install_compile_cache_listener,
    maybe_emit_timeline_counters,
    record_boundary, record_chaos, record_collective,
    record_collective_error, record_collective_latency,
    record_compile_cache, record_elastic_event, record_elastic_recovery,
    record_fusion_flush, record_fusion_kv, record_http_kv, record_kv_retry,
    record_negotiation, record_plan_cache, record_stall,
)
from horovod_tpu.metrics.server import (  # noqa: F401
    MetricsServer, http_server_port, start_http_server, stop_http_server,
)
from horovod_tpu.metrics import merge  # noqa: F401  (mergeable snapshots)


def snapshot():
    """JSON-able dict of every series' current value."""
    return REGISTRY.snapshot()


def render_text():
    """Prometheus text exposition (format 0.0.4) of the whole registry."""
    return REGISTRY.render_text()


def reset():
    """Zero every series (registered families survive) — test hygiene."""
    return REGISTRY.reset()
