"""Dependency-free metrics registry: Counter / Gauge / Histogram.

The reference framework has no aggregate metrics layer — its observability
stops at the Chrome-trace timeline and the stall inspector (PAPER §5.1-5.2).
This module is the always-on complement: cheap process-local counters the
rest of the stack increments on every collective dispatch, fusion flush,
control-plane RPC and elastic event, exposed as a snapshot dict and in
Prometheus text exposition format (``render_text``).

Design constraints (the hot path is the eager collective enqueue):

- O(1) recording: one dict lookup for the labelled child (cached), one
  short per-child lock around the float add. No allocation after the first
  observation of a label set, no I/O, nothing held across RPC or flush
  boundaries.
- No third-party deps: ``prometheus_client`` is deliberately not required —
  the text format is small and stable (version 0.0.4), and the container
  must not grow dependencies.
- Histograms use exponential bucket boundaries (latencies and byte sizes
  both span decades); cumulative bucket counts are computed at render time
  so ``observe`` touches exactly one bucket slot.
"""

import bisect
import threading


def exponential_buckets(start, factor, count):
    """``count`` upper bounds ``start * factor**i`` — the +Inf bucket is
    implicit. Mirrors prometheus_client's helper of the same name."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("exponential_buckets needs start>0, factor>1, "
                         "count>=1")
    return tuple(start * factor ** i for i in range(count))


def _validate_name(name):
    if not name or not all(c.isalnum() or c in "_:" for c in name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _escape_label_value(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(v):
    """Prometheus sample value: integers render without a trailing .0."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Value:
    """One labelled series of a counter or gauge."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def get(self):
        with self._lock:
            return self._value


class _HistogramValue:
    """One labelled series of a histogram: per-bucket counts + sum."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum")

    def __init__(self, bounds):
        self._bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)   # last slot = +Inf
        self._sum = 0.0

    def observe(self, value):
        value = float(value)
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value

    def get(self):
        """(cumulative [(le, count), ..., ('+Inf', total)], sum, count)."""
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
        cum, acc = [], 0
        for bound, c in zip(self._bounds, counts[:-1]):
            acc += c
            cum.append((bound, acc))
        acc += counts[-1]
        cum.append(("+Inf", acc))
        return cum, total_sum, acc


class _Family:
    """A named metric with a fixed label schema; children are the labelled
    series. ``labels(...)`` is the hot path: a tuple build + dict lookup."""

    kind = None

    def __init__(self, name, documentation, labelnames=()):
        self.name = _validate_name(name)
        self.documentation = documentation
        self.labelnames = tuple(labelnames)
        self._children = {}
        self._lock = threading.Lock()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{values!r}")
        key = tuple(str(v) for v in values)
        child = self._children.get(key)       # GIL-safe dict read
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _default(self):
        """The label-less child (only valid when the family has no labels) —
        lets ``counter.inc()`` work directly for unlabelled metrics."""
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels {self.labelnames}")
        return self.labels()

    def series(self):
        """[(labels_dict, child)] sorted for deterministic exposition."""
        with self._lock:
            items = sorted(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]

    def clear(self):
        with self._lock:
            self._children.clear()


class Counter(_Family):
    kind = "counter"

    def _new_child(self):
        return _Value()

    def inc(self, amount=1.0):
        self._default().inc(amount)


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self):
        return _Value()

    def set(self, value):
        self._default().set(value)

    def inc(self, amount=1.0):
        self._default().inc(amount)


class Histogram(_Family):
    kind = "histogram"

    DEFAULT_BUCKETS = exponential_buckets(1e-5, 2.0, 22)  # 10us .. ~21s

    def __init__(self, name, documentation, labelnames=(), buckets=None):
        super().__init__(name, documentation, labelnames)
        b = tuple(float(x) for x in (buckets or self.DEFAULT_BUCKETS))
        if list(b) != sorted(b) or len(set(b)) != len(b):
            raise ValueError("histogram buckets must be strictly increasing")
        self.buckets = b

    def _new_child(self):
        return _HistogramValue(self.buckets)

    def observe(self, value):
        self._default().observe(value)


class MetricsRegistry:
    """Name -> family registry with idempotent getters (a second
    ``counter()`` call with the same schema returns the existing family —
    instrumentation sites don't coordinate creation order)."""

    def __init__(self, prefix="horovod"):
        self.prefix = prefix
        self._families = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, documentation, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) \
                        or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type/label schema ({fam.kind}{fam.labelnames} vs "
                        f"{cls.kind}{tuple(labelnames)})")
                return fam
            fam = cls(name, documentation, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, documentation, labelnames=()):
        return self._get_or_create(Counter, name, documentation, labelnames)

    def gauge(self, name, documentation, labelnames=()):
        return self._get_or_create(Gauge, name, documentation, labelnames)

    def histogram(self, name, documentation, labelnames=(), buckets=None):
        return self._get_or_create(Histogram, name, documentation,
                                   labelnames, buckets=buckets)

    def families(self):
        with self._lock:
            return list(self._families.values())

    def reset(self):
        """Zero every series (keep the registered families) — test/bench
        hygiene, the analog of ``negotiation.stats_reset``."""
        for fam in self.families():
            fam.clear()

    # --- exposition ----------------------------------------------------

    def snapshot(self):
        """JSON-able nested dict of every series' current value. Counters/
        gauges: ``{"labels": {...}, "value": v}``; histograms additionally
        carry cumulative ``buckets`` ([le, count] pairs), ``sum`` and
        ``count``."""
        out = {}
        for fam in self.families():
            series = []
            for labels, child in fam.series():
                if fam.kind == "histogram":
                    cum, s, c = child.get()
                    series.append({"labels": labels,
                                   "buckets": [[le, n] for le, n in cum],
                                   "sum": s, "count": c})
                else:
                    series.append({"labels": labels, "value": child.get()})
            out[fam.name] = {"type": fam.kind,
                             "help": fam.documentation,
                             "series": series}
        return out

    def render_text(self):
        """Prometheus text exposition format 0.0.4 of the whole registry."""
        lines = []
        prefix = f"{self.prefix}_" if self.prefix else ""
        for fam in self.families():
            full = prefix + fam.name
            lines.append(f"# HELP {full} {fam.documentation}")
            lines.append(f"# TYPE {full} {fam.kind}")
            for labels, child in fam.series():
                base_lab = ",".join(
                    f'{k}="{_escape_label_value(v)}"'
                    for k, v in labels.items())
                if fam.kind == "histogram":
                    cum, s, c = child.get()
                    for le, n in cum:
                        le_s = "+Inf" if le == "+Inf" else _fmt(le)
                        lab = (base_lab + "," if base_lab else "") \
                            + f'le="{le_s}"'
                        lines.append(f"{full}_bucket{{{lab}}} {n}")
                    suffix = f"{{{base_lab}}}" if base_lab else ""
                    lines.append(f"{full}_sum{suffix} {_fmt(s)}")
                    lines.append(f"{full}_count{suffix} {c}")
                else:
                    suffix = f"{{{base_lab}}}" if base_lab else ""
                    lines.append(f"{full}{suffix} {_fmt(child.get())}")
        return "\n".join(lines) + "\n"
