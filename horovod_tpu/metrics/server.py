"""Prometheus-style scrape endpoint for the metrics registry.

Reuses the stdlib threaded-HTTP-server idiom of
:mod:`horovod_tpu.runner.http_kv` (no new dependencies): ``GET /metrics``
returns the registry in text exposition format 0.0.4. Started by
``hvd.init()`` when ``HOROVOD_METRICS_PORT`` is set (each process binds
``port + local_rank`` so same-host processes don't collide while every
host keeps the same base port), or manually via :func:`start_http_server`.
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _MetricsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence
        pass

    def _send_json(self, body, code=200,
                   content_type="application/json"):
        data = body if isinstance(body, bytes) else body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        import json as _json
        from urllib.parse import parse_qs, urlsplit

        split = urlsplit(self.path)
        path = split.path
        if path == "/debug/profile":
            # On-demand jax.profiler capture: block this handler thread
            # for ?ms=<window> (default 1000, cap 60000), dump the trace
            # under HOROVOD_PROFILE_DIR, and answer with the capture
            # directory. 409 when a capture is already running — the
            # profiler session is process-global.
            from horovod_tpu.profile import capture
            try:
                ms = int(parse_qs(split.query).get("ms", ["1000"])[0])
            except ValueError:
                ms = 1000
            # Clamped HERE too so the response reports the window that
            # actually ran, not the client's ask.
            ms = capture.clamp_ms(ms)
            d = capture.capture_ms(ms, tag="debug_endpoint")
            if d is None:
                self._send_json(_json.dumps(
                    {"error": "capture already running or profiler "
                              "unavailable"}), code=409)
                return
            self._send_json(_json.dumps({"path": d, "ms": ms}))
            return
        if path == "/debug/steps":
            # The step profiler's recent records + aggregate — the live
            # counterpart of the HVD_STEP_REPORT_FILE stream.
            from horovod_tpu.profile import ledger
            try:
                last = int(parse_qs(split.query).get("last", ["32"])[0])
            except ValueError:
                last = 32
            # records(last=...) (not step_report): "records" must be a
            # list even for last=1.
            self._send_json(_json.dumps({
                "summary": ledger.step_report_summary(),
                "records": ledger.get().records(last=last)}))
            return
        if path == "/debug/flight":
            # On-demand flight-recorder dump: the ring served directly
            # (meta line + events as JSONL), no file written — the live
            # counterpart of the crash-path dumps in flight/recorder.py.
            from horovod_tpu.flight import recorder as _flight
            body = _flight.render_jsonl("debug_endpoint").encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonl")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/serving/health":
            # The serving engine's readiness frame: queue depth, slot
            # fill, served counts and a saturation flag — what a load
            # balancer (or `telemetry top --once --serving`) reads to
            # decide whether to keep routing traffic here. 503 when no
            # engine runs in this process: an LB probe must fail closed.
            from horovod_tpu.serving.engine import serving_snapshot
            snap = serving_snapshot()
            if snap is None:
                self._send_json(_json.dumps(
                    {"error": "no serving engine in this process"}),
                    code=503)
                return
            self._send_json(_json.dumps(snap))
            return
        if path == "/cluster/health":
            # The hierarchical telemetry plane's job view: per-rank
            # health states, per-slice digest counts, step progress and
            # the transition event log (hvd.cluster_snapshot()). Served
            # from any rank: non-leaders fetch the view from the
            # launcher KV (one GET), leaders answer from memory.
            from horovod_tpu.telemetry import aggregator
            self._send_json(_json.dumps(aggregator.cluster_snapshot()))
            return
        if path == "/cluster/steps":
            # Per-rank step progress + job medians from the merged slice
            # summaries — the cluster-level /debug/steps.
            from horovod_tpu.telemetry import aggregator
            self._send_json(_json.dumps(aggregator.cluster_steps()))
            return
        if path == "/cluster/metrics":
            # Job-aggregated Prometheus exposition: counters summed and
            # histograms merged within each slice, every series stamped
            # with its slice label.
            from horovod_tpu.telemetry import aggregator
            body = aggregator.cluster_metrics_text().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path not in ("/metrics", "/"):
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        body = self.server.registry.render_text().encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsServer:
    """Background scrape server over one registry; ``port=0`` binds a free
    port (read it back from ``.port`` after ``start()``)."""

    def __init__(self, port=0, registry=None, addr="0.0.0.0"):
        from horovod_tpu.metrics.instruments import REGISTRY
        self._httpd = ThreadingHTTPServer((addr, port), _MetricsHandler)
        self._httpd.registry = registry if registry is not None else REGISTRY
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="hvd-metrics-scrape")
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


_server = None
_server_lock = threading.Lock()


def start_http_server(port=0, registry=None, addr="0.0.0.0"):
    """Start (or return) the process-wide scrape server; returns the bound
    port. Idempotent: a second call returns the running server's port."""
    global _server
    with _server_lock:
        if _server is None:
            s = MetricsServer(port=port, registry=registry, addr=addr)
            s.start()
            _server = s
        return _server.port


def stop_http_server():
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None


def http_server_port():
    """Bound port of the running scrape server, or None."""
    with _server_lock:
        return _server.port if _server is not None else None
