"""Chunked Parquet dataset reader — the petastorm-reader analog.

Reference: the Spark estimators feed training from Store-written Parquet via
petastorm readers with worker sharding and bounded memory
(horovod/spark/common/store.py:38-540 + keras/remote.py data path). This
reader streams record batches from a (possibly partitioned, possibly remote)
Parquet dataset with pyarrow, shards row groups across workers, and keeps a
bounded shuffle buffer — the driver never materializes the dataset.
"""

import numpy as np


class ParquetBatchReader:
    """Stream ``batch_size``-row numpy column dicts from a Parquet dataset.

    Args:
        path: file or dataset directory (part files).
        columns: columns to read (None = all).
        batch_size: rows per yielded batch.
        shard_rank / shard_count: this worker reads row groups
            ``shard_rank, shard_rank + shard_count, ...`` — the petastorm
            ``cur_shard/shard_count`` contract.
        shuffle: shuffle fragment order and within a bounded buffer.
        shuffle_buffer: rows held for shuffling (bounds memory).
        seed: epoch-stable base seed; pass a different ``epoch`` to
            :meth:`batches` to reshuffle per epoch.
        filesystem: optional ``pyarrow.fs.FileSystem`` (e.g. the HDFS store's).
        drop_last: drop the final partial batch (SPMD-friendly static shapes).
    """

    def __init__(self, path, columns=None, batch_size=32, shard_rank=0,
                 shard_count=1, shuffle=False, shuffle_buffer=10000, seed=0,
                 filesystem=None, drop_last=True):
        import pyarrow.dataset as pads
        self._ds = pads.dataset(path, format="parquet",
                                filesystem=filesystem)
        self.columns = list(columns) if columns is not None else None
        self.batch_size = batch_size
        self.shard_rank = shard_rank
        self.shard_count = shard_count
        self.shuffle = shuffle
        self.shuffle_buffer = max(int(shuffle_buffer), batch_size)
        self.seed = seed
        self.drop_last = drop_last

    def __len__(self):
        """Total rows in this worker's shard (metadata scan only)."""
        return sum(f.count_rows() for f in self._shard_fragments())

    def head(self, n=1):
        """First ``n`` rows as a column dict — shape/schema probing without
        reading (or shuffling) a whole buffer of data."""
        t = self._ds.head(n, columns=self.columns)
        return {name: self._to_numpy(t.column(j))
                for j, name in enumerate(t.schema.names)}

    def _shard_fragments(self):
        """This worker's row-group-level fragments, round-robin sharded
        (reference contract: petastorm cur_shard/shard_count)."""
        i = 0
        for frag in self._ds.get_fragments():
            for rg in frag.split_by_row_group():
                if i % self.shard_count == self.shard_rank:
                    yield rg
                i += 1

    def batches(self, epoch=0):
        """Yield dicts of column -> numpy array, ``batch_size`` rows each."""
        rng = np.random.default_rng((self.seed, epoch)) if self.shuffle \
            else None
        frags = list(self._shard_fragments())
        if rng is not None:
            rng.shuffle(frags)

        buffer = []      # list of (columns dict) row chunks
        buffered = 0

        def drain(final=False):
            nonlocal buffer, buffered
            if not buffer:
                return
            cols = {k: np.concatenate([c[k] for c in buffer])
                    for k in buffer[0]}
            n = len(next(iter(cols.values())))
            order = rng.permutation(n) if rng is not None else np.arange(n)
            end = n if final else (n // self.batch_size) * self.batch_size
            for s in range(0, end, self.batch_size):
                idx = order[s:s + self.batch_size]
                if len(idx) < self.batch_size and self.drop_last:
                    break
                yield {k: v[idx] for k, v in cols.items()}
            rest = order[end:]
            if len(rest) and not final:
                buffer = [{k: v[rest] for k, v in cols.items()}]
                buffered = len(rest)
            else:
                buffer = []
                buffered = 0

        for frag in frags:
            for rb in frag.to_batches(columns=self.columns):
                chunk = {name: self._to_numpy(rb.column(j))
                         for j, name in enumerate(rb.schema.names)}
                buffer.append(chunk)
                buffered += rb.num_rows
                if buffered >= self.shuffle_buffer:
                    yield from drain()
        yield from drain(final=True)

    @staticmethod
    def _to_numpy(col):
        a = col.to_numpy(zero_copy_only=False)
        if a.dtype == object:  # list-valued column -> dense 2-D
            a = np.stack([np.asarray(v) for v in a])
        return a
