from horovod_tpu.data.data_loader import (  # noqa: F401
    BaseDataLoader, AsyncDataLoaderMixin, ShardedDataLoader,
    prefetch_to_device,
)
from horovod_tpu.data.compute_service import (  # noqa: F401
    ComputeServiceConfig, ComputeServiceDataLoader, DataDispatcher,
    DataWorker,
)
from horovod_tpu.data.parquet import ParquetBatchReader  # noqa: F401
