from horovod_tpu.data.data_loader import (  # noqa: F401
    BaseDataLoader, AsyncDataLoaderMixin, ShardedDataLoader,
    prefetch_to_device,
)
