"""Data loading utilities.

Reference: horovod/data/data_loader_base.py (165 LoC) — ``BaseDataLoader`` and
``AsyncDataLoaderMixin`` (a background thread prefetching batches into a
bounded queue).

TPU additions: ``ShardedDataLoader`` (per-rank sharding by slicing the global
batch rank-major, the layout eager collectives use) and
``prefetch_to_device`` (async H2D staging so input upload overlaps the
device step — the TPU analog of the reference's GPU-side staging buffers).
"""

import queue
import threading

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


class BaseDataLoader:
    """reference: data_loader_base.py BaseDataLoader."""

    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        self._iterator = iter(self._iterate())
        return self._iterator

    def _iterate(self):
        raise NotImplementedError


class AsyncDataLoaderMixin:
    """Background-thread prefetch (reference: data_loader_base.py
    AsyncDataLoaderMixin — same queue + poison-pill protocol).

    Use as ``class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader)``.
    """

    def __init__(self, async_loading=True, queue_size=8, *args, **kwargs):
        self.async_loading = async_loading
        self._queue_size = queue_size
        super().__init__(*args, **kwargs)

    def __iter__(self):
        if not self.async_loading:
            return super().__iter__()
        q = queue.Queue(maxsize=self._queue_size)
        done = object()

        def producer():
            try:
                for batch in super(AsyncDataLoaderMixin, self)._iterate():
                    q.put(batch)
            except Exception as ex:
                # Forward to the consumer thread so a failing epoch raises
                # instead of silently truncating (reference:
                # data_loader_base.py _async_worker puts the exception).
                q.put(ex)
            finally:
                q.put(done)

        t = threading.Thread(target=producer, daemon=True)
        t.start()

        def consumer():
            while True:
                item = q.get()
                if item is done:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item

        return consumer()

    def close_async_loader(self):
        """Kept for API parity (the daemon producer dies with the iterator;
        reference: close_async_loader drains the queue)."""


class ShardedDataLoader(BaseDataLoader):
    """Iterates (x, y) arrays in rank-major global batches: each batch has
    leading axis ``size * per_rank_batch`` laid out host-major so slice ``r``
    feeds chip ``r`` — ready for ``make_train_step``'s ``P('hvd')`` spec."""

    def __init__(self, arrays, batch_size, size=None, shuffle=True, seed=0,
                 drop_last=True):
        from horovod_tpu.common import basics
        self._arrays = [np.asarray(a) for a in arrays]
        n = len(self._arrays[0])
        assert all(len(a) == n for a in self._arrays)
        self._n = n
        self._size = size if size is not None else basics.size()
        self._global_batch = batch_size * self._size
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._drop_last = drop_last

    def __len__(self):
        if self._drop_last:
            return self._n // self._global_batch
        return (self._n + self._global_batch - 1) // self._global_batch

    def _iterate(self):
        idx = np.arange(self._n)
        if self._shuffle:
            self._rng.shuffle(idx)
        for start in range(0, self._n, self._global_batch):
            sel = idx[start:start + self._global_batch]
            if len(sel) < self._global_batch:
                if self._drop_last:
                    break
                # Pad the final batch by wrapping so the leading axis stays
                # divisible by the world size (the rank-major sharding
                # contract) — the reference's ElasticSampler pads the same
                # way (reference: torch/elastic/sampler.py).
                pad = self._global_batch - len(sel)
                sel = np.concatenate([sel, idx[:pad]])
            yield tuple(a[sel] for a in self._arrays)


def prefetch_to_device(iterator, mesh=None, spec=None, buffer_size=2):
    """Stage host batches onto the mesh ahead of consumption so H2D upload
    overlaps compute. Yields device arrays sharded by ``spec``
    (default rank-major over ``hvd``)."""
    from horovod_tpu.common import basics
    if mesh is None:
        mesh = basics.topology().mesh
    sharding = NamedSharding(mesh, spec if spec is not None else P("hvd"))

    staged = []
    it = iter(iterator)

    def stage(batch):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), batch)

    try:
        for _ in range(buffer_size):
            staged.append(stage(next(it)))
    except StopIteration:
        pass
    while staged:
        out = staged.pop(0)
        try:
            staged.append(stage(next(it)))
        except StopIteration:
            pass
        yield out
