"""Side-car data-processing cluster: CPU workers preprocess batches off the
training hosts' critical path.

Reference: horovod/tensorflow/data/compute_service.py (TfDataServiceConfig,
``send_to_data_service``; dispatcher/worker side-car run under horovodrun via
compute_worker.py) and runner/common/service/compute_service.py (dispatcher
registration RPC).

TPU-native shape: TPU hosts burn their cores feeding chips, so preprocessing
moves to separate CPU worker processes. A :class:`DataDispatcher` (on the
training driver) hands shard assignments to registered
:class:`DataWorker` s; each worker runs the user's ``dataset_fn(shard,
num_shards)`` generator and streams pickled batches over TCP to the training
host, which consumes them through :class:`ComputeServiceDataLoader` — the
AsyncDataLoader-compatible client. tf.data's dispatcher protocol is replaced
by the HTTP-KV store already used for rendezvous.
"""

import dataclasses
import json
import pickle
import queue
import socket
import struct
import threading

from horovod_tpu.runner.http_kv import KVStoreClient, KVStoreServer

_SCOPE = "compute_service"


@dataclasses.dataclass(frozen=True)
class ComputeServiceConfig:
    """Serializable service endpoint config (reference:
    TfDataServiceConfig compute_service.py:34-88, incl. write/read via an
    atomically-renamed JSON file for side-car startup ordering)."""

    kv_addr: str
    kv_port: int
    num_workers: int
    timeout: int = 60

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        return ComputeServiceConfig(**d)

    def write(self, filename):
        import os
        import tempfile
        d = os.path.dirname(filename) or "."
        with tempfile.NamedTemporaryFile("w", dir=d, delete=False) as w:
            w.write(json.dumps(self.to_dict()))
        os.rename(w.name, filename)

    @staticmethod
    def read(filename, wait_for_file_creation=False, timeout=120):
        import os
        import time
        deadline = time.time() + timeout
        while wait_for_file_creation and not os.path.exists(filename):
            if time.time() > deadline:
                raise TimeoutError(f"config file {filename} never appeared "
                                   f"within {timeout}s")
            time.sleep(0.2)
        with open(filename) as r:
            return ComputeServiceConfig.from_dict(json.loads(r.read()))


class DataDispatcher:
    """Driver-side registry: workers announce their batch-stream endpoints;
    training clients look them up by shard."""

    def __init__(self, num_workers):
        self.num_workers = num_workers
        self._kv = KVStoreServer()
        self._port = self._kv.start()

    @property
    def config(self):
        # Must be reachable from side-car workers on OTHER hosts — the whole
        # point of the service — so never "localhost".
        return ComputeServiceConfig(kv_addr=socket.gethostname(),
                                    kv_port=self._port,
                                    num_workers=self.num_workers)

    def stop(self):
        self._kv.stop()


class DataWorker:
    """One preprocessing worker: serves ``dataset_fn(shard, num_shards)``
    batches over TCP (reference: compute_worker.py main loop)."""

    def __init__(self, config, shard, dataset_fn):
        self.config = config
        self.shard = shard
        self.dataset_fn = dataset_fn
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("", 0))
        self._srv.listen(4)
        self._thread = None
        self._stop = threading.Event()

    def start(self):
        kv = KVStoreClient(self.config.kv_addr, self.config.kv_port)
        port = self._srv.getsockname()[1]
        kv.put(_SCOPE, f"worker_{self.shard}",
               json.dumps({"addr": socket.gethostname(),
                           "port": port}).encode())
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return port

    def _serve(self):
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.5)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._stream, args=(conn,),
                             daemon=True).start()

    def _stream(self, conn):
        try:
            for batch in self.dataset_fn(self.shard,
                                         self.config.num_workers):
                payload = pickle.dumps(batch, pickle.HIGHEST_PROTOCOL)
                conn.sendall(struct.pack(">Q", len(payload)) + payload)
                if self._stop.is_set():
                    break
            conn.sendall(struct.pack(">Q", 0))  # end-of-stream
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        self._srv.close()
        if self._thread is not None:
            self._thread.join(timeout=2)


class ComputeServiceDataLoader:
    """Training-side client: iterates batches streamed from this rank's
    assigned worker, prefetching into a bounded queue
    (reference: send_to_data_service + AsyncDataLoaderMixin semantics)."""

    def __init__(self, config, shard, queue_size=8, connect_timeout=30):
        self.config = config
        self.shard = shard
        self.queue_size = queue_size
        self.connect_timeout = connect_timeout

    def _endpoint(self):
        import time
        kv = KVStoreClient(self.config.kv_addr, self.config.kv_port)
        deadline = time.time() + self.connect_timeout
        while time.time() < deadline:
            raw = kv.get(_SCOPE, f"worker_{self.shard}")
            if raw:
                info = json.loads(raw.decode())
                return info["addr"], info["port"]
            time.sleep(0.2)
        raise TimeoutError(
            f"compute-service worker for shard {self.shard} never "
            f"registered (have {self.config.num_workers} workers started?)")

    def __iter__(self):
        addr, port = self._endpoint()
        sock = socket.create_connection((addr, port),
                                        timeout=self.connect_timeout)
        # The connect timeout must not govern reads: a dataset_fn may
        # legitimately take longer than it between batches, and an inherited
        # per-recv timeout would masquerade as end-of-stream.
        sock.settimeout(None)
        q = queue.Queue(maxsize=self.queue_size)
        _END = object()
        abandoned = threading.Event()

        def put_bounded(item):
            # Bounded put that aborts if the consumer walked away —
            # otherwise an early `break` in the training loop leaks
            # this thread and the socket forever.
            while not abandoned.is_set():
                try:
                    q.put(item, timeout=0.5)
                    return
                except queue.Full:
                    continue

        def reader():
            final = _END
            try:
                buf = sock.makefile("rb")
                while not abandoned.is_set():
                    header = buf.read(8)
                    if len(header) < 8:
                        # The protocol ends with an explicit n==0 terminator;
                        # a bare FIN / short header means the worker died
                        # mid-stream — surface it, don't end the epoch.
                        raise EOFError(
                            "compute-service connection closed without "
                            "end-of-stream sentinel")
                    (n,) = struct.unpack(">Q", header)
                    if n == 0:
                        break
                    put_bounded(pickle.loads(buf.read(n)))
            except Exception as e:  # noqa: BLE001
                # A transport error is NOT end-of-stream: surface it so the
                # training loop fails loudly instead of silently truncating
                # the epoch. (If the consumer abandoned us, the shutdown()
                # below caused this error — swallow it.)
                if not abandoned.is_set():
                    final = e
            finally:
                put_bounded(final)
                sock.close()

        threading.Thread(target=reader, daemon=True).start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, Exception):
                    raise RuntimeError(
                        "compute-service stream failed mid-epoch") from item
                yield item
        finally:
            # Runs on exhaustion AND on generator close (early break/del).
            abandoned.set()
            # The reader may be parked inside buf.read() where the abandoned
            # flag is never polled; shutdown() (unlike close()) reliably
            # wakes a blocked recv, and leaves the fd for the reader alone
            # to close — no fd-reuse race with other threads.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
