"""Runnable data-service worker: the side-car process the launcher starts on
CPU hosts.

Reference: horovod/tensorflow/data/compute_worker.py — run under the
launcher as ``horovodrun -np N python -m horovod.tensorflow.data
.compute_worker /path/config.json``; rank 0 additionally hosts the
dispatcher and writes the config file the training job reads.

Usage here::

    bin/hvdrun -np 4 -H cpuhost1:2,cpuhost2:2 \
        python -m horovod_tpu.data.compute_worker \
        --dataset-fn mypkg.pipeline:batches /shared/compute_service.json

``--dataset-fn module:callable`` names a ``dataset_fn(shard, num_shards)``
generator importable on every worker host. Rank 0 starts the
:class:`~horovod_tpu.data.compute_service.DataDispatcher` and atomically
writes the config file (shared filesystem, same contract as the
reference); every rank serves its shard with a
:class:`~horovod_tpu.data.compute_service.DataWorker`. The training job
reads the config with ``ComputeServiceConfig.read(path,
wait_for_file_creation=True)`` and consumes batches through
:class:`~horovod_tpu.data.compute_service.ComputeServiceDataLoader`.
"""

import argparse
import importlib
import os
import signal
import sys
import threading

from horovod_tpu.data.compute_service import (ComputeServiceConfig,
                                              DataDispatcher, DataWorker)


def _load_dataset_fn(spec):
    if ":" not in spec:
        raise SystemExit(
            f"--dataset-fn must be 'module:callable', got {spec!r}")
    mod, _, name = spec.partition(":")
    fn = getattr(importlib.import_module(mod), name, None)
    if fn is None:
        raise SystemExit(f"{name!r} not found in module {mod!r}")
    return fn


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.data.compute_worker",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("configfile",
                   help="shared path where rank 0 writes the service config")
    p.add_argument("--dataset-fn", required=True,
                   help="module:callable naming dataset_fn(shard, num_shards)")
    p.add_argument("--timeout", type=int, default=60,
                   help="seconds non-root ranks wait for the config file")
    args = p.parse_args(argv)

    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    size = int(os.environ.get("HOROVOD_SIZE", "1"))
    dataset_fn = _load_dataset_fn(args.dataset_fn)

    dispatcher = None
    if rank == 0:
        dispatcher = DataDispatcher(num_workers=size)
        cfg = dispatcher.config
        cfg.write(args.configfile)
    else:
        cfg = ComputeServiceConfig.read(args.configfile,
                                        wait_for_file_creation=True,
                                        timeout=args.timeout)

    worker = DataWorker(cfg, shard=rank, dataset_fn=dataset_fn)
    worker.start()
    print(f"# compute worker shard {rank}/{size} serving", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    worker.stop()
    if dispatcher is not None:
        dispatcher.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
