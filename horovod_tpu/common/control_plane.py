"""Hierarchical control plane: slice-local negotiation, leaders-only DCN
rounds, and the scope->shard KV routing convention.

The flat control plane issues O(world) blocking KV reads per rank per
negotiation round and fans every fusion boundary out to every follower
through one listener — exactly the host-side fan-out "Collective
Communication for 100k+ GPUs" (arXiv 2510.20171) names as the cliff past
~10k ranks. PR 7 proved the fix's shape on the telemetry plane: fan-in
that scales with slice count, leased leadership, generation-scoped keys.
This module applies the same shape to the ACTUAL control plane:

- ``exchange_groups`` partitions the participating processes by TPU
  slice, reusing :func:`topology.slice_layout` / ``slice_of_rank`` (the
  PR-11 seam shared with ``_build_dcn_mesh``) so the static and runtime
  hierarchies can never disagree.
- ``hier_exchange`` decomposes one ``negotiation.exchange`` round into a
  slice-local exchange (members <-> their slice leader), ONE leaders-only
  cross-slice round over DCN, and a leader->member fan-back: per-rank
  blocking gets drop from O(world) to O(slice_size + num_slices) for
  leaders and O(1) for members.
- ``flat_exchange`` keeps the flat path but reads peers rotated from
  ``me+1`` with a bounded short-timeout sweep, so one slow early rank no
  longer head-of-line-blocks every later get.
- ``boundary_role`` assigns the fusion boundary stream's re-publish roles
  (coordinator publishes once; slice leaders re-publish to their
  members), with lease-based takeover when a leader dies mid-stream.
- ``slice_scope`` / ``shard_of_scope`` are the runner HTTP-KV's routing
  convention: slice-local scopes resolve to the per-slice shard server,
  job-global scopes to the root listener.
- ``simulate_exchange`` drives the REAL exchange implementations over an
  in-memory KV with one thread per virtual rank — the n=128-512 dryrun
  tier (``docs/scale_validation.md``) and the ``bench.py control_sweep``
  leg both measure through it.

Strategy is env-gated: ``HOROVOD_CONTROL_PLANE=flat|hier`` ("" = auto,
meaning hier whenever the slice layout has >1 slice). A 1-slice layout
always falls back to flat — the hierarchy would add hops for no fan-out
saving there.
"""

import json
import os
import threading
import time

from horovod_tpu.common.topology import slice_layout

# Bounded short-timeout sweep ahead of the blocking pass (flat path): a
# ready peer is drained in ``SWEEP_MS``; after ``SWEEP_MISS_CAP`` misses
# the sweep stops and the remaining peers get the normal blocking read,
# so the sweep can never add more than CAP x SWEEP_MS latency.
SWEEP_MS = 50
SWEEP_MISS_CAP = 4


def configured():
    """The ``HOROVOD_CONTROL_PLANE`` knob, normalized: ``"flat"``,
    ``"hier"``, or ``""`` (auto: hier when the slice layout has >1
    slice)."""
    v = os.environ.get("HOROVOD_CONTROL_PLANE", "").strip().lower()
    if v in ("flat", "hier"):
        return v
    return ""


def _live_num_slices():
    """Slice count of the live topology (device-derived multi-slice), or
    0 to defer to the forced ``HOROVOD_MESH_SLICES`` layout."""
    try:
        from horovod_tpu.common import basics
        if basics.is_initialized() \
                and getattr(basics, "_sim_world", None) is None:
            topo = basics.topology()
            if topo.num_slices > 1:
                return topo.num_slices
    except Exception:  # noqa: BLE001 — uninitialized: env layout only
        pass
    return 0


def proc_slice_layout(n_procs, local_size=None, num_slices=None):
    """``(num_slices, procs_per_slice)`` over the PROCESS space, derived
    from the rank-space layout (:func:`topology.slice_layout`, the seam
    shared with ``_build_dcn_mesh``). Collapses to ``(1, n_procs)`` when
    the rank layout is single-slice or a process's rank block would
    straddle a slice boundary (processes own rank-major contiguous
    blocks, so the hierarchy is only usable when slice boundaries align
    with process boundaries)."""
    n_procs = max(int(n_procs), 1)
    if local_size is None:
        local_size = 1
        try:
            import jax
            local_size = max(int(jax.local_device_count()), 1)
        except Exception:  # noqa: BLE001 — no backend: 1 rank per proc
            pass
    size = n_procs * local_size
    k, rank_ss = slice_layout(size, num_slices or _live_num_slices()
                              or None)
    if k <= 1 or rank_ss % local_size != 0:
        return 1, n_procs
    per = rank_ss // local_size
    if per < 1 or n_procs % per != 0:
        return 1, n_procs
    return n_procs // per, per


def exchange_groups(procs, local_size=None):
    """Slice groups (ordered list of ordered process lists) for one
    hierarchical exchange over the sorted participant list ``procs`` —
    or ``None`` for the flat path (knob forced flat, 1-slice layout, or
    every participant landing in one slice). Resolved per call so an
    elastic shrink to an undivisible world degrades to flat on every
    process identically (the layout math is pure and the knob env is
    propagated)."""
    if configured() == "flat":
        return None
    try:
        import jax
        n_procs = jax.process_count()
    except Exception:  # noqa: BLE001
        n_procs = (max(procs) + 1) if procs else 1
    k, per = proc_slice_layout(n_procs, local_size=local_size)
    if k <= 1:
        return None
    groups = {}
    for p in procs:
        groups.setdefault(int(p) // per, []).append(p)
    if len(groups) <= 1:
        return None
    return [groups[s] for s in sorted(groups)]


def boundary_role(proc, groups, coordinator=0):
    """Fusion-boundary consumer role of ``proc`` under ``groups``:
    ``(slice_id, role, n_members)`` with role in ``{"root", "leader",
    "member"}``. The coordinator (who publishes, never consumes) and
    every process on a flat layout read the root key; each slice's
    leader — its lowest non-coordinator process — reads the root key and
    re-publishes to the slice key; everyone else reads the slice key.
    ``n_members`` is how many members the leader re-publishes for (0
    means the re-publish can be skipped)."""
    if groups is None or proc == coordinator:
        return 0, "root", 0
    for sid, g in enumerate(groups):
        if proc not in g:
            continue
        followers = [p for p in g if p != coordinator]
        leader = followers[0] if followers else None
        n_members = max(len(followers) - 1, 0)
        if proc == leader:
            return sid, "leader", n_members
        return sid, "member", n_members
    return 0, "root", 0


def exchange_plan(world, num_slices):
    """Structural per-role KV RPC counts for ONE negotiation round — the
    quantities the scaling guards and the static cost model price. Pure
    math over the SAME layout rules the runtime resolves
    (:func:`topology.slice_layout`)."""
    world = max(int(world), 1)
    k, per = slice_layout(world, num_slices or None)
    if k <= 1:
        n = world - 1
        return {"strategy": "flat", "num_slices": 1, "slice_size": world,
                "member_gets": n, "leader_gets": n,
                "leader_local_gets": 0, "leader_cross_gets": 0,
                "member_sets": 1, "leader_sets": 1,
                "round_gets_total": world * n}
    return {
        "strategy": "hier", "num_slices": k, "slice_size": per,
        # Members: publish own payload, read ONE fan-back blob.
        "member_gets": 1, "member_sets": 1,
        # Leaders: read their members, one leaders-only DCN round, then
        # publish the aggregate + the fan-back (3 sets incl. own key).
        "leader_local_gets": per - 1, "leader_cross_gets": k - 1,
        "leader_gets": (per - 1) + (k - 1), "leader_sets": 3,
        "round_gets_total": k * ((per - 1) + (k - 1)) + (world - k),
    }


def kv_shard_count(size, num_slices=None):
    """Launcher-side shard count for the sharded HTTP-KV plane: the
    ``HOROVOD_KV_SHARD_COUNT`` override, else one shard per slice of the
    ``size``-rank layout when the hierarchical control plane is armed
    (0 = unsharded)."""
    from horovod_tpu.common.config import _env_int
    explicit = _env_int("HOROVOD_KV_SHARD_COUNT", 0)
    if explicit:
        return max(explicit, 0)
    if configured() == "flat":
        return 0
    k, _ = slice_layout(max(int(size), 1), num_slices or None)
    return k if k > 1 else 0


# --- scope -> shard routing (the runner HTTP-KV convention) --------------

_SLICE_SCOPE_SEP = "@s"


def slice_scope(scope, sid):
    """Slice-local spelling of ``scope``: routed by
    :class:`~horovod_tpu.runner.http_kv.KVStoreClient` (and the server's
    in-process accessors) to slice ``sid``'s shard listener when shards
    exist, and served from the root store (as a distinct scope) when they
    don't."""
    return f"{scope}{_SLICE_SCOPE_SEP}{int(sid)}"


def shard_of_scope(scope, n_shards):
    """Shard index for ``scope`` (``None`` = the root store): scopes
    carrying the ``@s<k>`` suffix resolve to shard ``k % n_shards``,
    job-global scopes to the root."""
    if n_shards <= 0:
        return None
    i = scope.rfind(_SLICE_SCOPE_SEP)
    if i < 0:
        return None
    try:
        sid = int(scope[i + len(_SLICE_SCOPE_SEP):])
    except ValueError:
        return None
    return sid % int(n_shards)


# --- KV adapters ---------------------------------------------------------

class CoordKV:
    """The jax.distributed coordination-service client behind the small
    set/get/delete surface the exchange implementations drive (so the
    virtual-world simulator can substitute :class:`LocalKV`)."""

    __slots__ = ("_c",)

    def __init__(self, client):
        self._c = client

    def set(self, key, value, overwrite=False):
        if overwrite:
            try:
                self._c.key_value_set(key, value, allow_overwrite=True)
                return
            except TypeError:  # older client: no overwrite kwarg
                pass
        self._c.key_value_set(key, value)

    def get(self, key, timeout_ms):
        # Blocking server-side until the key appears; raises on timeout.
        return self._c.blocking_key_value_get(key, int(timeout_ms))

    def delete(self, key):
        self._c.key_value_delete(key)


class LocalKV:
    """In-memory blocking KV with the :class:`CoordKV` surface — the
    virtual-world simulation tier (one thread per simulated rank drives
    the real exchange code against it).

    ``observer(op, key)`` (optional) is called once per ``set``/``get``
    entry — the event seam the scale digital twin
    (:mod:`horovod_tpu.sim`) and the dryrun cross-checks hang per-op
    accounting on. It runs OUTSIDE the condition lock, before the
    blocking wait, so an observer can never deadlock the exchange (and
    a get that times out still counts as the one RPC it issued)."""

    def __init__(self, observer=None):
        self._d = {}
        self._cv = threading.Condition()
        self._observer = observer

    def set(self, key, value, overwrite=False):
        if self._observer is not None:
            self._observer("set", key)
        with self._cv:
            if key in self._d and not overwrite:
                raise KeyError(f"key exists: {key}")
            self._d[key] = value
            self._cv.notify_all()

    def get(self, key, timeout_ms):
        if self._observer is not None:
            self._observer("get", key)
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._d:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"key not set: {key}")
                self._cv.wait(remaining)
            return self._d[key]

    def delete(self, key):
        with self._cv:
            self._d.pop(key, None)


# --- exchange implementations -------------------------------------------

def _rotated_after(procs, me):
    """Peers in ``procs`` order rotated to start just after ``me`` — the
    head-of-line fix: the peer most likely ready first (the one whose
    publish raced ours) is read first, and a slow early rank only delays
    the reads behind it instead of every read."""
    i = procs.index(me)
    return procs[i + 1:] + procs[:i]


def flat_exchange(kv, me, procs, base, blob, timeout_ms,
                  sweep_ms=SWEEP_MS):
    """One flat exchange round over ``kv``: publish own blob, read every
    peer rotated from ``me+1`` with a bounded short-timeout sweep before
    the long blocking pass. Returns ``(blobs_by_proc, counters)``."""
    kv.set(f"{base}/{me}", blob)
    got = {me: blob}
    pending = []
    attempts = 0
    order = _rotated_after(procs, me)
    misses = 0
    for p in order:
        if misses >= SWEEP_MISS_CAP:
            pending.append(p)
            continue
        attempts += 1
        try:
            got[p] = kv.get(f"{base}/{p}", sweep_ms)
        except Exception:  # noqa: BLE001 — not published yet
            misses += 1
            pending.append(p)
    for p in pending:
        attempts += 1
        got[p] = kv.get(f"{base}/{p}", timeout_ms)
    counters = {"sets": 1, "gets": len(procs) - 1, "attempts": attempts,
                "gets_local": 0, "gets_cross": 0, "gets_fanback": 0}
    return got, counters


def hier_exchange(kv, me, procs, base, blob, groups, timeout_ms):
    """One hierarchical exchange round: slice-local gather (members ->
    leader), ONE leaders-only cross-slice round, leader -> member
    fan-back. Returns ``(ordered_payloads, counters)`` where the payload
    list is bit-identical (same ordering, same JSON values) to the flat
    path's result over the same ``procs``.

    Key layout under ``base``: every participant publishes its own blob
    at ``{base}/{p}`` (flat-compatible); slice ``s``'s leader publishes
    the slice aggregate at ``{base}/agg/{s}`` and the full ordered
    fan-back at ``{base}/fb/{s}``. Blobs are raw JSON, so aggregation is
    string concatenation — no decode/re-encode drift between tiers."""
    sid = next(i for i, g in enumerate(groups) if me in g)
    group = groups[sid]
    leader = group[0]
    kv.set(f"{base}/{me}", blob)
    if me != leader:
        fanback = kv.get(f"{base}/fb/{sid}", timeout_ms)
        out = [p for g in json.loads(fanback) for p in g]
        counters = {"sets": 1, "gets": 1, "attempts": 1,
                    "gets_local": 0, "gets_cross": 0, "gets_fanback": 1}
        return out, counters
    # Slice-local gather, rotated like the flat path.
    raw_by_proc = {me: blob}
    for p in _rotated_after(group, me):
        raw_by_proc[p] = kv.get(f"{base}/{p}", timeout_ms)
    agg = "[" + ",".join(raw_by_proc[p] for p in group) + "]"
    kv.set(f"{base}/agg/{sid}", agg)
    # Leaders-only cross-slice round (the one DCN rendezvous).
    aggs = []
    for gi in range(len(groups)):
        aggs.append(agg if gi == sid
                    else kv.get(f"{base}/agg/{gi}", timeout_ms))
    fanback = "[" + ",".join(aggs) + "]"
    kv.set(f"{base}/fb/{sid}", fanback)
    out = [p for g in json.loads(fanback) for p in g]
    counters = {"sets": 3, "gets": (len(group) - 1) + (len(groups) - 1),
                "attempts": (len(group) - 1) + (len(groups) - 1),
                "gets_local": len(group) - 1,
                "gets_cross": len(groups) - 1, "gets_fanback": 0}
    return out, counters


def gc_exchange_keys(kv, me, base_prev, groups):
    """Best-effort deletion of one SUPERSEDED round's keys (the lag-2 GC
    discipline ``negotiation.exchange`` documents): own payload key
    always; the slice aggregate + fan-back too when this process led its
    slice that round."""
    keys = [f"{base_prev}/{me}"]
    if groups is not None:
        for sid, g in enumerate(groups):
            if g and g[0] == me:
                keys += [f"{base_prev}/agg/{sid}", f"{base_prev}/fb/{sid}"]
    for key in keys:
        try:
            kv.delete(key)
        except Exception:  # noqa: BLE001 — housekeeping only
            pass


# --- virtual-world dryrun tier ------------------------------------------

def simulate_exchange(world, num_slices, rounds=1, payload_fn=None,
                      strategy="hier", sweep_ms=5, observer=None):
    """Drive the REAL exchange implementations at a virtual world size:
    one thread per simulated rank over a :class:`LocalKV`, ``rounds``
    exchange rounds each. This is the n=128-512 control-plane dryrun —
    no devices, no processes, but the exact code path and the exact RPC
    counts (``docs/scale_validation.md``).

    Returns a dict with the resolved layout, whether every rank produced
    the identical ordered payload list (the SPMD contract), and per-role
    RPC counters aggregated over all rounds. ``observer`` is forwarded to
    :class:`LocalKV` — per-op ``(op, key)`` callbacks, the hook the twin
    parity cross-checks use."""
    world = int(world)
    procs = list(range(world))
    k, per = slice_layout(world, num_slices or None)
    hier = strategy == "hier" and k > 1
    groups = [procs[i * per:(i + 1) * per] for i in range(k)] if hier \
        else None
    kv = LocalKV(observer=observer)
    payload_fn = payload_fn or (lambda p, r: [p + 1, r, p % 7])
    counters = [dict.fromkeys(
        ("sets", "gets", "attempts", "gets_local", "gets_cross",
         "gets_fanback"), 0) for _ in procs]
    outs = [None] * world
    payload_bytes = [0] * world
    errors = []

    def run(p):
        try:
            for r in range(rounds):
                base = f"sim/{r}"
                blob = json.dumps(payload_fn(p, r))
                payload_bytes[p] += len(blob)
                if groups is None:
                    got, c = flat_exchange(kv, p, procs, base, blob,
                                           timeout_ms=120_000,
                                           sweep_ms=sweep_ms)
                    out = [json.loads(got[q]) for q in procs]
                else:
                    out, c = hier_exchange(kv, p, procs, base, blob,
                                           groups, timeout_ms=120_000)
                for key, v in c.items():
                    counters[p][key] += v
                outs[p] = out
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((p, repr(e)))

    threads = [threading.Thread(target=run, args=(p,), daemon=True)
               for p in procs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    hung = [p for p, t in zip(procs, threads) if t.is_alive()]
    if hung:
        # A deadlocked exchange must FAIL the dryrun, not score as a
        # clean run with zeroed counters (the guard exists for exactly
        # this failure mode).
        raise RuntimeError(
            f"simulated exchange deadlocked: {len(hung)} rank(s) still "
            f"blocked after 300s (first: {hung[:8]})")
    if errors:
        raise RuntimeError(f"simulated exchange failed: {errors[:4]}")
    identical = all(o == outs[0] for o in outs)
    leaders = [g[0] for g in groups] if groups else []
    member_gets = [counters[p]["gets"] for p in procs
                   if p not in leaders] if groups else \
        [counters[p]["gets"] for p in procs]
    leader_gets = [counters[p]["gets"] for p in leaders]
    return {
        "world": world, "num_slices": k if hier else 1,
        "slice_size": per if hier else world,
        "strategy": "hier" if hier else "flat", "rounds": rounds,
        "identical": identical, "per_proc": counters,
        "payload_bytes": sum(payload_bytes),
        "gets_total": sum(c["gets"] for c in counters),
        "member_gets_per_round": (max(member_gets) / rounds)
        if member_gets else 0.0,
        "leader_gets_per_round": (max(leader_gets) / rounds)
        if leader_gets else 0.0,
        "result": outs[0],
    }
