"""Device topology and mesh construction.

The reference derives rank/local_rank/cross_rank from the launcher env and builds
MPI/Gloo communicators for the global, node-local, and cross-node rings
(reference: horovod/common/mpi/mpi_context.h, gloo/gloo_context.cc:67-94).

TPU-native replacement: one ``jax.sharding.Mesh`` over all addressable devices.
A Horovod *rank* is a mesh position (one chip), not an OS process:

- ``hvd`` axis   — flat 1-D axis over all chips; global collectives ride ICI.
- ``cross``/``local`` axes — 2-D factorization (host × chip-per-host) used by the
  hierarchical/torus allreduce equivalents, mapping the reference's
  NCCLHierarchicalAllreduce / NCCLTorusAllreduce two-level strategies
  (reference: horovod/common/ops/nccl_operations.cc:606-843) onto DCN × ICI.

Multi-process (multi-host) setups get the same mesh via ``jax.distributed``; each
process contributes its local devices, and rank r owns device ``mesh.devices[r]``.
"""

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

HVD_AXIS = "hvd"
CROSS_AXIS = "cross"
LOCAL_AXIS = "local"


@dataclasses.dataclass
class Topology:
    """Resolved topology for one init() call."""

    devices: list                  # all devices, rank-major order
    mesh: Mesh                     # 1-D mesh, axis ('hvd',)
    mesh2d: Mesh                   # 2-D mesh, axes ('cross', 'local')
    size: int                      # number of ranks == number of chips
    local_size: int                # chips per host
    cross_size: int                # number of hosts
    process_index: int             # this process's index (0 in single-controller)
    local_device_ranks: list       # ranks owned by this process
    num_slices: int = 1            # TPU slices (DCN-connected groups)
    mesh_dcn: Mesh = None          # ('cross', 'local') = (slice, chips-in-
    #                                slice) when multi-slice, else None

    def rank_of_device(self, device):
        return self.devices.index(device)

    @property
    def hierarchical_mesh(self):
        """The mesh the 2-level strategies (torus/hierarchical allreduce,
        parallel/strategies.py) should run over: slice-boundary factorization
        when the job spans DCN (the slow link the 2-level schedule exists
        for), host-boundary otherwise."""
        return self.mesh_dcn if self.mesh_dcn is not None else self.mesh2d


def _sorted_devices(devices):
    # Rank-major order: group by host (process_index), then stable device order
    # within the host. This makes local_rank = rank % local_size and
    # cross_rank = rank // local_size, matching the reference's host-major slot
    # assignment (reference: horovod/runner/common/util/hosts.py:100
    # get_host_assignments).
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def _slice_id(d):
    """TPU slice id of a device in a multi-slice (DCN) job, else None.
    jax renamed slice_index → partition_index; accept both."""
    for attr in ("slice_index", "partition_index"):
        v = getattr(d, attr, None)
        if v is not None:
            return v
    return None


def forced_slices():
    """The ``HOROVOD_MESH_SLICES`` override, or 0 when unset (same
    semantics as the mesh construction's own read)."""
    from horovod_tpu.common.config import _env_int
    return _env_int("HOROVOD_MESH_SLICES", 0)


def slice_layout(size, num_slices=None):
    """``(num_slices, slice_size)`` for a ``size``-rank world: the SAME
    divisibility rules :func:`_build_dcn_mesh` applies when it builds the
    real DCN mesh, so static consumers (the analysis cost model's tier
    classifier) and the runtime hierarchy can never disagree. An explicit
    ``num_slices`` wins over the forced env knob; an undivisible or <2
    slice count collapses to the single-slice layout, exactly like the
    mesh construction does."""
    size = max(int(size), 1)
    k = int(num_slices) if num_slices else forced_slices()
    if k <= 1 or size % k != 0:
        return 1, size
    return k, size // k


def slice_of_rank(rank, slice_size):
    """Slice id of a rank under the rank-major (slice, chips-in-slice)
    reshape — the layout :func:`_build_dcn_mesh` materializes."""
    return int(rank) // max(int(slice_size), 1)


def _build_dcn_mesh(devices, size):
    """(slice × chips-per-slice) mesh when the job spans multiple TPU
    slices — the factorization whose 'cross' axis is the DCN, which is what
    the 2-level allreduce strategies actually want (reference mapping:
    SURVEY §5.8; NCCLTorusAllreduce's node boundary ↔ slice boundary).

    ``HOROVOD_MESH_SLICES=k`` overrides/fakes the slice count (virtual-CPU
    tier testing of the DCN path; also multi-slice setups whose devices
    don't expose slice ids).
    """
    if forced_slices():
        k, per = slice_layout(size)
        if k <= 1:
            return 1, None
        arr = np.array(devices, dtype=object).reshape(k, per)
        return k, Mesh(arr, (CROSS_AXIS, LOCAL_AXIS))
    sids = [_slice_id(d) for d in devices]
    if any(s is None for s in sids):
        return 1, None
    uniq = sorted(set(sids))
    k = len(uniq)
    # Every slice must hold exactly size/k devices: a reshape over unequal
    # slices would mix slices within a row, silently putting the 'local'
    # axis across DCN — the opposite of what this mesh exists for.
    if k <= 1 or any(sids.count(s) != size // k for s in uniq):
        return max(k, 1), None
    order = sorted(devices,
                   key=lambda d: (_slice_id(d), d.process_index, d.id))
    arr = np.array(order, dtype=object).reshape(k, size // k)
    return k, Mesh(arr, (CROSS_AXIS, LOCAL_AXIS))


def build_topology(devices=None):
    """Build the global topology over all (or the given) devices."""
    if devices is None:
        devices = jax.devices()
    devices = _sorted_devices(list(devices))
    size = len(devices)

    # Chips per host. On TPU pods every host exposes the same number of chips;
    # fall back to size (single host) when process information is unavailable.
    proc_ids = sorted({d.process_index for d in devices})
    cross_size = len(proc_ids)
    if size % cross_size != 0:
        raise ValueError(
            f"Non-uniform hosts: {size} devices over {cross_size} processes. "
            f"Horovod-TPU requires the same chip count per host.")
    local_size = size // cross_size

    dev_array = np.array(devices, dtype=object)
    mesh = Mesh(dev_array, (HVD_AXIS,))
    mesh2d = Mesh(dev_array.reshape(cross_size, local_size), (CROSS_AXIS, LOCAL_AXIS))

    process_index = jax.process_index()
    local_device_ranks = [i for i, d in enumerate(devices)
                          if d.process_index == process_index]

    num_slices, mesh_dcn = _build_dcn_mesh(devices, size)

    return Topology(
        devices=devices,
        mesh=mesh,
        mesh2d=mesh2d,
        size=size,
        local_size=local_size,
        cross_size=cross_size,
        process_index=process_index,
        local_device_ranks=local_device_ranks,
        num_slices=num_slices,
        mesh_dcn=mesh_dcn,
    )


def build_submesh(topology, ranks):
    """A 1-D mesh over a subset of ranks (a process set's communicator).

    Maps the reference's per-process-set communicators
    (reference: horovod/common/process_set.cc) onto a device sub-mesh.
    """
    devs = np.array([topology.devices[r] for r in ranks], dtype=object)
    return Mesh(devs, (HVD_AXIS,))
