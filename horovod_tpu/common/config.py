"""Runtime configuration knobs.

The reference core reads ~30 ``HOROVOD_*`` environment variables at background-thread
start (reference: horovod/common/operations.cc:456-646, full knob list
horovod/common/common.h:115-149). This module is the TPU-native mirror: every knob is
an attribute of :class:`Config`, populated from the same environment variable names so
launcher-side ``config_parser.set_env_from_args`` semantics carry over unchanged.

Knobs that only make sense for the CUDA/NCCL runtime (num NCCL streams, GPU ops
selection) are accepted-and-ignored for compatibility; TPU-specific knobs are added
under the same naming convention.
"""

import dataclasses
import os


def _env_bool(name, default=False):
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


def _env_int(name, default):
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _env_float(name, default):
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return float(v)
    except ValueError:
        return default


@dataclasses.dataclass
class Config:
    # --- fusion / cycle (reference common.h:119-121, operations.cc:515,551) ---
    # Fusion buffer threshold in bytes; batches small eager tensors into one
    # fused collective. Reference default 128 MB - ours is 64 MB because XLA
    # fuses aggressively already and HBM is the scarce resource.
    fusion_threshold: int = 64 * 1024 * 1024
    # Background flush cycle in ms for the eager bucketing runtime.
    cycle_time_ms: float = 1.0

    # --- cache (reference common.h:122-123) ---
    # Compiled-program cache capacity (the response cache's TPU analog is the
    # jit cache keyed on the fused tensor-set signature).
    cache_capacity: int = 1024

    # --- algorithm selection (reference common.h:130-132) ---
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    torus_allreduce: bool = False  # fork knob HOROVOD_TORUS_ALLREDUCE (common.h:132)

    # --- autotune (reference common.h:133-138) ---
    autotune: bool = False
    autotune_log_file: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8

    # --- autopilot (horovod_tpu/autopilot; ROADMAP item 4 — the online
    # self-driving controller: closed-loop tuning over the signal plane
    # plus automated straggler/dead-rank remediation through the elastic
    # driver). Opt-in: decisions change compiled programs and can remove
    # hosts; see docs/performance.md for the levers/guardrails and the
    # docs/troubleshooting.md "the controller removed my rank" runbook.
    autopilot: bool = False
    # Decision-epoch cadence in seconds (the controller thread's tick).
    autopilot_interval: float = 10.0
    # Remediation rate limiter: at most this many controller-initiated
    # host removals per rolling window (autopilot/remediate.WINDOW_S).
    autopilot_max_removals: int = 1
    # Hysteresis: a rank must be named (watchdog straggler / telemetry
    # dead/stalled) this many CONSECUTIVE decision epochs before the
    # controller may act on it.
    autopilot_hysteresis: int = 3
    # Do-not-shrink floor: never remediate below this world size
    # (0 = derive: the elastic launch's --min-np, else 1).
    autopilot_min_world: int = 0
    # Twin-pretrained warm start: path to an export_observations JSON
    # artifact (horovod_tpu.sim.autopilot writes one) — the controller
    # skips the categorical sweep and starts the numeric search at the
    # twin's best point. "" = cold start. A mismatched/malformed prior
    # is rejected with a warning, never fatal.
    autopilot_prior: str = ""

    # --- hvdsim scale digital twin (horovod_tpu/sim; ROADMAP item 3) —
    # latency model of the virtual control plane: base cost of one KV
    # RPC and the cross-slice (DCN) surcharge, in microseconds. The
    # twin's guards assert on RPC *counts*; these only shape virtual
    # timings (docs/scale_validation.md).
    sim_kv_us: float = 5.0
    sim_dcn_us: float = 50.0

    # --- timeline (reference common.h:117-118) ---
    timeline_filename: str = ""
    timeline_mark_cycles: bool = False

    # --- stall inspector (reference common.h:124-125) ---
    stall_check_disable: bool = False
    stall_check_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0
    # Debug: cross-rank verification that every process dispatches the same
    # eager collective with the same signature, in the same order — turns
    # SPMD-contract violations (which otherwise hang or corrupt) into
    # immediate errors. The runtime analog of the reference coordinator's
    # shape/dtype mismatch checks (controller.h:158-163), extended with
    # order checking. Costs one tiny KV exchange per collective: debug only.
    order_check: bool = False

    # --- elastic / process sets (reference common.h:139-143) ---
    elastic: bool = False
    # Accepted for launcher compatibility; NOT a gate here. The reference
    # requires HOROVOD_DYNAMIC_PROCESS_SETS=1 before add/remove at runtime
    # because dynamic sets cost it communicator construction; on TPU a
    # process set is a sub-mesh + compiled-program cache entry, so dynamic
    # add/remove is always available (process_sets.py).
    dynamic_process_sets: bool = False
    # Multi-process JOIN (uneven final batches across hosts, reference
    # controller.cc:269-327). The reference's background controller
    # negotiates every collective, which is what lets a joined rank keep
    # answering with zero contributions; the TPU hot path has no such
    # negotiation, so JOIN across processes is an opt-in mode: while armed,
    # every global-set eager collective starts with one tiny KV round
    # (see ops/collective_ops._join_sync). Single-controller join() needs
    # no mode flag.
    join_mode: bool = False

    # --- bootstrap (reference gloo_run.py:203-214 env plumbing) ---
    rank: int = 0
    local_rank: int = 0
    cross_rank: int = 0
    size: int = -1
    local_size: int = -1
    cross_size: int = -1
    coordinator_addr: str = ""
    coordinator_port: int = 0

    # --- TPU-specific additions ---
    # Wire dtype for collective payloads ("" = keep dtype): float16/bfloat16
    # cast the fused buckets; int8/fp8 route eligible allreduces — eager,
    # fused AND the in-jit entry points — through the block-scaled
    # quantized exchange (ops/wire.py; fp8 falls back to bfloat16 when the
    # dtype is missing). Overridable per process set at runtime via
    # hvd.set_wire_dtype (the autotuner steers the global set through the
    # same registry).
    wire_dtype: str = ""
    # Per-link-tier wire policy: the wire dtype of the CROSS-SLICE (DCN)
    # leg of the hierarchical dispatch tier ("" = inherit wire_dtype).
    # The ICI legs of the 2-level decomposition always stay exact — this
    # knob quantizes only the scarce inter-slice hop (the EQuARX
    # deployment shape). Overridable per process set via
    # hvd.set_wire_dtype(dtype, tier="dcn").
    wire_dtype_dcn: str = ""
    # Hierarchical dispatch tier (ROADMAP item 3): when a slice hierarchy
    # exists (HOROVOD_MESH_SLICES / multi-slice topology), eligible
    # allreduces on all three dispatch paths decompose into local RS
    # (exact, ICI) -> cross-slice allreduce (wire_dtype_dcn, DCN) ->
    # local AG. Opt-in: a 1-slice layout would pay two extra ICI legs for
    # no DCN saving (hvdlint HVP113).
    hierarchical_dispatch: bool = False
    # Hierarchical ALLTOALL tier (MoE expert dispatch, ISSUE 18): when a
    # slice hierarchy exists, eligible equal-splits alltoalls (eager) and
    # the MoE layer's dispatch/combine (jit) decompose into a slice-local
    # a2a (ICI) -> cross-slice a2a on the per-tier wire (DCN). Opt-in for
    # the same reason as hierarchical_dispatch (hvdlint HVP113). Distinct
    # from the allreduce knob on purpose: a2a moves activations.
    hierarchical_alltoall: bool = False
    # Wire dtype of the hierarchical alltoall's CROSS-SLICE (DCN) leg
    # ("" = exact). Deliberately does NOT inherit wire_dtype/
    # wire_dtype_dcn: alltoall payloads are activations without error
    # feedback, so quantizing them is an explicit choice
    # (docs/performance.md "when NOT to quantize the expert leg").
    # Overridable per process set via hvd.set_alltoall_cross_dtype.
    alltoall_cross_dtype: str = ""
    # Cross-leg overlap in the fusion flush scheduler: the DCN leg of a
    # hierarchical bucket is left in flight at flush return and only
    # awaited when the next flush (or the step boundary / a sync
    # collective's fence) needs it, booking the wait to the step
    # profiler's cross_wait category instead of the flush critical path.
    cross_overlap: bool = True
    # Error feedback for the quantized wire: keep each bucket's fp32
    # quantization error and add it back before the next quantize
    # (eager + fused paths; in-jit callers thread residuals themselves).
    # Residuals are zeroed by clear_program_caches / elastic reset.
    wire_error_feedback: bool = True
    # Donate fused buffers to XLA (buffer reuse).
    donate_buffers: bool = True
    # Donate SYNC eager-collective inputs that are already correctly-sharded
    # jax.Arrays (the dispatch-plan fast path). Requires the caller to treat
    # allreduce as consuming its input, so it is opt-in: armed only when
    # HOROVOD_DONATE_BUFFERS is set EXPLICITLY (and truthy) in the
    # environment — the default-on donate_buffers above covers only the
    # fusion runtime's host-staged buckets, which alias nothing.
    donate_eager: bool = False
    # Persistent XLA compilation cache directory (HOROVOD_COMPILE_CACHE_DIR;
    # "" = off). Wired to jax's compilation cache in basics.init so elastic
    # re-rendezvous and repeat launches skip recompiles — recovery time is
    # a perf metric too. See docs/performance.md.
    compile_cache_dir: str = ""

    # --- hierarchical control plane (common/control_plane.py) ---
    # flat | hier | "" = auto (hier whenever the slice layout has >1
    # slice). hier decomposes negotiation.exchange into slice-local +
    # leaders-only rounds, mirrors fusion boundaries through slice
    # leaders, and (launcher-side) shards the HTTP-KV per slice — member
    # ranks issue O(1) blocking control-plane reads instead of O(world).
    control_plane: str = ""
    # Per-slice HTTP-KV shard listeners started by the launcher (0 =
    # one per slice when the hierarchical control plane is armed; an
    # explicit count overrides the slice layout).
    kv_shard_count: int = 0
    # First shard listener port; shard k binds base + k (0 = ephemeral
    # ports, propagated to workers via HOROVOD_KV_SHARD_PORTS).
    kv_shard_port_base: int = 0
    # Leader lease for the hierarchical fusion-boundary stream: a member
    # that observes a root boundary its slice leader has not re-published
    # within this window takes the re-publish role over.
    control_lease_ms: float = 2000.0

    # --- control-plane resilience (runner/http_kv.py KVStoreClient) ---
    # A single transient connection reset mid-negotiation used to kill the
    # caller; the client now retries transient transport faults (URLError,
    # connection reset, HTTP 5xx) this many times with jittered exponential
    # backoff before surfacing the error. 404s and other 4xx are semantic
    # answers, never retried.
    kv_retries: int = 3
    kv_retry_backoff_ms: float = 50.0
    kv_retry_backoff_max_ms: float = 2000.0

    # --- chaos / fault injection (horovod_tpu/chaos; docs/robustness.md).
    # A seeded declarative fault plan: path to a YAML/JSON file or inline
    # text. "" = disarmed (every injection site is a single bool check).
    chaos_plan: str = ""
    # Seed overriding the plan's own (probabilistic triggers are a
    # counter-hash of seed x spec x call count — reproducible schedules).
    chaos_seed: int = 0
    # Directory for the per-rank JSONL injection ledgers.
    chaos_ledger: str = ""

    # --- flight recorder (horovod_tpu/flight; no reference analog — the
    # reference's timeline must be armed BEFORE the run, so unpredicted
    # failures leave no artifact). Always-on bounded event ring, dumped on
    # failure paths; budgeted by TestFlightRecorderOverhead.
    flight: bool = True
    # Ring capacity in events (two per collective: dispatch + complete).
    flight_capacity: int = 4096
    # Dump directory ("" = ./flight_dumps); hvdrun --flight-dir exports it
    # to every worker so the elastic driver collects per-rank dumps in one
    # place.
    flight_dir: str = ""

    # --- step profiler (horovod_tpu/profile; the Horovod-timeline idea
    # rebuilt as structured per-step accounting — docs/observability.md).
    # Always-on by default: the ledger hot path is a short lock + float
    # adds (guarded by TestStepProfilerOverhead).
    step_profiler: bool = True
    # JSONL stream of per-step records ("" = in-memory ring only);
    # rendered by `python -m horovod_tpu.profile.report`.
    step_report_file: str = ""
    # "a:b" = capture a jax.profiler trace from the step-a marker to the
    # step-b marker ("" = off).
    profile_steps: str = ""
    # Capture output directory ("" = ./profile_traces).
    profile_dir: str = ""
    # Watchdog cross-rank publish cadence in steps (0 = local-only).
    profile_publish_steps: int = 16

    # --- cluster telemetry plane (horovod_tpu/telemetry; no reference
    # analog — the reference's observability is strictly per-rank).
    # Hierarchical rank → slice-leader → job-view aggregation over the
    # launcher HTTP-KV; armed by hvd.init when the KV is reachable and
    # the world is multi-process. See docs/observability.md.
    telemetry: bool = True
    # Beacon/aggregation round cadence in seconds.
    telemetry_interval: float = 2.0
    # Include the mergeable metrics snapshot in each digest (=0 keeps
    # beacons minimal: liveness + step + anomaly counts only).
    telemetry_metrics: bool = True
    # Health thresholds (0 = derive from the interval; see
    # telemetry/health.thresholds): beacon age marking a rank dead, step
    # clock stop marking it stalled.
    telemetry_dead_after: float = 0.0
    telemetry_stall_after: float = 0.0
    # Step-lag (vs the job median) marking a rank straggling, and
    # global-collective-seq lag marking it desynced.
    telemetry_step_lag: int = 5
    telemetry_seq_lag: int = 64

    # --- logging (common/logging.py; reference: HOROVOD_LOG_LEVEL /
    # HOROVOD_LOG_HIDE_TIME, horovod/common/logging.cc) ---
    log_level: str = "warning"
    log_hide_time: bool = False

    # --- elastic control knobs read outside Config (declared here so the
    # launcher propagates them and the docs catalogue them; the reading
    # sites keep their import-time env reads) ---
    # Coordination-service heartbeat window under HOROVOD_ELASTIC (s).
    elastic_heartbeat_timeout: int = 10
    # "min,max" cooldown seconds before a blacklisted host is retried
    # (runner/elastic/discovery.py; "" = built-in defaults).
    blacklist_cooldown_range: str = ""
    # Ports the membership watchdog's data-plane abort must never sever
    # (comma-separated; common/sockets.py).
    abort_exclude_ports: str = ""
    # Virtual slice layout override "slices" or "slices:len" for the
    # hierarchical telemetry/topology plane (common/topology.py).
    mesh_slices: str = ""

    # --- step-profiler tuning (horovod_tpu/profile; the always-on knobs
    # above arm the subsystem, these tune it) ---
    # Completed per-step records kept in the in-memory ring.
    profile_history: int = 512
    # Per-peer KV read budget in the watchdog's cross-rank round (ms).
    profile_publish_timeout_ms: int = 250
    # Robust z-score marking a rank a straggler, and the minimum absolute
    # excess (ms) so microsecond jitter never trips it.
    profile_z_threshold: float = 4.0
    profile_straggler_min_ms: float = 5.0
    # Roofline peak overrides (0 = detected chip table): bf16 TFLOP/s,
    # HBM / ICI / DCN GB/s (profile/roofline.py).
    peak_tflops: float = 0.0
    peak_hbm_gbs: float = 0.0
    peak_ici_gbs: float = 0.0
    peak_dcn_gbs: float = 0.0

    # --- Pallas flash-attention kernels (ops/pallas/flash_attention.py) ---
    # Tile-size cap for on-chip sweeps (0 = auto).
    flash_block: int = 0
    # Re-enable the kernels on non-multiple-of-block shapes (padded
    # path; off pending silicon sentinel evidence — ROADMAP item 4).
    flash_allow_padded: bool = False

    # --- static cost model (horovod_tpu/analysis/cost.py) ---
    # Per-step DCN byte budget for the static link-tier cost model: when
    # > 0, `python -m horovod_tpu.analysis.cost` (and cost_report) raise
    # HVP111 tier_budget_exceeded if the predicted cross-slice bytes of
    # one step exceed it. 0 = no budget declared.
    dcn_bytes_budget: int = 0

    # --- bench/progress plumbing (bench.py, chaos/soak.py) ---
    # JSONL progress stream consumed by the evidence sentinel ("" = off).
    bench_progress_file: str = ""

    # --- serving (horovod_tpu/serving; docs/inference.md) ---
    # Serving mode: `hvdrun --serving` sets it; `python -m
    # horovod_tpu.serving` is the reference worker it launches.
    serving: bool = False
    # Request-frontend base port (each process binds port + local_rank,
    # like the metrics endpoint; 0 = bind a free port).
    serving_port: int = 0
    # Decode-batch slot count — the continuous batch's fixed width (one
    # compiled decode program; slots retire/refill independently).
    serving_slots: int = 4
    # KV-cache capacity per slot in tokens (prompt + generation; 0 =
    # the model config's max_position_embeddings).
    serving_max_len: int = 0
    # Chunked-prefill feed width in tokens (time-to-first-token costs
    # ~P/chunk forwards; the fp32 score transient scales with it).
    serving_prefill_chunk: int = 64
    # Admission-queue capacity (0 = unbounded). At the limit submits are
    # rejected with backpressure (HTTP 503 + Retry-After) and the
    # /serving/health frame reports saturated — the readiness gate's
    # stop-routing-here signal.
    serving_queue_limit: int = 0
    # Migrate in-flight KV caches through elastic membership changes as
    # host snapshots (graceful scale up/down resumes decoding without
    # re-prefill). Off: in-flight requests re-queue from their last
    # committed token and re-prefill — same token streams either way.
    serving_migrate_kv: bool = False
    # Reference-worker model selector (gpt_tiny | gpt2 | llama_tiny).
    serving_model: str = "gpt_tiny"
    # Elastic commit cadence in engine steps (the requeue granularity: a
    # disruption replays at most this many tokens per in-flight request).
    serving_commit_steps: int = 1

    # --- request tracing + SLO burn rate (horovod_tpu/trace,
    # telemetry/slo.py; docs/observability.md) ---
    # Request/step-level span tracing: every serving request carries a
    # trace id from admission through requeue to completion, read live
    # at GET /debug/trace/<rid>; training steps trace negotiation /
    # flush / cross_wait spans. Always-on like the flight recorder (the
    # perf guard bounds the dispatch host cost at <= 2x tracing-off).
    trace: bool = True
    # Live request traces kept per process (bounded store; step traces
    # have their own smaller cap).
    trace_capacity: int = 256
    # Directory for per-rank trace shard dumps ("" = no dumps): the
    # serving frontend writes trace_r<rank>.json on stop, merged by
    # `python -m horovod_tpu.trace.analyze`.
    trace_dir: str = ""
    # Declared SLO objectives (0 = not declared): p99 TTFT target in ms
    # and a generated-tokens/sec floor. Burn rates are computed over
    # slo_window_s and exported as slo_burn_rate{objective}.
    slo_ttft_p99_ms: float = 0.0
    slo_tps: float = 0.0
    slo_window_s: float = 60.0

    # --- goodput/badput accounting + durable run history
    # (horovod_tpu/goodput; docs/observability.md "Goodput accounting").
    # Per-rank wall-clock decomposition into productive_compute vs named
    # badput categories with a 1% conservation guarantee. Always-on like
    # the flight recorder: the hot path is one boundary call per step.
    goodput: bool = True
    # Directory for per-rank goodput summary dumps at shutdown
    # (goodput_r<rank>.json; "" = no dumps).
    goodput_dir: str = ""
    # Durable cross-run history: rank 0 appends run_<id>.jsonl journals
    # (run id, config fingerprint, goodput heartbeats, bench records,
    # final cluster view) under this directory, one flushed line per
    # record so a killed run still leaves evidence. "" = off.
    run_history_dir: str = ""
    # Journal goodput-heartbeat cadence in seconds (HOROVOD_GOODPUT_JOURNAL_S):
    # how often rank 0 appends a goodput summary line, so a SIGKILLed run's
    # last record is at most this stale.
    goodput_journal_s: float = 10.0
    # Run id override (HOROVOD_RUN_ID): names the journal file
    # run_<id>.jsonl; default is a launch-time timestamp+pid. Set it when
    # an external scheduler already has a job id worth correlating on.
    run_id: str = ""

    # --- metrics / telemetry (horovod_tpu/metrics; no reference analog —
    # the reference's observability stops at timeline + stall inspector).
    # Always-on by default: the registry hot path is O(1) and lock-light
    # (guarded by tests/test_perf_guards.py TestMetricsOverheadBudget).
    metrics: bool = True
    # Scrape endpoint port; 0 = no HTTP server. Each process binds
    # port + local_rank: same-host processes must not collide, but every
    # host keeps the same base port for uniform scrape configs.
    metrics_port: int = 0
    # Scrape endpoint bind address. Prometheus-exporter convention is
    # bind-all (the payload is read-only telemetry, and an off-host
    # scraper is the point of the endpoint); set 127.0.0.1 to keep it
    # host-local on shared machines.
    metrics_addr: str = "0.0.0.0"
    # Series-name prefix in the text exposition.
    metrics_prefix: str = "horovod"

    def __post_init__(self):
        # Normalize/validate on EVERY construction path (env, CLI, direct):
        # the fusion runtime CASTS float buffers to a 16-bit wire dtype,
        # while "int8" routes the fused bucket through the two-phase
        # quantized exchange (strategies.allreduce_int8) — any other value
        # would silently destroy gradients.
        for attr in ("wire_dtype", "wire_dtype_dcn",
                     "alltoall_cross_dtype"):
            val = {"fp16": "float16",
                   "bf16": "bfloat16"}.get(getattr(self, attr),
                                           getattr(self, attr))
            setattr(self, attr, val)
            if val and val not in ("float16", "bfloat16", "int8", "fp8"):
                raise ValueError(
                    f"{attr}={val!r}: float16/bfloat16 (cast) "
                    "or int8/fp8 (block-scaled quantized exchange) are the "
                    "wire options; inside jit the same tier is reachable "
                    "via Compression.int8 on the optimizer or "
                    "strategies.allreduce_quantized")
        self.control_plane = (self.control_plane or "").strip().lower()
        if self.control_plane not in ("", "flat", "hier"):
            raise ValueError(
                f"control_plane={self.control_plane!r}: flat, hier, or "
                "empty (auto: hier when the slice layout has >1 slice)")
        if self.trace_capacity < 1:
            raise ValueError(
                f"trace_capacity={self.trace_capacity}: need >= 1 (the "
                "trace store must hold at least the request being read)")
        if self.slo_ttft_p99_ms < 0.0 or self.slo_tps < 0.0:
            raise ValueError(
                f"SLO targets must be >= 0 (0 = not declared), got "
                f"slo_ttft_p99_ms={self.slo_ttft_p99_ms}, "
                f"slo_tps={self.slo_tps}")
        if self.slo_window_s <= 0.0:
            raise ValueError(
                f"slo_window_s={self.slo_window_s}: the burn-rate "
                "window must be positive")
        # Normalize the goodput paths: surrounding whitespace in an env
        # var must not silently create a different directory, and the
        # journal requires goodput accounting (there would be nothing to
        # heartbeat into it).
        self.goodput_dir = (self.goodput_dir or "").strip()
        self.run_history_dir = (self.run_history_dir or "").strip()
        if self.run_history_dir and not self.goodput:
            raise ValueError(
                "run_history_dir requires goodput=True "
                "(HOROVOD_GOODPUT=1): the run journal's heartbeat IS the "
                "goodput summary")
        if self.goodput_journal_s <= 0.0:
            raise ValueError(
                f"goodput_journal_s={self.goodput_journal_s}: the journal "
                "heartbeat cadence must be positive")

    @classmethod
    def from_env(cls):
        c = cls()
        c.fusion_threshold = _env_int("HOROVOD_FUSION_THRESHOLD", c.fusion_threshold)
        c.cycle_time_ms = _env_float("HOROVOD_CYCLE_TIME", c.cycle_time_ms)
        c.cache_capacity = _env_int("HOROVOD_CACHE_CAPACITY", c.cache_capacity)
        c.hierarchical_allreduce = _env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE",
                                             c.hierarchical_allreduce)
        c.hierarchical_allgather = _env_bool("HOROVOD_HIERARCHICAL_ALLGATHER",
                                             c.hierarchical_allgather)
        c.torus_allreduce = _env_bool("HOROVOD_TORUS_ALLREDUCE", c.torus_allreduce)
        c.autotune = _env_bool("HOROVOD_AUTOTUNE", c.autotune)
        c.autotune_log_file = os.environ.get("HOROVOD_AUTOTUNE_LOG", c.autotune_log_file)
        c.autotune_warmup_samples = _env_int("HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
                                             c.autotune_warmup_samples)
        c.autotune_steps_per_sample = _env_int("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
                                               c.autotune_steps_per_sample)
        c.autotune_bayes_opt_max_samples = _env_int(
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", c.autotune_bayes_opt_max_samples)
        c.autotune_gaussian_process_noise = _env_float(
            "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", c.autotune_gaussian_process_noise)
        c.autopilot = _env_bool("HOROVOD_AUTOPILOT", c.autopilot)
        c.autopilot_interval = _env_float("HOROVOD_AUTOPILOT_INTERVAL",
                                          c.autopilot_interval)
        c.autopilot_max_removals = _env_int(
            "HOROVOD_AUTOPILOT_MAX_REMOVALS", c.autopilot_max_removals)
        c.autopilot_hysteresis = _env_int("HOROVOD_AUTOPILOT_HYSTERESIS",
                                          c.autopilot_hysteresis)
        c.autopilot_min_world = _env_int("HOROVOD_AUTOPILOT_MIN_WORLD",
                                         c.autopilot_min_world)
        c.autopilot_prior = os.environ.get("HOROVOD_AUTOPILOT_PRIOR",
                                           c.autopilot_prior)
        c.sim_kv_us = _env_float("HOROVOD_SIM_KV_US", c.sim_kv_us)
        c.sim_dcn_us = _env_float("HOROVOD_SIM_DCN_US", c.sim_dcn_us)
        c.timeline_filename = os.environ.get("HOROVOD_TIMELINE", c.timeline_filename)
        c.timeline_mark_cycles = _env_bool("HOROVOD_TIMELINE_MARK_CYCLES",
                                           c.timeline_mark_cycles)
        c.stall_check_disable = _env_bool("HOROVOD_STALL_CHECK_DISABLE",
                                          c.stall_check_disable)
        c.stall_check_time_seconds = _env_float("HOROVOD_STALL_CHECK_TIME_SECONDS",
                                                c.stall_check_time_seconds)
        c.stall_shutdown_time_seconds = _env_float(
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", c.stall_shutdown_time_seconds)
        c.order_check = _env_bool("HOROVOD_ORDER_CHECK", c.order_check)
        c.elastic = _env_bool("HOROVOD_ELASTIC", c.elastic)
        c.dynamic_process_sets = _env_bool("HOROVOD_DYNAMIC_PROCESS_SETS",
                                           c.dynamic_process_sets)
        c.join_mode = _env_bool("HOROVOD_JOIN_MODE", c.join_mode)
        c.rank = _env_int("HOROVOD_RANK", c.rank)
        c.local_rank = _env_int("HOROVOD_LOCAL_RANK", c.local_rank)
        c.cross_rank = _env_int("HOROVOD_CROSS_RANK", c.cross_rank)
        c.size = _env_int("HOROVOD_SIZE", c.size)
        c.local_size = _env_int("HOROVOD_LOCAL_SIZE", c.local_size)
        c.cross_size = _env_int("HOROVOD_CROSS_SIZE", c.cross_size)
        # mpirun/jsrun/srun-launched workers get no per-host HOROVOD_* rank
        # env; derive the process index from the MPI/PMI/Slurm-provided env
        # (reference: test/utils/common.py:32-64 mpi_env_rank_and_size reads
        # the same variables).
        if "HOROVOD_CROSS_RANK" not in os.environ:
            for rank_var, size_var in (
                    ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
                    ("PMI_RANK", "PMI_SIZE"),
                    ("SLURM_PROCID", "SLURM_NTASKS")):
                if rank_var in os.environ:
                    c.cross_rank = _env_int(rank_var, c.cross_rank)
                    c.cross_size = _env_int(size_var, c.cross_size)
                    break
        c.coordinator_addr = os.environ.get("HOROVOD_COORDINATOR_ADDR",
                                            c.coordinator_addr)
        c.coordinator_port = _env_int("HOROVOD_COORDINATOR_PORT", c.coordinator_port)
        c.wire_dtype = os.environ.get("HOROVOD_WIRE_DTYPE", c.wire_dtype)
        c.wire_dtype_dcn = os.environ.get("HOROVOD_WIRE_DTYPE_DCN",
                                          c.wire_dtype_dcn)
        c.hierarchical_dispatch = _env_bool("HOROVOD_HIERARCHICAL_DISPATCH",
                                            c.hierarchical_dispatch)
        c.hierarchical_alltoall = _env_bool("HOROVOD_HIERARCHICAL_ALLTOALL",
                                            c.hierarchical_alltoall)
        c.alltoall_cross_dtype = os.environ.get(
            "HOROVOD_ALLTOALL_CROSS_DTYPE", c.alltoall_cross_dtype)
        c.cross_overlap = _env_bool("HOROVOD_CROSS_OVERLAP",
                                    c.cross_overlap)
        c.wire_error_feedback = _env_bool("HOROVOD_WIRE_ERROR_FEEDBACK",
                                          c.wire_error_feedback)
        c.control_plane = os.environ.get("HOROVOD_CONTROL_PLANE",
                                         c.control_plane)
        c.kv_shard_count = _env_int("HOROVOD_KV_SHARD_COUNT",
                                    c.kv_shard_count)
        c.kv_shard_port_base = _env_int("HOROVOD_KV_SHARD_PORT_BASE",
                                        c.kv_shard_port_base)
        c.control_lease_ms = _env_float("HOROVOD_CONTROL_LEASE_MS",
                                        c.control_lease_ms)
        c.__post_init__()  # re-normalize after the env override
        c.donate_buffers = _env_bool("HOROVOD_DONATE_BUFFERS", c.donate_buffers)
        # Eager-path donation only on an EXPLICIT opt-in (see field docs).
        c.donate_eager = "HOROVOD_DONATE_BUFFERS" in os.environ \
            and c.donate_buffers
        c.compile_cache_dir = os.environ.get("HOROVOD_COMPILE_CACHE_DIR",
                                             c.compile_cache_dir)
        c.kv_retries = _env_int("HOROVOD_KV_RETRIES", c.kv_retries)
        c.kv_retry_backoff_ms = _env_float("HOROVOD_KV_RETRY_BACKOFF_MS",
                                           c.kv_retry_backoff_ms)
        c.kv_retry_backoff_max_ms = _env_float(
            "HOROVOD_KV_RETRY_BACKOFF_MAX_MS", c.kv_retry_backoff_max_ms)
        c.chaos_plan = os.environ.get("HOROVOD_CHAOS_PLAN", c.chaos_plan)
        c.chaos_seed = _env_int("HOROVOD_CHAOS_SEED", c.chaos_seed)
        c.chaos_ledger = os.environ.get("HOROVOD_CHAOS_LEDGER",
                                        c.chaos_ledger)
        c.flight = _env_bool("HOROVOD_FLIGHT_RECORDER", c.flight)
        c.flight_capacity = _env_int("HOROVOD_FLIGHT_CAPACITY",
                                     c.flight_capacity)
        c.flight_dir = os.environ.get("HOROVOD_FLIGHT_DIR", c.flight_dir)
        c.step_profiler = _env_bool("HOROVOD_STEP_PROFILER",
                                    c.step_profiler)
        c.step_report_file = os.environ.get("HVD_STEP_REPORT_FILE",
                                            c.step_report_file)
        c.profile_steps = os.environ.get("HOROVOD_PROFILE_STEPS",
                                         c.profile_steps)
        c.profile_dir = os.environ.get("HOROVOD_PROFILE_DIR",
                                       c.profile_dir)
        c.profile_publish_steps = _env_int("HOROVOD_PROFILE_PUBLISH_STEPS",
                                           c.profile_publish_steps)
        c.telemetry = _env_bool("HOROVOD_TELEMETRY", c.telemetry)
        c.telemetry_interval = _env_float("HOROVOD_TELEMETRY_INTERVAL",
                                          c.telemetry_interval)
        c.telemetry_metrics = _env_bool("HOROVOD_TELEMETRY_METRICS",
                                        c.telemetry_metrics)
        c.telemetry_dead_after = _env_float("HOROVOD_TELEMETRY_DEAD_AFTER",
                                            c.telemetry_dead_after)
        c.telemetry_stall_after = _env_float(
            "HOROVOD_TELEMETRY_STALL_AFTER", c.telemetry_stall_after)
        c.telemetry_step_lag = _env_int("HOROVOD_TELEMETRY_STEP_LAG",
                                        c.telemetry_step_lag)
        c.telemetry_seq_lag = _env_int("HOROVOD_TELEMETRY_SEQ_LAG",
                                       c.telemetry_seq_lag)
        c.log_level = os.environ.get("HOROVOD_LOG_LEVEL", c.log_level)
        c.log_hide_time = _env_bool("HOROVOD_LOG_HIDE_TIME",
                                    c.log_hide_time)
        c.elastic_heartbeat_timeout = _env_int(
            "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", c.elastic_heartbeat_timeout)
        c.blacklist_cooldown_range = os.environ.get(
            "HOROVOD_BLACKLIST_COOLDOWN_RANGE", c.blacklist_cooldown_range)
        c.abort_exclude_ports = os.environ.get(
            "HOROVOD_ABORT_EXCLUDE_PORTS", c.abort_exclude_ports)
        c.mesh_slices = os.environ.get("HOROVOD_MESH_SLICES",
                                       c.mesh_slices)
        c.profile_history = _env_int("HOROVOD_PROFILE_HISTORY",
                                     c.profile_history)
        c.profile_publish_timeout_ms = _env_int(
            "HOROVOD_PROFILE_PUBLISH_TIMEOUT_MS",
            c.profile_publish_timeout_ms)
        c.profile_z_threshold = _env_float("HOROVOD_PROFILE_Z_THRESHOLD",
                                           c.profile_z_threshold)
        c.profile_straggler_min_ms = _env_float(
            "HOROVOD_PROFILE_STRAGGLER_MIN_MS", c.profile_straggler_min_ms)
        c.peak_tflops = _env_float("HOROVOD_PEAK_TFLOPS", c.peak_tflops)
        c.peak_hbm_gbs = _env_float("HOROVOD_PEAK_HBM_GBS", c.peak_hbm_gbs)
        c.peak_ici_gbs = _env_float("HOROVOD_PEAK_ICI_GBS", c.peak_ici_gbs)
        c.peak_dcn_gbs = _env_float("HOROVOD_PEAK_DCN_GBS", c.peak_dcn_gbs)
        c.flash_block = _env_int("HVD_FLASH_BLOCK", c.flash_block)
        c.flash_allow_padded = _env_bool("HVD_FLASH_ALLOW_PADDED",
                                         c.flash_allow_padded)
        c.dcn_bytes_budget = _env_int("HOROVOD_DCN_BYTES_BUDGET",
                                      c.dcn_bytes_budget)
        c.bench_progress_file = os.environ.get("HVD_BENCH_PROGRESS_FILE",
                                               c.bench_progress_file)
        c.serving = _env_bool("HOROVOD_SERVING", c.serving)
        c.serving_port = _env_int("HOROVOD_SERVING_PORT", c.serving_port)
        c.serving_slots = _env_int("HOROVOD_SERVING_SLOTS",
                                   c.serving_slots)
        c.serving_max_len = _env_int("HOROVOD_SERVING_MAX_LEN",
                                     c.serving_max_len)
        c.serving_prefill_chunk = _env_int("HOROVOD_SERVING_PREFILL_CHUNK",
                                           c.serving_prefill_chunk)
        c.serving_queue_limit = _env_int("HOROVOD_SERVING_QUEUE_LIMIT",
                                         c.serving_queue_limit)
        c.serving_migrate_kv = _env_bool("HOROVOD_SERVING_MIGRATE_KV",
                                         c.serving_migrate_kv)
        c.serving_model = os.environ.get("HOROVOD_SERVING_MODEL",
                                         c.serving_model)
        c.serving_commit_steps = _env_int("HOROVOD_SERVING_COMMIT_STEPS",
                                          c.serving_commit_steps)
        c.trace = _env_bool("HOROVOD_TRACE", c.trace)
        c.trace_capacity = _env_int("HOROVOD_TRACE_CAPACITY",
                                    c.trace_capacity)
        c.trace_dir = os.environ.get("HOROVOD_TRACE_DIR", c.trace_dir)
        c.slo_ttft_p99_ms = _env_float("HOROVOD_SLO_TTFT_P99_MS",
                                       c.slo_ttft_p99_ms)
        c.slo_tps = _env_float("HOROVOD_SLO_TPS", c.slo_tps)
        c.slo_window_s = _env_float("HOROVOD_SLO_WINDOW_S",
                                    c.slo_window_s)
        c.goodput = _env_bool("HOROVOD_GOODPUT", c.goodput)
        c.goodput_dir = os.environ.get("HOROVOD_GOODPUT_DIR",
                                       c.goodput_dir)
        c.run_history_dir = os.environ.get("HOROVOD_RUN_HISTORY_DIR",
                                           c.run_history_dir)
        c.goodput_journal_s = _env_float("HOROVOD_GOODPUT_JOURNAL_S",
                                         c.goodput_journal_s)
        c.run_id = os.environ.get("HOROVOD_RUN_ID", c.run_id)
        c.__post_init__()  # re-validate: goodput paths read after the
        # control-plane re-normalization above
        c.metrics = _env_bool("HOROVOD_METRICS", c.metrics)
        c.metrics_port = _env_int("HOROVOD_METRICS_PORT", c.metrics_port)
        c.metrics_addr = os.environ.get("HOROVOD_METRICS_ADDR",
                                        c.metrics_addr)
        c.metrics_prefix = os.environ.get("HOROVOD_METRICS_PREFIX",
                                          c.metrics_prefix)
        return c
