"""Global runtime state and the core init/query API.

TPU-native equivalent of the reference's ``HorovodBasics`` ctypes wrapper +
C API (reference: horovod/common/basics.py:29-505 and
horovod/common/operations.cc:934-1449 ``horovod_init``/``horovod_rank``/...).

Key semantic shift: the reference binds one OS process to one accelerator, so
``rank()`` is "my process". On TPU a single controller process typically owns
many chips, so a *rank is a chip* (a position in the global mesh). For each
process:

- ``size()``/``local_size()``/``cross_size()`` describe the global chip mesh,
- ``rank()`` is the first chip this process owns (0 on a single controller),
- ``process_index()``/``process_count()`` expose the host-level view.

There is no background negotiation thread: jitted collectives need no per-step
negotiation (the compile cache keyed on tensor signatures plays the reference's
response-cache role, reference: horovod/common/response_cache.h:45), and the
eager path's bucketing runtime lives in :mod:`horovod_tpu.ops.fusion`.
"""

import atexit
import os
import threading

import jax

from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.common.config import Config
from horovod_tpu.common.exceptions import NotInitializedError
from horovod_tpu.common.topology import build_topology

_lock = threading.RLock()
_state = None


def _distributed_client_active():
    try:
        if hasattr(jax.distributed, "is_initialized"):
            return jax.distributed.is_initialized()
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False


def _current_coordinator():
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.coordinator_address
    except Exception:
        return None


class _State:
    def __init__(self, topology, config):
        self.topology = topology
        self.config = config
        self.process_set_table = None   # set by process_sets module
        self.timeline = None            # set lazily by timeline module
        self.fusion = None              # set lazily by ops.fusion
        self.parameter_manager = None   # set lazily by autotune
        self.joined_ranks = set()       # ranks that called join()
        self.shutdown_called = False


def init(comm=None, process_sets=None, devices=None):
    """Initialize Horovod-TPU.

    Mirrors ``hvd.init(comm, process_sets)`` (reference: basics.py:51-148).
    ``comm`` is accepted for API compatibility; rank subsets should use
    ``process_sets`` / :class:`horovod_tpu.ProcessSet` instead.

    In a multi-host launch (``hvdrun``), ``jax.distributed`` bootstrap replaces
    the reference's Gloo HTTP-KV rendezvous (reference:
    horovod/common/gloo/gloo_context.cc:160-230): the launcher exports
    ``HOROVOD_COORDINATOR_ADDR/PORT`` + ``HOROVOD_CROSS_RANK/CROSS_SIZE`` and we
    call ``jax.distributed.initialize`` here.
    """
    global _state
    with _lock:
        if _state is not None:
            return
        config = Config.from_env()

        # Chaos fault injection (HOROVOD_CHAOS_PLAN): armed before any
        # control-plane traffic so the whole init/rendezvous path is
        # injectable. Idempotent across elastic in-place re-inits — site
        # counters and the injection ledger survive shutdown()/init()
        # cycles within one process (a mid-plan reset would re-fire
        # already-spent faults).
        if config.chaos_plan:
            from horovod_tpu.chaos import injector as _chaos_injector
            _chaos_injector.install_from_env()

        # Flight recorder (always-armed crash forensics): configured
        # before any dispatch so the ring covers init/rendezvous too.
        # configure() never clears a live ring — elastic in-place
        # re-init must keep the pre-failure events (they ARE the
        # evidence a post-mortem needs).
        from horovod_tpu.flight import recorder as _flight_recorder
        _flight_recorder.configure(config)

        # Step profiler: arm the per-step ledger/watchdog/capture knobs
        # before any dispatch so attribution covers the first step.
        # Completed records survive re-init (like the flight ring); only
        # the open window resets (basics.shutdown).
        from horovod_tpu.profile import ledger as _profile_ledger
        _profile_ledger.configure(config)

        # Request/step tracing + declared SLOs: armed next to the flight
        # recorder (the trace store survives re-init for the same reason
        # the ring does — a requeued request's spans ARE its history).
        from horovod_tpu import trace as _trace
        _trace.configure(config)
        from horovod_tpu.telemetry import slo as _slo
        _slo.configure(config)

        # Goodput accounting: start the wall clock before the distributed
        # bootstrap so rendezvous + compile book to init_compile. The
        # ledger survives elastic re-init (configure is start-once); the
        # durable run journal arms after bootstrap, once the rank is
        # known (rank 0 only).
        from horovod_tpu.goodput import ledger as _goodput
        _goodput.configure(config)

        # Decide on distributed bootstrap from the env alone: probing
        # jax.process_count() here would initialize the local backend and
        # forbid jax.distributed.initialize afterwards.
        if config.coordinator_addr and config.cross_size > 1:
            # Multi-process CPU tier (tests, soaks, dry runs): jax 0.4.x
            # ships cross-process CPU collectives behind an explicit gloo
            # selection — without it every multi-device program dies with
            # "Multiprocess computations aren't implemented on the CPU
            # backend". Newer jax defaults to gloo and drops the knob
            # (AttributeError), hence the probe.
            plat = (os.environ.get("JAX_PLATFORMS") or "").lower()
            if plat.startswith("cpu"):
                global _gloo_selected_by_init
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                    _gloo_selected_by_init = True
                except AttributeError:
                    pass
            target = f"{config.coordinator_addr}:{config.coordinator_port}"
            replace = False
            if _distributed_client_active():
                current = _current_coordinator()
                if current == target:
                    replace = False  # our cluster already bootstrapped
                    # Reused service ⇒ its KV store may hold the previous
                    # incarnation's last (un-GC'd) negotiation keys; move
                    # every participant to a fresh epoch namespace.
                    from horovod_tpu.common import negotiation
                    negotiation.bump_epoch()
                else:
                    # A platform site hook pre-created a distributed client
                    # that doesn't belong to our cluster — replace it.
                    hvd_logging.warning(
                        "replacing pre-existing jax.distributed client "
                        "(%s) with launcher coordinator %s", current, target)
                    jax.distributed.shutdown()
                    replace = True
            else:
                replace = True
            if replace:
                # Backends created before distributed bootstrap (site
                # hooks, or a previous smaller world during elastic
                # scale-up) would freeze a stale topology view; clear them
                # so they rebuild with the cluster's global topology.
                # Failures propagate: continuing with a stale backend is
                # the exact wedge this block exists to prevent.
                from jax._src import xla_bridge as _xb
                if _xb.backends_are_initialized():
                    hvd_logging.warning(
                        "clearing pre-initialized XLA backends before "
                        "distributed bootstrap")
                    _clear_backends_and_program_caches()
                if os.environ.get("HOROVOD_ELASTIC"):
                    # Elastic membership: a peer dying must surface as a
                    # recoverable collective error in survivors, not a
                    # process-fatal coordination abort, and failure
                    # detection should beat the default 100 s heartbeat
                    # (reference: NCCL comms marked elastic abort instead
                    # of hanging, nccl_operations.h:55). Version-dependent
                    # plumbing lives in _elastic_distributed_initialize.
                    hb = int(os.environ.get(
                        "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", "10"))
                    _elastic_distributed_initialize(
                        target, config.cross_size, config.cross_rank, hb)
                else:
                    jax.distributed.initialize(
                        coordinator_address=target,
                        num_processes=config.cross_size,
                        process_id=config.cross_rank)
                # Fresh coordination service: empty KV store, epoch 0 for
                # every participant (incl. replacement elastic workers).
                from horovod_tpu.common import negotiation
                negotiation.reset_epoch()

        # Persistent XLA compile cache BEFORE the first backend touch, so
        # every compile this job performs (including the eager collective
        # programs) is eligible: elastic re-rendezvous and repeat launches
        # then skip XLA recompiles entirely (see docs/performance.md).
        if config.compile_cache_dir:
            _setup_compile_cache(config.compile_cache_dir)

        topology = build_topology(devices)
        _state = _State(topology, config)

        from horovod_tpu.common import process_sets as ps
        ps._init_table(_state, process_sets)

        if config.timeline_filename:
            start_timeline(config.timeline_filename,  # hvdrace: disable=HVR202 -- one-shot native lib build at init, bounded by subprocess timeout=120 and cached by native._tried
                           mark_cycles=config.timeline_mark_cycles)

        # Metrics: arm the always-on registry with this job's knobs and
        # (optionally) the scrape endpoint. Offset by the LOCAL (per-host)
        # process rank only — same-host processes must not fight over one
        # bind, while every host keeps the same base port so a uniform
        # scrape config works across the fleet.
        from horovod_tpu import metrics as hvd_metrics
        hvd_metrics.set_enabled(config.metrics)
        hvd_metrics.set_prefix(config.metrics_prefix)
        if config.metrics and config.metrics_port:
            # Topology-derived, not config.local_rank: launchers that skip
            # the HOROVOD_LOCAL_RANK env (direct jax.distributed, SLURM)
            # would leave every same-host process at offset 0.
            local_rank_now = (
                topology.local_device_ranks[0] % topology.local_size
                if topology.local_device_ranks else 0)
            try:
                port = hvd_metrics.start_http_server(
                    config.metrics_port + local_rank_now,
                    addr=config.metrics_addr)
                hvd_logging.info("metrics scrape endpoint on :%d", port)
            except OSError as e:  # busy port must not kill training
                hvd_logging.warning("metrics endpoint failed to bind: %s", e)

        # Cluster telemetry plane: rank → slice-leader → job-view
        # aggregation over the launcher HTTP-KV (horovod_tpu/telemetry).
        # Armed after the topology is known (slice membership comes from
        # it); no-ops on single-process or KV-less runs, where
        # hvd.cluster_snapshot() serves the local-only view.
        try:
            from horovod_tpu.telemetry import aggregator as _telemetry
            _telemetry.start_from_config(config, topology)
        except Exception as e:  # noqa: BLE001 — telemetry must not block init
            hvd_logging.warning("telemetry plane failed to start: %s", e)

        # Durable run-history journal (HOROVOD_RUN_HISTORY_DIR): armed on
        # the coordinator rank once the world shape is known. Arm-once
        # like the goodput ledger — an elastic re-init keeps appending to
        # the same run's journal (a new coordinator after a rank-0 death
        # opens its own).
        try:
            from horovod_tpu.goodput import history as _run_history
            if _run_history.get_journal() is None:
                # config.cross_rank is the launcher-assigned process id
                # (0 on the coordinator / single-controller).
                _run_history.journal_configure(
                    config, rank=config.cross_rank, world=topology.size)
        except Exception as e:  # noqa: BLE001 — must not block init
            hvd_logging.warning("run-history journal failed to arm: %s", e)

        # Autopilot (HOROVOD_AUTOPILOT): the online controller closing the
        # signal plane → knobs loop, coordinator rank only (followers
        # adopt flips at flush boundaries). Armed AFTER telemetry so its
        # first frame can already read the health plane. An elastic
        # re-init restarts it under the new membership like the
        # telemetry agent.
        try:
            from horovod_tpu.autopilot import controller as _autopilot
            _autopilot.start_from_config(config)
        except Exception as e:  # noqa: BLE001 — must not block init
            hvd_logging.warning("autopilot failed to start: %s", e)

        hvd_logging.info(
            "horovod_tpu initialized: size=%d local_size=%d cross_size=%d",
            topology.size, topology.local_size, topology.cross_size)
        atexit.register(shutdown)


# True while a jax-0.4.x compat elastic client (no shutdown barrier on
# teardown — see _elastic_distributed_initialize) is connected.
_elastic_compat_client = False

# True when init's CPU-tier probe selected gloo collectives itself (vs a
# user-configured jax_cpu_collectives_implementation, which teardown must
# not clobber).
_gloo_selected_by_init = False

# Superseded compat coordination services and clients, kept alive on
# purpose: see teardown_distributed. Shutting a service down kills every
# still-connected peer, and DESTROYING a connected client races its own
# error-polling thread against the destructor — the poll fails with
# "Socket closed" and the hardwired fatal callback terminates the process.
# Neither object is ever destroyed mid-run; compat worker processes end
# with os._exit (runner/task.py) so interpreter finalization cannot run
# the destructors either.
_leaked_compat_services = []
_leaked_compat_clients = []

# Every coordinator port a compat membership has ever used in this process.
# Leaked clients keep live gRPC connections to leaked services on the OLD
# ports; the membership watchdog's data-plane abort (common/sockets.py) must
# treat those as control plane — severing one fires the leaked client's
# hardwired fatal callback.
_compat_coordinator_ports = set()


def compat_coordinator_ports():
    """All coordinator-service ports this process has connected to across
    compat elastic memberships (current + leaked)."""
    return set(_compat_coordinator_ports)


def elastic_compat_leaks():
    """True when this process holds leaked jax-0.4.x compat distributed
    objects whose destructors must never run (exit via os._exit)."""
    return bool(_leaked_compat_services or _leaked_compat_clients)


def _elastic_distributed_initialize(target, num_processes, process_id, hb):
    """Bootstrap ``jax.distributed`` with ELASTIC failure semantics: a peer
    dying must become a recoverable error in survivors — never a
    process-fatal abort.

    Newer jax exposes exactly that via ``jax_enable_recoverability`` +
    heartbeat/shutdown kwargs on the public ``initialize``. jax 0.4.x has
    neither, and its coordination-service defaults are all-process
    fate-sharing: the service declares a silent task dead after the
    heartbeat window and PROPAGATES a fatal error to every connected
    client, whose hardwired callback terminates the process (xla
    distributed client.h) — survivors never outlive a crash, and during a
    staggered elastic teardown even HEALTHY peers kill each other (the
    first client to drop out looks dead to the service, which then fatals
    the stragglers; a Python callback override is not viable — invoking
    it from the C++ polling thread dies in argument marshalling). So on
    0.4.x the compat bootstrap inverts the contract:

    - the service gets an effectively-infinite miss window — liveness is
      OUR elastic driver's job, and the data plane already surfaces peer
      death instantly (gloo connection reset → HorovodInternalError);
    - teardown never runs the client shutdown barrier (a dead peer can
      never join it; failing it propagates a job-fatal error) — the
      client reference is dropped instead, see teardown_distributed;
    - if the COORDINATOR process itself dies, surviving workers still hit
      the default fatal callback (~100 s heartbeat window) and the driver
      respawns the job from the last commit — elastic-by-restart instead
      of in-place, the documented old-jax degradation."""
    import inspect

    global _elastic_compat_client
    try:
        jax.config.update("jax_enable_recoverability", True)
    except AttributeError:
        pass                       # pre-recoverability jax: compat below
    accepted = inspect.signature(jax.distributed.initialize).parameters
    if "heartbeat_timeout_seconds" in accepted:
        jax.distributed.initialize(
            coordinator_address=target, num_processes=num_processes,
            process_id=process_id, heartbeat_timeout_seconds=hb,
            shutdown_timeout_seconds=hb)
        return
    from jax._src import distributed as _dist
    from jax._src.lib import xla_extension as _xe
    state = _dist.global_state
    if process_id == 0:
        if state.service is not None:
            raise RuntimeError("distributed service already running; "
                               "teardown_distributed() first")
        bind = "[::]:" + target.rsplit(":", 1)[1]
        state.service = _xe.get_distributed_runtime_service(
            bind, num_processes, heartbeat_interval=10,
            max_missing_heartbeats=1_000_000)
    if state.client is not None:
        raise RuntimeError("distributed client already connected; "
                           "teardown_distributed() first")
    # Bounded connect: if the new world cannot assemble (e.g. its
    # coordinator is itself wedged), the client's connect failure fires
    # the hardwired fatal callback and this process dies — better to
    # surface that within the recovery budget than after 300 s of limbo.
    # shutdown_timeout bounds ONLY the clean-finish shutdown barrier
    # (runner/task.py _orderly_distributed_exit); the failure-recovery
    # teardown never runs it. Generous: ranks reach the barrier staggered
    # by their result uploads, and a timeout here degrades a clean exit
    # into the abrupt-disconnect race.
    state.client = _xe.get_distributed_runtime_client(
        target, process_id, init_timeout=max(60, 10 * hb),
        shutdown_timeout=max(6 * hb, 30),
        shutdown_on_destruction=False, use_compression=True)
    state.client.connect()
    state.num_processes = num_processes
    state.process_id = process_id
    state.coordinator_address = target
    _elastic_compat_client = True
    _compat_coordinator_ports.add(int(target.rsplit(":", 1)[1]))
    hvd_logging.info("compat elastic client connected to %s (world %d)",
                     target, num_processes)


def _setup_compile_cache(path):
    """Arm JAX's persistent compilation cache at ``path``
    (``HOROVOD_COMPILE_CACHE_DIR``).

    The min-compile-time / min-entry-size gates are zeroed: the eager
    collective programs are individually cheap compiles, but an elastic
    restart pays ALL of them again back-to-back — exactly the latency this
    cache exists to remove. Cache-hit/request totals are mirrored into the
    metrics registry (``compile_cache_events_total``). Failures downgrade
    to a warning: a broken cache dir must not block training (delete a
    stale directory if hits stay at zero — docs/troubleshooting.md)."""
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax latches its "is the cache usable" decision at the first
        # compile; compiles before init() (user warmup, site hooks) would
        # have latched it off — reset so the new dir takes effect.
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
        from horovod_tpu import metrics as hvd_metrics
        hvd_metrics.install_compile_cache_listener()
        hvd_logging.info("persistent XLA compile cache at %s", path)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        hvd_logging.warning("compile cache setup failed (%s): %s", path, e)


def _clear_backends_and_program_caches():
    """Drop every XLA client AND every compiled-program cache that captures
    mesh/device objects, so everything rebuilds against the next backend.

    Must be the PUBLIC clear (``jax.extend.backend.clear_backends``) — the
    private ``xla_bridge._clear_backends`` leaves the ``get_backend``
    util.cache serving the old client, which keeps ``jax.devices()``
    returning a dead multi-process world after an elastic resize."""
    from jax.extend.backend import clear_backends
    clear_backends()
    from horovod_tpu.ops import collective_ops
    collective_ops.clear_program_caches()
    # The old CPU client must actually DIE here, not linger until an
    # arbitrary later GC: it owns the gloo contexts, and its destruction
    # is what closes their TCP connections — peers blocked on us in a
    # collective unblock on that close. Reference-cycle stragglers
    # (exception tracebacks through jit frames are the usual holders)
    # otherwise keep the sockets open indefinitely.
    import gc
    gc.collect()


def teardown_distributed():
    """Fully dissolve the jax.distributed cluster membership so backends
    rebuilt afterwards see a single-process world.

    ``jax.distributed.shutdown()`` alone resets the client/service but
    leaves ``num_processes``/``process_id`` behind, and the CPU/TPU client
    factories read those at backend creation — without this, a worker
    shrinking to world size 1 rebuilds a backend that still believes in its
    dead peers. Used by elastic in-place re-initialization
    (horovod_tpu/elastic/state.py _reset)."""
    global _elastic_compat_client
    if _elastic_compat_client:
        # jax-0.4.x elastic client: NEVER run the shutdown barrier — with
        # a dead peer it can only time out, and the coordination service
        # then propagates the failure as a job-fatal error to every peer
        # still connected (killing healthy survivors mid-recovery). But
        # the client must not be DESTROYED either: its destructor races
        # its own error-polling thread, which sees the dying channel as
        # "Socket closed" and fires the hardwired fatal callback. And
        # the OLD service (this process = old rank 0) must not be shut
        # down while peers' old clients still poll it — same callback,
        # every peer at once. So both are LEAKED alive: the old client
        # keeps polling the old (leaked, healthy) service until process
        # exit, which must be os._exit for compat workers
        # (runner/task.py) so finalization can't run the destructors.
        # Cost: a few threads + a port per membership change; the next
        # membership's service lives on a fresh driver-assigned port.
        try:
            from jax._src import distributed as _dist
            if _dist.global_state.client is not None:
                _leaked_compat_clients.append(_dist.global_state.client)
                _dist.global_state.client = None
            if _dist.global_state.service is not None:
                _leaked_compat_services.append(_dist.global_state.service)
                _dist.global_state.service = None
        except Exception:  # pragma: no cover
            pass
        _elastic_compat_client = False
    try:
        jax.distributed.shutdown()
    except Exception as e:  # old cluster half-dead: proceed with teardown
        hvd_logging.warning("jax.distributed shutdown: %s", e)
    try:
        from jax._src import distributed as _dist
        # A failed shutdown barrier (elastic: a DEAD peer can never join
        # it) raises out of State.shutdown BEFORE it nulls client/service;
        # the next initialize would then refuse with "should only be
        # called once". Finish the dismantling by hand — the compat client
        # is created with shutdown_on_destruction=False, so dropping the
        # reference cannot re-trigger the barrier.
        if _dist.global_state.client is not None:
            _dist.global_state.client = None
        if _dist.global_state.service is not None:
            try:
                _dist.global_state.service.shutdown()
            except Exception:  # noqa: BLE001 — stopping a dead service
                pass
            _dist.global_state.service = None
        _dist.global_state.preemption_sync_manager = None
        _dist.global_state.process_id = 0
        _dist.global_state.num_processes = 1
        _dist.global_state.coordinator_address = None
    except Exception as e:  # pragma: no cover
        hvd_logging.warning("distributed state reset: %s", e)
    # Undo the multi-process CPU gloo selection (jax 0.4.x): a backend
    # rebuilt at world size 1 has no distributed client, and the gloo
    # factory requires one — a worker shrinking to a single-process world
    # would otherwise fail its re-init inside make_cpu_client. Only undone
    # when init itself made the selection: a user-configured
    # JAX_CPU_COLLECTIVES_IMPLEMENTATION (e.g. with JAX_PLATFORMS unset,
    # which init's probe would not re-select on the next cycle) is theirs
    # to keep.
    global _gloo_selected_by_init
    if _gloo_selected_by_init:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "none")
        except AttributeError:
            pass
        _gloo_selected_by_init = False
    _clear_backends_and_program_caches()


def shutdown():
    """Finalize: flush pending fused collectives and the timeline
    (reference: horovod_shutdown, operations.cc:1006-1013)."""
    global _state
    with _lock:
        if _state is None or _state.shutdown_called:
            return
        _state.shutdown_called = True
        if _state.fusion is not None:
            try:
                _state.fusion.shutdown()
            except Exception as e:  # pragma: no cover
                hvd_logging.warning("flush on shutdown failed: %s", e)
        if _state.timeline is not None:
            # Final registry dump as Chrome-trace counter events, so the
            # written trace ends with the job's aggregate totals.
            try:
                from horovod_tpu import metrics as hvd_metrics
                hvd_metrics.emit_timeline_counters(_state.timeline)
            except Exception:  # noqa: BLE001 — telemetry must not block
                pass
            _state.timeline.close()
        from horovod_tpu import metrics as hvd_metrics
        hvd_metrics.stop_http_server()
        # Run-history journal: append the current cluster view + goodput
        # summary while the telemetry agent is still alive (the reader
        # takes the LAST of each kind, so mid-run elastic resets just
        # refresh the evidence; the final run_end marker comes from the
        # goodput atexit finalizer).
        try:
            from horovod_tpu.goodput import history as _run_history
            if _run_history.get_journal() is not None:
                from horovod_tpu.goodput import ledger as _goodput_ledger
                from horovod_tpu.telemetry import aggregator as _telemetry
                agent = _telemetry.get_agent()
                if agent is not None:
                    _run_history.journal_append(
                        "cluster", view=agent.cluster_snapshot())
                _run_history.journal_append(
                    "goodput", summary=_goodput_ledger.snapshot())
        except Exception:  # noqa: BLE001 — history must not block exit
            pass
        # Telemetry agent: stopped here, restarted by the next init (an
        # elastic re-init restarts it under the new membership generation
        # — rank numbering changes across memberships, so the old agent's
        # keys must not outlive it).
        try:
            from horovod_tpu.telemetry import aggregator as _telemetry
            _telemetry.stop()
        except Exception:  # noqa: BLE001 — telemetry must not block exit
            pass
        # Autopilot control thread: stopped with the runtime it steers
        # (an elastic re-init re-arms it under the new membership).
        try:
            from horovod_tpu.autopilot import controller as _autopilot
            _autopilot.stop()
        except Exception:  # noqa: BLE001 — must not block exit
            pass
        # Step profiler: discard the OPEN window and bump the record
        # epoch — an elastic reset's recovery traffic must not be
        # attributed to the first post-restore step, and reports must not
        # double-count across a rendezvous (completed records are kept).
        try:
            from horovod_tpu.profile import ledger as _profile_ledger
            _profile_ledger.reset_window()
            # A trace capture still open (step window never reached its
            # stop marker, mid-/debug/profile shutdown) must flush to
            # disk now — the session would otherwise leak past teardown.
            from horovod_tpu.profile import capture as _profile_capture
            _profile_capture.shutdown()
        except Exception:  # noqa: BLE001 — profiling must not block exit
            pass
        trace_dir = _state.config.trace_dir
        t = _state.topology
        trace_rank = t.local_device_ranks[0] if t.local_device_ranks \
            else 0
        from horovod_tpu.common import negotiation
        negotiation.reset()
        _state = None
    # Membership watchdog: terminate it on the way out (it joins a thread,
    # so — like the trace dump below — it runs after releasing the lock).
    try:
        from horovod_tpu.elastic import worker as _elastic_worker
        _elastic_worker.stop_collective_abort()
    except Exception:  # noqa: BLE001 — must not block exit
        pass
    # Trace shard: a configured HOROVOD_TRACE_DIR gets this process's
    # span store on the way out (trace_r<rank>.json, merged by
    # `python -m horovod_tpu.trace.analyze`) — written AFTER releasing
    # the state lock: a dump is file I/O and must not sit in the
    # critical section (the PR-5 signal-handler deadlock class).
    if trace_dir:
        try:
            import os as _os

            from horovod_tpu import trace as _trace
            _os.makedirs(trace_dir, exist_ok=True)
            _trace.dump(_os.path.join(trace_dir,
                                      f"trace_r{trace_rank}.json"),
                        rank=trace_rank)
        except Exception:  # noqa: BLE001 — must not block exit
            pass


def is_initialized():
    """reference: horovod_is_initialized (operations.cc:1027)."""
    return _state is not None


def _get_state():
    if _state is None:
        raise NotInitializedError()
    return _state


def topology():
    return _get_state().topology


def config():
    return _get_state().config


# --- rank / size queries (reference: operations.cc:1119-1229) ---

# Simulated-world overlay (horovod_tpu/analysis/program.py): while the
# static analyzer abstract-evals a step function "as rank r of n", the
# rank/size queries below answer from this overlay instead of the live
# topology — rank-conditional Python control flow then resolves per
# simulated rank with zero device execution. None = no simulation.
_sim_world = None


class _SimWorld:
    __slots__ = ("rank", "size", "local_rank", "local_size",
                 "cross_rank", "cross_size")

    def __init__(self, rank, size, local_size=None):
        self.rank = rank
        self.size = size
        self.local_size = size if local_size is None else local_size
        self.local_rank = rank % self.local_size
        self.cross_rank = rank // self.local_size
        self.cross_size = max(1, size // self.local_size)


def _set_sim_world(sim):
    """Install (or clear, with ``None``) the simulated world. Returns the
    previous overlay so nested simulations can restore it."""
    global _sim_world
    prev = _sim_world
    _sim_world = sim
    return prev


def size():
    if _sim_world is not None:
        return _sim_world.size
    return _get_state().topology.size


def local_size():
    if _sim_world is not None:
        return _sim_world.local_size
    return _get_state().topology.local_size


def cross_size():
    if _sim_world is not None:
        return _sim_world.cross_size
    return _get_state().topology.cross_size


def rank():
    if _sim_world is not None:
        return _sim_world.rank
    t = _get_state().topology
    return t.local_device_ranks[0] if t.local_device_ranks else 0


def local_rank():
    if _sim_world is not None:
        return _sim_world.local_rank
    t = _get_state().topology
    return rank() % t.local_size


def cross_rank():
    if _sim_world is not None:
        return _sim_world.cross_rank
    t = _get_state().topology
    return rank() // t.local_size


def process_index():
    return _get_state().topology.process_index


def process_count():
    return jax.process_count()


def is_homogeneous():
    """TPU slices are homogeneous by construction
    (reference: horovod_is_homogeneous, operations.cc:1233)."""
    _get_state()
    return True


# --- build-capability queries (reference: operations.cc:1307-1449).
# These exist so code written against the reference API keeps working; the
# honest answers for a TPU runtime are below.

def mpi_threads_supported():
    return False


def mpi_enabled():
    return False


def mpi_built():
    return False


def gloo_enabled():
    return False


def gloo_built():
    return False


def nccl_built():
    return 0


def ddl_built():
    return False


def ccl_built():
    return False


def cuda_built():
    return False


def rocm_built():
    return False


def xla_built():
    """The entire data plane is XLA on this framework."""
    return True


def ici_built():
    """TPU inter-chip-interconnect collectives available."""
    return True


# --- timeline control (reference: horovod_start_timeline, operations.cc:1079) ---

def start_timeline(file_path, mark_cycles=False):
    """Start (or restart) the Chrome-trace timeline.

    Multi-process: the COORDINATOR (process 0) writes ``file_path``, like
    the reference's rank-0 timeline writer (timeline.cc); every other
    process writes ``file_path.p<index>`` — same observability per host
    without processes clobbering one shared file."""
    st = _get_state()
    from horovod_tpu.timeline import Timeline
    if st.timeline is not None:
        st.timeline.close()
    if jax.process_count() > 1 and jax.process_index() != 0:
        file_path = f"{file_path}.p{jax.process_index()}"
    st.timeline = Timeline(file_path, mark_cycles=mark_cycles)
    return st.timeline


def stop_timeline():
    st = _get_state()
    if st.timeline is not None:
        st.timeline.close()
        st.timeline = None


def timeline():
    st = _state
    return st.timeline if st is not None else None


# --- metrics (horovod_tpu/metrics; no reference analog — the reference's
# observability stops at the timeline + stall inspector) ---

def metrics_snapshot():
    """JSON-able dict of every metrics series' current value (counters,
    gauges, histograms with cumulative buckets). Works before init too —
    the registry is process-global."""
    from horovod_tpu import metrics as hvd_metrics
    return hvd_metrics.snapshot()


def metrics_text():
    """The metrics registry in Prometheus text exposition format 0.0.4 —
    the same payload the ``HOROVOD_METRICS_PORT`` scrape endpoint serves."""
    from horovod_tpu import metrics as hvd_metrics
    return hvd_metrics.render_text()


def cluster_snapshot():
    """The job-level cluster view from the hierarchical telemetry plane
    (horovod_tpu/telemetry): per-rank health states
    (healthy/straggling/desynced/stalled/dead), per-slice digest counts
    and leader, job step progress, and the bounded state-transition event
    log — the same payload ``GET /cluster/health`` serves. Falls back to
    a local-only view on single-process or KV-less runs; never returns
    None. Works before init too (local fallback)."""
    from horovod_tpu.telemetry import aggregator as _telemetry
    return _telemetry.cluster_snapshot()
