"""Exception types for horovod_tpu.

Parity with reference horovod/common/exceptions.py (HorovodInternalError,
HorovodVersionMismatchError, HostsUpdatedInterrupt, get_version_mismatch_message).
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective routine fails.

    Elastic training recovers from this by restoring the last committed state
    (reference: horovod/common/exceptions.py:20-24).
    """


class HorovodVersionMismatchError(ImportError):
    """Raised when the installed framework version doesn't match the one the
    native extension was built against (reference: exceptions.py:27-37)."""

    def __init__(self, name, version, installed_version):
        super().__init__(get_version_mismatch_message(name, version, installed_version))
        self.name = name
        self.version = version
        self.installed_version = installed_version


def get_version_mismatch_message(name, version, installed_version):
    return (
        f'Framework {name} installed with version {installed_version} '
        f'but found version {version}.\n\n'
        f'This can result in unexpected behavior including runtime errors.\n'
        f'Reinstall Horovod-TPU using `pip install --no-cache-dir` to build '
        f'with the new version.'
    )


class HostsUpdatedInterrupt(RuntimeError):
    """Raised in elastic mode when the driver notifies workers that the host
    set changed. The training loop keeps its current (uncommitted) state and
    re-initializes collectives (reference: exceptions.py:40-50).
    """

    def __init__(self, skip_sync=False):
        super().__init__()
        self.skip_sync = skip_sync


class NotInitializedError(RuntimeError):
    """Raised when a horovod_tpu API is used before hvd.init()."""

    def __init__(self, what="Horovod-TPU"):
        super().__init__(
            f'{what} has not been initialized; run hvd.init() first.')


class ProcessSetError(ValueError):
    """Invalid process-set operation (unknown set, duplicate ranks, ...)."""


class TensorShapeMismatchError(ValueError):
    """Ranks submitted mismatched shapes/dtypes for one collective, the moral
    equivalent of the coordinator's error response
    (reference: horovod/common/controller.cc ConstructResponse error checks)."""
