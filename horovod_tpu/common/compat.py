"""JAX version compatibility shims.

The codebase (and its tests) target the modern ``jax.shard_map`` entry
point. On older jax (< 0.5, e.g. the 0.4.x line) the function only exists
as ``jax.experimental.shard_map.shard_map`` — same signature for the
keyword form used throughout (``mesh=``, ``in_specs=``, ``out_specs=``).
Alias it onto the ``jax`` module once, at package import, so every caller
(library, tests, user code importing ``horovod_tpu``) sees one surface.
"""

import jax
from jax import lax


def install():
    _shard_map = getattr(jax, "shard_map", None)
    if _shard_map is None:
        try:
            from jax.experimental.shard_map import shard_map as _shard_map
        except ImportError:  # pragma: no cover — no known jax lacks both
            _shard_map = None
    if _shard_map is not None:
        # Keyed on kwarg ACCEPTANCE, not existence: some versions expose
        # a top-level jax.shard_map that still spells the replication
        # check ``check_rep`` (the pre-rename window) — those need the
        # translation just as much as the experimental entry point.
        import functools
        import inspect

        try:
            params = inspect.signature(_shard_map).parameters
            takes_vma = "check_vma" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        except (TypeError, ValueError):  # C callable etc.: leave as-is
            takes_vma = True
        if not takes_vma:
            @functools.wraps(_shard_map)
            def shard_map(f, *args, **kwargs):
                kwargs["check_rep"] = kwargs.pop(
                    "check_vma", kwargs.pop("check_rep", True))
                return _shard_map(f, *args, **kwargs)

            jax.shard_map = shard_map
        elif not hasattr(jax, "shard_map"):
            jax.shard_map = _shard_map
    if not hasattr(lax, "axis_size"):
        # The canonical pre-0.5 idiom: psum of the literal 1 over a named
        # axis resolves statically to the axis size.
        def axis_size(axis_name):
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size
    if not hasattr(jax, "typeof"):
        # Pre-VMA jax: avals carry no ``vma`` set, which is exactly what
        # callers probing ``getattr(jax.typeof(x), "vma", ())`` expect.
        from jax.core import get_aval

        jax.typeof = get_aval
    if not hasattr(lax, "pcast"):
        # No varying-manual-axes type system on this jax — but 0.4.x
        # shard_map DOES run a replication checker (check_rep), and its
        # rules reject control flow whose branches/carries disagree on the
        # rep set: exactly what mark_varying exists to harmonize. The
        # modern pvary's moral ancestor is the internal pbroadcast
        # primitive (drops axes from a tracer's rep set); unlike pvary it
        # REFUSES values already varying over the axis, so the shim
        # applies it only when the tracer's rep actually contains the
        # axis — the idempotent pvary contract. Outside shard_map tracing
        # (or with check_rep off) tracers carry no rep and the identity
        # is semantically exact.
        def _drop_rep(x, axis_name):
            rep = getattr(x, "rep", None)
            if rep and axis_name in rep:
                try:
                    from jax.experimental.shard_map import pbroadcast
                except ImportError:
                    return x
                return pbroadcast(x, axis_name)
            return x

        def pcast(x, axis_name, to="varying"):
            if to != "varying":
                return x
            return _drop_rep(x, axis_name)

        lax.pcast = pcast
    if not hasattr(lax, "pvary"):
        def pvary(x, axis_name):
            return lax.pcast(x, axis_name, to="varying")

        lax.pvary = pvary


install()
