"""Leveled, rank-prefixed logging.

TPU-native equivalent of the reference's C++ logging (horovod/common/logging.cc:1-190):
glog-style levels selected by ``HOROVOD_LOG_LEVEL`` (trace/debug/info/warning/error/
fatal) and timestamp hiding via ``HOROVOD_LOG_HIDE_TIME``.
"""

import logging
import os
import sys

TRACE = 5
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_logger = None


def _rank_prefix():
    # Late import to avoid cycles; shows [rank] like LogMessage in logging.cc.
    try:
        from horovod_tpu.common import basics
        if basics.is_initialized():
            return f"[{basics.rank()}]"
    except Exception:
        pass
    return "[-]"


class _RankFilter(logging.Filter):
    def filter(self, record):
        record.hvd_rank = _rank_prefix()
        return True


def get_logger():
    global _logger
    if _logger is None:
        _logger = logging.getLogger("horovod_tpu")
        level = _LEVELS.get(
            os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(),
            logging.WARNING)
        _logger.setLevel(level)
        handler = logging.StreamHandler(sys.stderr)
        from horovod_tpu.common.config import _env_bool
        hide_time = _env_bool("HOROVOD_LOG_HIDE_TIME", False)
        fmt = "%(hvd_rank)s %(levelname)s %(message)s" if hide_time else \
            "%(asctime)s %(hvd_rank)s %(levelname)s %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
        handler.addFilter(_RankFilter())
        _logger.addHandler(handler)
        _logger.propagate = False
    return _logger


def log(level, msg, *args):
    get_logger().log(_LEVELS.get(level, logging.INFO), msg, *args)


def trace(msg, *args):
    get_logger().log(TRACE, msg, *args)


def debug(msg, *args):
    get_logger().debug(msg, *args)


def info(msg, *args):
    get_logger().info(msg, *args)


def warning(msg, *args):
    get_logger().warning(msg, *args)


def error(msg, *args):
    get_logger().error(msg, *args)
