"""Shared helpers mirrored from the reference's ``horovod/common/util.py``.

The reference uses these for extension-loading checks (``check_extension``,
util.py:87-104), list chunking for explicit allreduce grouping
(``split_list``), and capability queries (``gpu_available``). On TPU there is
no per-framework compiled extension — the data plane is XLA — so the checks
degenerate to honest constant answers, kept so reference code ports without
edits.
"""


def check_extension(ext_name, ext_env_var=None, pkg_path=None, *args):
    """The reference verifies the framework's C++ extension was compiled
    (reference: common/util.py:87-104). The TPU build has a single native
    runtime shared by every frontend, loaded lazily — nothing to check.
    Kept for source compatibility; returns None like the reference.
    """
    return None


def check_installed_version(name=None, version=None, exception=None):
    """reference: common/util.py:107-121 — warns when the installed horovod
    version differs from the one the extension was built against. The TPU
    build is a single wheel; versions cannot diverge."""
    return None


def gpu_available(ext_base_name=None, verbose=False):
    """reference: common/util.py:124-137 (asks the extension whether it was
    built with CUDA/ROCm). This build targets TPU: no GPU operations."""
    return False


def split_list(items, num_parts):
    """Split ``items`` into exactly ``num_parts`` contiguous chunks whose
    sizes differ by at most one — trailing chunks may be empty (reference:
    common/util.py:244-249, used by the ``groups=N`` explicit-grouping path
    of DistributedOptimizer)."""
    if num_parts <= 0:
        raise ValueError(f"num_parts must be positive, got {num_parts}")
    d, r = divmod(len(items), num_parts)
    return [items[i * d + min(i, r):(i + 1) * d + min(i + 1, r)]
            for i in range(num_parts)]


def num_rank_is_power_2(num_rank):
    """True when ``num_rank`` is a power of two (reference:
    common/util.py:151-160; Adasum's recursive halving-doubling needs it)."""
    return num_rank != 0 and (num_rank & (num_rank - 1)) == 0
