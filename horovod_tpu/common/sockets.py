"""Data-plane socket abort: the compat-tier collective abort lever.

Reference semantics: on a membership change Horovod ABORTS in-flight gloo
collectives on every worker (the WorkerNotificationService push flips the
shutdown flag and the gloo context's pairs are closed, making blocked
send/recv calls raise instead of waiting out their timeout). jaxlib 0.4.x
exposes no abort on its gloo CPU collectives — ``make_gloo_tcp_collectives``
takes no timeout and XLA's collective thunks wait ~30 minutes — so a worker
blocked in an allreduce against a dead peer it is not directly connected to
would outlive every recovery deadline (only the dead rank's ring NEIGHBORS
see a connection reset; everyone else blocks on live-but-equally-stuck
peers).

This module implements abort at the file-descriptor level: ``shutdown(2)``
every ESTABLISHED TCP socket of this process except the control-plane
connections the recovery path still needs (coordination service, runner KV
store, metrics server). A shutdown makes the kernel send FIN/RST, so the
peer's blocked gloo read fails immediately AND this process's own blocked
collective errors out — surfacing as the ``HorovodInternalError`` the
elastic recovery loop already handles.

Safety: fds are never closed here — each is dup'd, the dup is wrapped,
``shutdown`` (which acts on the shared socket, not the descriptor) is
issued, and only the dup is closed. The C++ owner's later ``close`` of the
original fd therefore cannot double-close a recycled descriptor.
"""

import os
import socket

from horovod_tpu.common import logging as hvd_logging

_TCP_ESTABLISHED = "01"


def _established_inodes():
    """socket-inode -> (local_port, remote_port) for this netns's
    ESTABLISHED TCP connections (/proc/net/tcp + tcp6)."""
    out = {}
    for name in ("tcp", "tcp6"):
        try:
            with open(f"/proc/net/{name}") as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        for line in lines:
            parts = line.split()
            if len(parts) < 10 or parts[3] != _TCP_ESTABLISHED:
                continue
            try:
                local_port = int(parts[1].rsplit(":", 1)[1], 16)
                remote_port = int(parts[2].rsplit(":", 1)[1], 16)
                inode = int(parts[9])
            except (ValueError, IndexError):
                continue
            out[inode] = (local_port, remote_port)
    return out


def _socket_fds():
    """fd -> socket inode for this process."""
    out = {}
    try:
        fds = os.listdir("/proc/self/fd")
    except OSError:
        return out
    for fd in fds:
        try:
            target = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue
        if target.startswith("socket:["):
            out[int(fd)] = int(target[8:-1])
    return out


def abort_data_plane_sockets(exclude_ports=()):
    """Shut down every ESTABLISHED TCP socket of this process whose local
    AND remote port are both outside ``exclude_ports``. Returns the number
    of connections aborted.

    Callers exclude the control-plane ports (coordination service, KV
    store, metrics) so only data-plane (gloo) connections — which cannot
    be told apart by port, both ends being ephemeral — are severed.
    LISTEN sockets are never touched (not ESTABLISHED), so servers keep
    accepting after an abort."""
    exclude = {int(p) for p in exclude_ports if p}
    inodes = _established_inodes()
    aborted = 0
    for fd, inode in _socket_fds().items():
        ports = inodes.get(inode)
        if ports is None or any(p in exclude for p in ports):
            continue
        try:
            dup = os.dup(fd)
        except OSError:
            continue
        try:
            s = socket.socket(fileno=dup)
        except OSError:
            os.close(dup)
            continue
        try:
            s.shutdown(socket.SHUT_RDWR)
            aborted += 1
        except OSError:
            pass
        finally:
            s.close()  # closes only the dup; the original fd stays valid
    if aborted:
        hvd_logging.warning(
            "aborted %d in-flight data-plane connection(s)", aborted)
    return aborted


def control_plane_ports():
    """The ports the elastic recovery path still needs after an abort:
    coordination service, runner KV store, the metrics endpoint — plus
    any application connections the user declared off-limits via
    ``HOROVOD_ABORT_EXCLUDE_PORTS`` (comma-separated local-or-remote
    ports: data loaders, object stores, anything whose severed
    connection would surface as an error the elastic recovery loop does
    not handle)."""
    ports = set()
    for env in ("HOROVOD_COORDINATOR_PORT", "HOROVOD_KV_PORT"):
        v = os.environ.get(env)
        if v and v.isdigit():
            ports.add(int(v))
    for tok in os.environ.get("HOROVOD_ABORT_EXCLUDE_PORTS", "").split(","):
        tok = tok.strip()
        if tok.isdigit():
            ports.add(int(tok))
    try:
        # Historic compat coordinator ports: leaked jax-0.4.x clients hold
        # live connections to leaked services on the ports of SUPERSEDED
        # memberships — severing one fires its fatal callback.
        from horovod_tpu.common import basics
        ports.update(basics.compat_coordinator_ports())
    except Exception:  # noqa: BLE001 — never block the abort
        pass
    try:
        from horovod_tpu.metrics import server as _srv
        p = _srv.http_server_port()
        if p:
            ports.add(p)
    except Exception:  # noqa: BLE001 — metrics absence must not block abort
        pass
    return ports
