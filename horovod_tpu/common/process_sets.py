"""Process sets: collectives over subsets of ranks.

Reference semantics (horovod/common/process_set.h:26-180 + horovod/common/
process_sets.py:18-190): a process set is an ordered subset of global ranks with
its own communicator; ops carry a ``process_set_id``; sets can be added/removed
dynamically when ``HOROVOD_DYNAMIC_PROCESS_SETS`` is on.

TPU-native mapping: a process set is a device sub-mesh (eager path,
:func:`horovod_tpu.common.topology.build_submesh`) or an
``axis_index_groups`` partition for in-jit collectives — XLA's native notion of
rank subgroups, so no extra communicator state is needed inside jit.
"""

import threading

from horovod_tpu.common import basics
from horovod_tpu.common.exceptions import ProcessSetError
from horovod_tpu.common.topology import build_submesh

_lock = threading.RLock()


class ProcessSet:
    """An ordered subset of Horovod ranks (reference: process_sets.py:18-77).

    Instantiate with a list of ranks and register via ``hvd.init(process_sets=...)``
    or ``hvd.add_process_set``.
    """

    def __init__(self, ranks_or_comm=None):
        if ranks_or_comm is not None:
            ranks_or_comm = list(ranks_or_comm)
        self.ranks = ranks_or_comm  # None = global set
        self.process_set_id = None
        self._mesh = None
        # Per-set joined accounting for the armed multi-process JOIN
        # protocol (reference: joined_size lives on each ProcessSet,
        # controller.cc:269-327); global ranks. The GLOBAL set's protocol
        # tracks its state in basics state (st.joined_ranks) instead.
        self.joined_ranks = set()

    def _invalidate(self):
        self._mesh = None

    @property
    def mesh(self):
        """1-D mesh over this set's devices (lazy)."""
        if self._mesh is None:
            topo = basics.topology()
            ranks = self.ranks if self.ranks is not None \
                else list(range(topo.size))
            self._mesh = build_submesh(topo, ranks)
        return self._mesh

    def size(self):
        if self.process_set_id is None:
            raise ProcessSetError("Process set is not yet registered.")
        if self.ranks is None:
            return basics.size()
        return len(self.ranks)

    def rank(self):
        """This process's rank within the set, or -1 if not included
        (reference: process_sets.py:60-70)."""
        if self.process_set_id is None:
            raise ProcessSetError("Process set is not yet registered.")
        my = basics.rank()
        ranks = self.ranks if self.ranks is not None \
            else list(range(basics.size()))
        try:
            return ranks.index(my)
        except ValueError:
            return -1

    def included(self):
        return self.rank() >= 0

    def rank_list(self):
        if self.ranks is None:
            return list(range(basics.size()))
        return list(self.ranks)

    def __eq__(self, other):
        return isinstance(other, ProcessSet) and self.ranks == other.ranks

    def __hash__(self):
        return hash(tuple(self.ranks) if self.ranks is not None else None)

    def __str__(self):
        return f"ProcessSet(process_set_id={self.process_set_id}, ranks={self.ranks})"


global_process_set = ProcessSet(None)
global_process_set.process_set_id = 0


class _ProcessSetTable:
    """reference: ProcessSetTable (process_set.h:89-180)."""

    def __init__(self):
        self.by_id = {0: global_process_set}
        self.next_id = 1

    def register(self, ps):
        for existing in self.by_id.values():
            if existing == ps:
                ps.process_set_id = existing.process_set_id
                return existing
        topo_size = basics.size()
        if ps.ranks is not None:
            if len(set(ps.ranks)) != len(ps.ranks):
                raise ProcessSetError(f"Duplicate ranks in process set: {ps.ranks}")
            if any(r < 0 or r >= topo_size for r in ps.ranks):
                raise ProcessSetError(
                    f"Process set ranks out of range [0,{topo_size}): {ps.ranks}")
        ps.process_set_id = self.next_id
        self.next_id += 1
        self.by_id[ps.process_set_id] = ps
        return ps

    def remove(self, ps):
        if ps.process_set_id in (None, 0):
            raise ProcessSetError("Cannot remove the global process set.")
        del self.by_id[ps.process_set_id]
        ps.process_set_id = None
        ps._invalidate()


def _init_table(state, process_sets):
    table = _ProcessSetTable()
    state.process_set_table = table
    global_process_set._invalidate()
    if process_sets:
        for ps in process_sets:
            table.register(ps)


def _table():
    table = basics._get_state().process_set_table
    if table is None:
        raise ProcessSetError("Process set table missing (init not complete).")
    return table


def add_process_set(process_set):
    """Register a new process set at runtime
    (reference: process_sets.py:101-133, requires HOROVOD_DYNAMIC_PROCESS_SETS).

    Unlike the reference we don't require the dynamic knob: set creation is a
    host-side-only operation on TPU (sub-meshes are free), so it's always on.
    """
    with _lock:
        if not isinstance(process_set, ProcessSet):
            process_set = ProcessSet(process_set)
        return _table().register(process_set)


def remove_process_set(process_set):
    """reference: process_sets.py:136-155."""
    with _lock:
        _table().remove(process_set)


def process_set_by_id(process_set_id):
    try:
        return _table().by_id[process_set_id]
    except KeyError:
        raise ProcessSetError(f"Unknown process_set_id {process_set_id}")


def process_sets():
    """id -> ProcessSet mapping (reference: process_sets.py:80-98)."""
    return dict(_table().by_id)


def number_of_process_sets():
    """reference: basics.py _number_of_process_sets (common/elastic.py:22)."""
    return len(_table().by_id)


def is_process_set_included(process_set_id):
    """True when this process participates in the given set (reference:
    basics.py:467 _is_process_set_included). With several chips per process
    (SPMD dispatch, docs/api.md rank semantics) the process is included iff
    any chip-rank it owns is a member."""
    ps = process_set_by_id(process_set_id)
    from horovod_tpu.common import basics
    local = basics._get_state().topology.local_device_ranks
    member = set(ps.ranks if ps.ranks is not None
                 else range(basics.size()))
    return any(r in member for r in local)
