"""Host-side metadata negotiation for dynamic-shape collectives.

The reference's controller performs a control-plane exchange before any
dynamic-shape collective: allgather first-dims are gathered across ranks
(reference: horovod/common/controller.cc:74 ComputeResponseList tensor-shape
exchange) and alltoall splits are shared so every rank can size its receive
buffers (reference: horovod/common/ops/collective_operations.h:199-268).

TPU-native replacement: shapes are static *inside* XLA programs, so the only
thing that must cross process boundaries is the tiny per-rank size metadata
needed to build the padded program. That exchange rides the
``jax.distributed`` coordination service's key-value store — the same
control plane that bootstrapped the cluster — with a per-tag sequence number
so repeated calls never collide. Single-process setups short-circuit to a
local echo.

SPMD contract (same as the reference's enqueue contract): every process must
call the same negotiations in the same order.
"""

import json
import threading
import time

import jax

from horovod_tpu import trace as _trace
from horovod_tpu.chaos import injector as _chaos
from horovod_tpu.flight import recorder as _flight
from horovod_tpu.metrics import instruments as _metrics
from horovod_tpu.profile import ledger as _profile

_counters = {}
_lock = threading.Lock()
# Control-plane traffic accounting (this process's view). The scaling
# contract the reference's coordinator also satisfies (reference:
# controller.cc:74 — ONE ComputeResponseList negotiation per ready batch,
# regardless of world size): *rounds* per collective are O(1) in world
# size, and per-rank payloads stay small.  tests/test_multiproc.py's
# control-plane guard asserts both by comparing snapshots across worlds.
# The fusion runtime's boundary publish/consume path rides the raw
# coordination client (ops/fusion.py), so it reports here through
# record_fusion_kv() — otherwise the guard would be blind to the async
# path's KV traffic.
_stats = {"rounds": 0, "gets": 0, "payload_bytes": 0,
          # Per-tier breakdown of the hierarchical control plane
          # (HOROVOD_CONTROL_PLANE=hier / auto on a multi-slice layout):
          # rounds that decomposed, the leader's slice-local member
          # reads, the leaders-only cross-slice (DCN) reads, and the
          # members' O(1) fan-back reads. gets == gets_local + gets_cross
          # + gets_fanback for hier rounds, world-1 for flat rounds.
          "hier_rounds": 0, "gets_local": 0, "gets_cross": 0,
          "gets_fanback": 0,
          "fusion_sets": 0, "fusion_gets": 0, "fusion_payload_bytes": 0,
          # Fusion boundary reads by tier: root = the coordinator's
          # store key (leaders + flat followers), slice = the leader
          # re-publish keys (members). The hierarchy guard asserts a
          # member's root gets stay ZERO.
          "fusion_root_gets": 0, "fusion_slice_gets": 0}


def stats_snapshot():
    """Copy of this process's cumulative KV-traffic counters: ``rounds``
    (exchange() calls that hit the KV store — one set each), ``gets``
    (peer reads issued; world-1 per flat round, per-tier sums for
    hierarchical rounds — see ``hier_rounds``/``gets_local``/
    ``gets_cross``/``gets_fanback``), ``payload_bytes`` (serialized
    local payload), and the fusion runtime's boundary traffic
    (``fusion_sets``/``fusion_gets``/``fusion_payload_bytes`` plus the
    ``fusion_root_gets``/``fusion_slice_gets`` tier split)."""
    with _lock:
        return dict(_stats)


def stats_reset():
    with _lock:
        for k in _stats:
            _stats[k] = 0


def record_fusion_kv(sets=0, gets=0, payload_bytes=0, tier=None):
    """Report a fusion-runtime boundary KV operation (ops/fusion.py) into
    the shared traffic counters AND the metrics registry
    (``fusion_kv_rpcs_total`` / ``control_plane_rpcs_total``) — the
    hot-poll class of regression is a visible counter, not a code-review
    catch. ``tier`` ("root"/"slice") additionally books gets against the
    hierarchical boundary stream's per-tier counters."""
    with _lock:
        _stats["fusion_sets"] += sets
        _stats["fusion_gets"] += gets
        _stats["fusion_payload_bytes"] += payload_bytes
        if tier == "root":
            _stats["fusion_root_gets"] += gets
        elif tier == "slice":
            _stats["fusion_slice_gets"] += gets
    _metrics.record_fusion_kv(sets=sets, gets=gets,
                              payload_bytes=payload_bytes)
# Epoch namespace for the KV keys: bumped when an init REUSES a live
# coordination service (its store may still hold the last two undeleted
# keys per tag from the previous incarnation, see the lag-2 GC in
# exchange()); reset to 0 when a fresh service is bootstrapped. All
# processes follow the same init/shutdown sequence (SPMD contract), so
# epochs stay in lockstep.
_epoch = 0

# Timeout for peers to publish their metadata. Generous: a peer may be
# compiling its previous program.
_TIMEOUT_MS = 120_000


def _client():
    from jax._src import distributed
    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "negotiation requires the jax.distributed coordination service; "
            "was hvd.init() called with a multi-process launcher env?")
    return client


def _next_seq(key):
    with _lock:
        seq = _counters.get(key, 0)
        _counters[key] = seq + 1
    return seq


def reset():
    """Forget per-tag sequence numbers. Called by ``basics.shutdown()`` so an
    elastic re-init (which re-rendezvouses against a fresh coordination
    service) starts every participant back at sequence zero — survivors and
    replacement workers must agree on the key space."""
    with _lock:
        _counters.clear()


def bump_epoch():
    """Move to a fresh key namespace: the coordination service is being
    reused across ``hvd.init()`` incarnations and may still hold the
    previous incarnation's keys."""
    global _epoch
    with _lock:
        _epoch += 1


def reset_epoch():
    """Fresh coordination service bootstrapped: every participant (including
    replacement elastic workers that never saw earlier epochs) starts at
    epoch 0 against an empty store."""
    global _epoch
    with _lock:
        _epoch = 0


def exchange(tag, payload, procs=None):
    """Exchange a small JSON-serializable ``payload`` across processes.

    ``procs``: sorted process indices participating (a process set's owners,
    see ``collective_ops._mesh_processes``); defaults to every process.
    Returns the list of payloads ordered by participant. Every participant
    must call with the same ``tag`` in the same order (SPMD contract);
    non-participants must not call at all — scoping the exchange to the
    set's owners keeps them out of the rendezvous entirely.

    When a slice hierarchy exists (``HOROVOD_CONTROL_PLANE`` hier/auto,
    multi-slice layout), the round decomposes into a slice-local gather,
    ONE leaders-only cross-slice round, and a leader->member fan-back
    (``common/control_plane.py``): members issue O(1) blocking gets,
    leaders O(slice_size + num_slices) — never O(world). The returned
    payload list is bit-identical to the flat path's. The strategy is
    resolved per call from the (propagated) env + layout, so every
    participant decomposes — or doesn't — identically.
    """
    if procs is None:
        procs = list(range(jax.process_count()))
    if len(procs) <= 1:
        return [payload]
    me = jax.process_index()
    if me not in procs:
        raise RuntimeError(
            f"process {me} is not a participant of negotiation '{tag}' "
            f"(participants: {procs})")
    from horovod_tpu.common import control_plane as _cp
    proc_tag = ",".join(str(p) for p in procs)
    seq = _next_seq((tag, proc_tag))
    # Step-profiler bracket: the whole round — publish + blocking peer
    # reads — is control-plane time in the step attribution.
    t_cp = time.perf_counter() if _profile.armed else None
    t_round = time.time()
    client = _client()
    if _chaos.armed:
        # Chaos site: a delay here stalls this rank's publish, making every
        # peer's blocking get wait — the control-plane straggler mode.
        _chaos.fire("negotiation.exchange")
    base = f"hvd/neg/e{_epoch}/{tag}/{proc_tag}/{seq}"
    blob = json.dumps(payload)
    groups = _cp.exchange_groups(procs)
    if _flight.armed:
        # Negotiation rounds are SPMD-ordered like collectives, so a rank
        # wedged INSIDE an exchange shows as the last event before the gap.
        _flight.record_event("negotiation", name=tag, seq=seq,
                             nbytes=len(blob))
    kv = _cp.CoordKV(client)
    if groups is None:
        got, counters = _cp.flat_exchange(kv, me, procs, base, blob,
                                          _TIMEOUT_MS)
        out = [payload if p == me else json.loads(got[p]) for p in procs]
    else:
        out, counters = _cp.hier_exchange(kv, me, procs, base, blob,
                                          groups, _TIMEOUT_MS)
    with _lock:
        _stats["rounds"] += 1
        _stats["gets"] += counters["gets"]
        _stats["payload_bytes"] += len(blob)
        if groups is not None:
            _stats["hier_rounds"] += 1
            _stats["gets_local"] += counters["gets_local"]
            _stats["gets_cross"] += counters["gets_cross"]
            _stats["gets_fanback"] += counters["gets_fanback"]
    _metrics.record_negotiation(
        gets=counters["attempts"], payload_bytes=len(blob),
        sets=counters["sets"],
        tier_gets={"local": counters["gets_local"],
                   "cross": counters["gets_cross"],
                   "fanback": counters["gets_fanback"]}
        if groups is not None else None)
    # Bound coordinator memory on long jobs: reaching seq s implies this
    # process completed exchange s-1, which required reading every peer's
    # s-1 key (or, hierarchically, the covering aggregate/fan-back) — so
    # every peer had *started* s-1 and therefore finished s-2. Nobody can
    # still read an s-2 key: delete our own (and, as a leader, the
    # aggregate/fan-back blobs we published that round).
    if seq >= 2:
        _cp.gc_exchange_keys(
            kv, me, f"hvd/neg/e{_epoch}/{tag}/{proc_tag}/{seq - 2}",
            groups)
    if t_cp is not None:
        _profile.record_control_plane(time.perf_counter() - t_cp)
    # Negotiation-round span under the active step trace: the whole
    # publish + blocking-peer-read round, tagged so a slow round in the
    # merged Perfetto view names its exchange.
    _trace.add_span(_trace.get_active(), "negotiation", t_round,
                    time.time() - t_round, cat="train",
                    args={"tag": tag, "seq": seq})
    return out


def exchange_sizes(tag, local_sizes, procs=None):
    """Exchange per-rank integer size vectors; returns a flat list ordered by
    global rank (process-major, matching the rank-major device order of
    :func:`horovod_tpu.common.topology.build_topology`)."""
    per_proc = exchange(tag, [int(s) for s in local_sizes], procs=procs)
    flat = []
    for sizes in per_proc:
        flat.extend(int(s) for s in sizes)
    return flat
