"""Local gradient aggregation for the TF frontend.

Reference: horovod/tensorflow/gradient_aggregation.py
(LocalGradientAggregationHelper:23 — accumulate gradients in tf.Variables for
``backward_passes_per_step`` passes, allreduce once per flush, and gate the
optimizer's apply on the flush step) and gradient_aggregation_eager.py.

TF2 redesign: one implementation serves eager and ``tf.function`` — the
counter and accumulators are ``tf.Variable``s and the flush decision is a
``tf.cond``, so the whole step stays graph-compatible (the collective inside
the cond rides the frontend's host-callback op). The legacy TF1
variable-scope/LOCAL_VARIABLES plumbing is dropped.
"""


class LocalGradientAggregationHelper:
    _OPTIMIZER_TYPE_KERAS = "optimizer_type_keras"
    _OPTIMIZER_TYPE_LEGACY = "optimizer_type_legacy"

    def __init__(self, backward_passes_per_step, allreduce_func,
                 sparse_as_dense=False, average_aggregated_gradients=False,
                 rank=0, optimizer_type=_OPTIMIZER_TYPE_LEGACY,
                 process_set=None, scale_local_gradients=True):
        if backward_passes_per_step <= 0:
            raise ValueError("backward_passes_per_step must be > 0")
        self.backward_passes_per_step = backward_passes_per_step
        self._allreduce_grads = allreduce_func
        self.sparse_as_dense = sparse_as_dense
        self.average_aggregated_gradients = average_aggregated_gradients
        self.rank = rank
        self.optimizer_type = optimizer_type
        self.process_set = process_set
        self.scale_local_gradients = scale_local_gradients
        self.counter = None
        self.locally_aggregated_grads = []
        self._local_vars = set()
        self._flush_pred = None

    def register_local_var(self, var):
        """Mark ``var`` worker-local: its gradient is never allreduced
        (reference: gradient_aggregation.py:81-88)."""
        from horovod_tpu.tensorflow import var_key
        self._local_vars.add(var_key(var))

    def _densify(self, grad):
        import tensorflow as tf
        if isinstance(grad, tf.IndexedSlices):
            if not self.sparse_as_dense:
                raise ValueError(
                    "IndexedSlices are not supported with "
                    "backward_passes_per_step > 1 unless sparse_as_dense")
            return tf.convert_to_tensor(grad)
        return grad

    def _init_vars(self, grads):
        import tensorflow as tf
        if self.counter is None:
            self.counter = tf.Variable(0, dtype=tf.int32, trainable=False,
                                       name=f"hvd_agg_counter_{self.rank}")
            self.locally_aggregated_grads = [None] * len(grads)
        # Lazy per-slot accumulators: a variable whose gradient first
        # appears on a LATER pass (conditionally-active branch) still gets
        # one — fixing it to None on the first call would silently stop
        # that variable from ever training. Creation happens under
        # init_scope so a tf.function RETRACE (the very case where grad
        # structure changes) may create it too — plain creation inside a
        # non-first trace is forbidden by tf.function.
        for i, g in enumerate(grads):
            if g is not None and self.locally_aggregated_grads[i] is None:
                if g.shape.is_fully_defined():
                    with tf.init_scope():
                        self.locally_aggregated_grads[i] = tf.Variable(
                            tf.zeros(g.shape, g.dtype), trainable=False,
                            name=f"hvd_agg_grad_{self.rank}_{i}")
                else:
                    self.locally_aggregated_grads[i] = tf.Variable(
                        tf.zeros_like(g), trainable=False,
                        name=f"hvd_agg_grad_{self.rank}_{i}")

    def compute_gradients(self, grads, vars=None):
        """Accumulate ``grads``; on every ``backward_passes_per_step``-th
        call return the allreduced aggregate (optionally averaged over the
        passes), otherwise zeros (apply is gated off those steps anyway) —
        reference: gradient_aggregation.py:150-240."""
        import tensorflow as tf
        grads = [self._densify(g) for g in grads]
        self._init_vars(grads)
        vars = list(vars) if vars is not None else [None] * len(grads)

        # Collect the assign ops and gate everything downstream on them:
        # in a TF1 Session graph an un-depended-on assign_add never runs
        # (eager executes immediately; tf.function auto-chains stateful
        # ops; only the legacy graph path needs the explicit edge).
        updates = [acc.assign_add(g)
                   for acc, g in zip(self.locally_aggregated_grads, grads)
                   if acc is not None and g is not None]
        updates.append(self.counter.assign_add(1))

        def _flush():
            scale = (1.0 / self.backward_passes_per_step
                     if self.average_aggregated_gradients else 1.0)
            dense = [None if a is None else a * scale
                     for a in self.locally_aggregated_grads]
            from horovod_tpu.tensorflow import var_key
            reduce_idx = [i for i, (d, v) in enumerate(zip(dense, vars))
                          if d is not None
                          and (v is None
                               or var_key(v) not in self._local_vars)]
            reduced = self._allreduce_grads(
                [dense[i] for i in reduce_idx],
                [vars[i] for i in reduce_idx])
            out = list(dense)
            for i, r in zip(reduce_idx, reduced):
                out[i] = r
            if self._local_vars and self.scale_local_gradients:
                # Same down-scaling the bpps==1 path applies to local vars
                # (reference rationale: pull/3695).
                from horovod_tpu.ops.collective_ops import global_process_set
                ps = (self.process_set if self.process_set is not None
                      else global_process_set)
                n = ps.size()
                for i, v in enumerate(vars):
                    if v is not None \
                            and var_key(v) in self._local_vars \
                            and out[i] is not None:
                        out[i] = out[i] / n
            return [tf.zeros_like(g) if o is None else o
                    for o, g in zip(out, grads) if g is not None]

        def _hold():
            return [tf.zeros_like(g) for g in grads if g is not None]

        with tf.control_dependencies(updates):
            pred = tf.equal(self.counter % self.backward_passes_per_step, 0)
            # Stashed for apply_gradients: reading the counter fresh there
            # would not be ordered after the updates in a legacy graph.
            self._flush_pred = pred
            flushed = tf.cond(pred, _flush, _hold)
        it = iter(flushed)
        return [None if g is None else next(it) for g in grads]

    def apply_gradients(self, apply_grads_closure, optimizer, *args,
                        **kwargs):
        """Run the optimizer's apply only on flush steps, then zero the
        accumulators (reference: gradient_aggregation.py:242-303)."""
        import tensorflow as tf

        def _apply():
            op = apply_grads_closure()

            def _clear():
                for acc in self.locally_aggregated_grads:
                    if acc is not None:
                        acc.assign(tf.zeros_like(acc))
                return tf.constant(True)

            if op is None:
                return _clear()
            with tf.control_dependencies([op] if tf.is_tensor(op) else []):
                return _clear()

        pred = (self._flush_pred if self._flush_pred is not None
                else tf.equal(self.counter % self.backward_passes_per_step,
                              0))
        return tf.cond(pred, _apply, lambda: tf.constant(False))
