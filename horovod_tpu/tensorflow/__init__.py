"""TensorFlow frontend — the ``horovod.tensorflow`` API surface.

Reference: horovod/tensorflow/__init__.py (allreduce & friends :58-170,
DistributedGradientTape :957-1110, broadcast_variables), mpi_ops.py.

Design: like the torch frontend, TF here is a host-side frontend over the XLA
eager runtime — a tf.Tensor is bridged via numpy (TF already yields ml_dtypes
bfloat16 arrays, so the bf16 wire path is zero-copy in dtype terms), rides the
host's mesh slices, and the chip-axis collective equals the cross-host
collective. Inside ``tf.function``/graphs every collective rides a
``tf.numpy_function`` host-callback op (see :func:`_graph_op`) — the moral
equivalent of the reference's AsyncOpKernel registrations (reference:
tensorflow/mpi_ops.cc:443-1656) without a C++ scheduler to feed, since
dispatch is JAX's async dispatch.
"""

import numpy as np

from horovod_tpu.common.basics import (init, shutdown, is_initialized, rank,
                                       local_rank, cross_rank, size,
                                       local_size, cross_size,
                                       is_homogeneous, mpi_threads_supported,
                                       mpi_enabled, mpi_built, gloo_enabled,
                                       gloo_built, nccl_built, ddl_built,
                                       ccl_built, cuda_built, rocm_built,
                                       xla_built, ici_built, start_timeline,
                                       stop_timeline)
from horovod_tpu.common.exceptions import HorovodInternalError  # noqa: F401
from horovod_tpu.common.process_sets import (ProcessSet, add_process_set,
                                             global_process_set,
                                             process_set_by_id,
                                             remove_process_set)
from horovod_tpu.common.util import (check_extension, check_installed_version,
                                     gpu_available, num_rank_is_power_2,
                                     split_list)
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            ReduceOp, Sum)
from horovod_tpu.tensorflow.util import refs_to_vars, vars_to_refs
from horovod_tpu.version import __version__

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "cross_rank",
    "size", "local_size", "cross_size", "ProcessSet", "add_process_set",
    "global_process_set", "process_set_by_id", "remove_process_set",
    "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max", "Product",
    "allreduce", "grouped_allreduce", "allgather", "grouped_allgather",
    "broadcast", "broadcast_", "alltoall",
    "reducescatter", "grouped_reducescatter", "broadcast_variables",
    "broadcast_object", "broadcast_object_fn", "allgather_object",
    "DistributedGradientTape", "PartialDistributedGradientTape",
    "DistributedOptimizer", "Compression", "join", "barrier",
    "size_op", "rank_op", "local_size_op", "local_rank_op",
    "process_set_included_op",
    "is_homogeneous", "mpi_threads_supported", "mpi_enabled", "mpi_built",
    "gloo_enabled", "gloo_built", "nccl_built", "ddl_built", "ccl_built",
    "cuda_built", "rocm_built", "xla_built", "ici_built",
    "start_timeline", "stop_timeline",
    "check_extension", "check_installed_version", "gpu_available",
    "num_rank_is_power_2", "check_num_rank_power_of_2", "split_list",
    "handle_average_backwards_compatibility", "vars_to_refs", "refs_to_vars",
    "SyncBatchNormalization", "LocalGradientAggregationHelper", "elastic",
]


def __getattr__(name):
    # Lazy heavy symbols (each pulls in TensorFlow on first touch), PEP 562.
    if name == "SyncBatchNormalization":
        from horovod_tpu.tensorflow.sync_batch_norm import \
            SyncBatchNormalization
        return SyncBatchNormalization
    if name == "LocalGradientAggregationHelper":
        from horovod_tpu.tensorflow.gradient_aggregation import \
            LocalGradientAggregationHelper
        return LocalGradientAggregationHelper
    if name == "elastic":
        import horovod_tpu.tensorflow.elastic as elastic
        return elastic
    raise AttributeError(name)


def _tf():
    import tensorflow as tf
    return tf


def _to_numpy(t):
    tf = _tf()
    if isinstance(t, tf.IndexedSlices):
        raise ValueError(
            "IndexedSlices reach the dense path via convert_to_tensor first "
            "(see allreduce(sparse_as_dense) below)")
    if isinstance(t, tf.Variable):
        t = t.value()
    if not isinstance(t, tf.Tensor):
        t = tf.convert_to_tensor(t)
    return t.numpy(), t.dtype


def _to_tf(a, tf_dtype):
    tf = _tf()
    return tf.constant(np.asarray(a), dtype=tf_dtype)


def _stack(a, ps):
    # One row per rank this process owns (all of them single-controller,
    # the local chips multi-process) — the eager stacked contract.
    n_rows = C._expected_rows(ps.mesh, ps.size())
    return np.broadcast_to(a, (n_rows,) + a.shape)


def _ps(process_set):
    return process_set if process_set is not None else C.global_process_set


def var_key(v):
    """Hashable identity for a variable: tf Variables expose ``ref()``;
    Keras-3 backend Variables don't — fall back to object identity. Shared
    by the tape's local-source bookkeeping and the Keras optimizer's
    local-layer / groups bookkeeping so they cannot drift."""
    return v.ref() if hasattr(v, "ref") else id(v)


def _in_graph(tensor):
    """True when building a tf.function/graph: every input (symbolic
    tensors, Variables, python/numpy values) must ride the host-callback op
    — ``.numpy()`` bridging only exists eagerly."""
    return not _tf().executing_eagerly()


def _check_not_xla_jit(name):
    """Fail at TRACE time inside ``tf.function(jit_compile=True)``.

    The host-callback bridge cannot appear in an XLA-compiled tf.function
    (PyFunc has no XLA kernel; TF's own failure is a late, opaque
    tf2xla-conversion error, and gradients through the callback boundary
    silently break in that mode). The reference solves this with XLA
    CustomCalls (reference: tensorflow/xla_mpi_ops.cc:98-120); the
    TPU-native answer is the in-jit API — run the collective inside YOUR
    jitted program (horovod_tpu.ops.in_jit, docs/api.md). Detection walks
    the raw tracing frames for the calling ``tf.function``'s
    ``_jit_compile`` flag — there is no public trace-time marker (and no
    ``inspect.stack()``: that materializes source context for hundreds of
    TF tracing frames on every op build)."""
    import sys
    frame = sys._getframe(1)
    hit = False
    while frame is not None:
        if getattr(frame.f_locals.get("self"), "_jit_compile", None):
            hit = True
            break
        frame = frame.f_back
    del frame
    if hit:
        raise NotImplementedError(
            f"horovod_tpu.tensorflow.{name} cannot run inside "
            "tf.function(jit_compile=True): the collective rides a "
            "host callback (tf.numpy_function), which XLA cannot "
            "compile and whose gradients break under jit_compile. "
            "Either drop jit_compile=True for the horovod ops, or use "
            "the in-jit API (horovod_tpu.ops.in_jit) inside a JAX "
            "program — the TPU-native analog of the reference's XLA "
            "CustomCall ops (xla_mpi_ops.cc).")


def _graph_op(inputs, np_fn, name, out_dtypes=None, out_shapes=None,
              cast_back=None):
    """In-graph collective: a ``tf.numpy_function`` host callback around the
    eager numpy core — the moral equivalent of the reference's
    ``HorovodAllreduce``/... AsyncOpKernels usable inside graphs
    (reference: tensorflow/mpi_ops.cc:443-1656).

    Graph-mode contract: the callback runs on the host at graph execution
    time, ordered by TF's data dependencies; bf16/fp16 lanes are widened to
    fp32 across the numpy_function boundary (it has no half kernels) and
    cast back; outputs get static shapes from ``out_shapes`` (None entries
    stay dynamic). ``out_dtypes``/``cast_back`` default to one output per
    input with the same (widened / original) dtype — pass them explicitly
    only when outputs differ from inputs (e.g. alltoall's received splits).
    """
    tf = _tf()
    _check_not_xla_jit(name)
    half = (tf.bfloat16, tf.float16)
    inputs = [tf.convert_to_tensor(t) for t in inputs]
    wire = [tf.cast(t, tf.float32) if t.dtype in half else t for t in inputs]
    if out_dtypes is None:
        out_dtypes = [w.dtype for w in wire]
    if cast_back is None:
        cast_back = [t.dtype for t in inputs]
    if out_shapes is None:
        out_shapes = [t.shape for t in inputs]

    def _np(*arrs):
        return [np.asarray(o) for o in np_fn(*arrs)]

    outs = tf.numpy_function(_np, wire, out_dtypes, name=name)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    results = []
    for i, o in enumerate(outs):
        if out_shapes[i] is not None:
            o.set_shape(out_shapes[i])
        back = cast_back[i] if i < len(cast_back) else None
        results.append(tf.cast(o, back) if back is not None
                       and o.dtype != back else o)
    return results


class Compression:
    """reference: horovod/tensorflow/compression.py."""

    class none:
        @staticmethod
        def compress(a):
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a

    class fp16:
        @staticmethod
        def compress(a):
            if a.dtype in (np.float32, np.float64):
                return a.astype(np.float16), a.dtype
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a if ctx is None else np.asarray(a).astype(ctx)

    class bf16:
        @staticmethod
        def compress(a):
            import ml_dtypes
            if a.dtype in (np.float32, np.float64):
                return a.astype(ml_dtypes.bfloat16), a.dtype
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a if ctx is None else np.asarray(a).astype(ctx)


class Compressor:
    """Abstract wire compressor for user subclasses (reference:
    horovod/tensorflow/compression.py Compressor base)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


# Reference-named aliases (tensorflow/compression.py NoneCompressor /
# FP16Compressor; bf16 is the TPU-native addition).
NoneCompressor = Compression.none
FP16Compressor = Compression.fp16
BF16Compressor = Compression.bf16


def allreduce(tensor, average=None, op=None, prescale_factor=1.0,
              postscale_factor=1.0, compression=Compression.none,
              sparse_as_dense=False, name=None, process_set=None):
    """reference: hvd.allreduce (tensorflow/__init__.py:58-170, incl. the
    IndexedSlices→dense path when sparse_as_dense)."""
    tf = _tf()
    if op is not None and average is not None:
        raise ValueError("specify either op or the legacy average flag")
    if op is None:
        op = Average if (average is None or average) else Sum
    if isinstance(tensor, tf.IndexedSlices):
        if not sparse_as_dense:
            # dense allgather of values+indices is the reference's default
            # sparse path; here sparse inputs require opt-in densification.
            raise ValueError(
                "IndexedSlices input requires sparse_as_dense=True "
                "(the TPU data plane is dense)")
        tensor = tf.convert_to_tensor(tensor)

    def _np_core(a):
        compressed, ctx = compression.compress(np.asarray(a))
        ps = _ps(process_set)
        out = C.allreduce(_stack(compressed, ps), op=op,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor,
                          process_set=process_set, name=name)
        return np.asarray(
            compression.decompress(np.asarray(out)[0], ctx)).astype(a.dtype)

    if _in_graph(tensor):
        return _graph_op([tensor], lambda a: [_np_core(a)],
                         "hvd_allreduce")[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def grouped_allreduce(tensors, average=None, op=None, prescale_factor=1.0,
                      postscale_factor=1.0, name=None, process_set=None,
                      compression=None):
    if op is None:
        op = Average if (average is None or average) else Sum
    compression = compression or Compression.none
    tf = _tf()

    def _np_core(*arrs):
        ps = _ps(process_set)
        compressed, ctxs = zip(*(compression.compress(np.asarray(a))
                                 for a in arrs))
        outs = C.grouped_allreduce([_stack(c, ps) for c in compressed],
                                   op=op, prescale_factor=prescale_factor,
                                   postscale_factor=postscale_factor,
                                   process_set=process_set, name=name)
        return [np.asarray(compression.decompress(np.asarray(o)[0], ctx))
                .astype(a.dtype)
                for o, ctx, a in zip(outs, ctxs, arrs)]

    if not tf.executing_eagerly():
        return _graph_op(tensors, _np_core, "hvd_grouped_allreduce")
    arrs, dtypes = zip(*(_to_numpy(t) for t in tensors))
    return [_to_tf(o, dt) for o, dt in zip(_np_core(*arrs), dtypes)]


def allgather(tensor, name=None, process_set=None):
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    def _np_core(a):
        a = np.asarray(a)
        out = C.allgather(_stack(a, ps), process_set=process_set, name=name)
        flat = np.asarray(out)[0]
        return flat.reshape((n * a.shape[0],) + a.shape[1:]).astype(a.dtype)

    if _in_graph(tensor):
        tensor = tf.convert_to_tensor(tensor)
        d0 = tensor.shape[0] if tensor.shape.rank else None
        shape = tf.TensorShape(
            [n * d0 if d0 is not None else None]
            + list(tensor.shape[1:])) if tensor.shape.rank else None
        return _graph_op([tensor], lambda a: [_np_core(a)], "hvd_allgather",
                         out_shapes=[shape])[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def broadcast(tensor, root_rank=0, name=None, process_set=None):
    tf = _tf()

    def _np_core(a):
        a = np.asarray(a)
        out = C.broadcast(_stack(a, _ps(process_set)), root_rank,
                          process_set=process_set, name=name)
        return np.asarray(out)[0].astype(a.dtype)

    if _in_graph(tensor):
        return _graph_op([tensor], lambda a: [_np_core(a)],
                         "hvd_broadcast")[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def alltoall(tensor, splits=None, name=None, process_set=None):
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    if splits is None:
        def _np_core(a):
            a = np.asarray(a)
            out = C.alltoall(_stack(a, ps), process_set=process_set,
                             name=name)
            return np.asarray(out)[0].astype(a.dtype)

        if _in_graph(tensor):
            return _graph_op([tensor], lambda a: [_np_core(a)],
                             "hvd_alltoall")[0]
        a, dtype = _to_numpy(tensor)
        return _to_tf(_np_core(a), dtype)

    def _np_core2(a, sp):
        a = np.asarray(a)
        stacked = _stack(a, ps)
        # One splits row per stacked row (local rows only multi-process).
        mat = np.broadcast_to(np.asarray(sp), (stacked.shape[0], n))
        rows, received = C.alltoall(stacked, splits=mat,
                                    process_set=process_set, name=name)
        return (np.asarray(rows[0]).astype(a.dtype),
                np.asarray(received[0], np.int64))

    if _in_graph(tensor):
        tensor = tf.convert_to_tensor(tensor)
        half = (tf.bfloat16, tf.float16)
        out_dt = tf.float32 if tensor.dtype in half else tensor.dtype
        sp = tf.cast(tf.convert_to_tensor(splits), tf.int64)
        # First dim of the received rows is data-dependent: dynamic.
        vshape = tf.TensorShape([None] + list(tensor.shape[1:])) \
            if tensor.shape.rank else None
        out, received = _graph_op(
            [tensor, sp], _np_core2, "hvd_alltoall",
            out_dtypes=[out_dt, tf.int64],
            out_shapes=[vshape, tf.TensorShape([n])],
            cast_back=[tensor.dtype, None])
        return out, received
    a, dtype = _to_numpy(tensor)
    vals, received = _np_core2(a, splits)
    return _to_tf(vals, dtype), tf.constant(received)


def reducescatter(tensor, op=Sum, name=None, process_set=None):
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    def _np_core(a):
        a = np.asarray(a)
        out = C.reducescatter(_stack(a, ps), op=op,
                              process_set=process_set, name=name)
        return np.asarray(out)[0].astype(a.dtype)

    if _in_graph(tensor):
        tensor = tf.convert_to_tensor(tensor)
        d0 = tensor.shape[0] if tensor.shape.rank else None
        shape = tf.TensorShape(
            [d0 // n if d0 is not None else None]
            + list(tensor.shape[1:])) if tensor.shape.rank else None
        return _graph_op([tensor], lambda a: [_np_core(a)],
                         "hvd_reducescatter", out_shapes=[shape])[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def broadcast_object(obj, root_rank=0, session=None, name=None,
                     process_set=None):
    # ``session`` kept for reference source compatibility (TF1); unused.
    return C.broadcast_object(obj, root_rank=root_rank, name=name,
                              process_set=process_set)


def broadcast_object_fn(root_rank=0, session=None, name=None,
                        process_set=None):
    """Return a reusable object-broadcast callable (reference:
    tensorflow/functions.py broadcast_object_fn — TF1 needed a pre-built op;
    here it simply closes over the arguments)."""
    return lambda obj: broadcast_object(obj, root_rank=root_rank, name=name,
                                        process_set=process_set)


def allgather_object(obj, session=None, name=None, process_set=None):
    """Gather one picklable object per rank; every caller receives the full
    list (reference: tensorflow/functions.py allgather_object)."""
    return C.allgather_object_single(obj, process_set=process_set, name=name)


def broadcast_variables(variables, root_rank=0, process_set=None):
    """Assign every variable its root-rank value (reference:
    hvd.broadcast_variables tensorflow/functions.py)."""
    for v in variables:
        v.assign(broadcast(v, root_rank=root_rank, process_set=process_set))


def join(process_set=None):
    return C.join(process_set=process_set)


def barrier(process_set=None):
    C.barrier(process_set=process_set)


def handle_average_backwards_compatibility(op, average):
    """Resolve the deprecated ``average`` flag against ``op`` (reference:
    tensorflow/mpi_ops.py handle_average_backwards_compatibility)."""
    if op is not None:
        if average is not None:
            raise ValueError(
                "The op parameter supersedes average. Please provide only "
                "the op parameter.")
        return op
    return Average if (average is None or average) else Sum


def check_num_rank_power_of_2(num_rank):
    """Adasum's recursive halving-doubling requires a power-of-two world
    (reference: tensorflow/mpi_ops.py check_num_rank_power_of_2)."""
    if not num_rank_is_power_2(num_rank):
        raise ValueError(
            "Running Adasum with non-power-of-2 ranks is not supported yet.")


# --- graph-mode query ops ---------------------------------------------------
# The reference registers tiny custom kernels (HorovodSize/Rank/..., mpi_ops.
# cc:1565-1656) so elastic graphs read the LIVE world size at execution time
# rather than baking a constant in at trace time. Here each is a
# tf.numpy_function host callback reading the runtime's current answer.

def _query_op(read, name):
    tf = _tf()
    _check_not_xla_jit(name)
    out = tf.numpy_function(lambda: np.int32(read()), [], tf.int32, name=name)
    out.set_shape(())
    return out


def size_op(process_set_id=0, name=None):
    return _query_op(lambda: process_set_by_id(process_set_id).size(),
                     name or "HorovodSize")


def local_size_op(name=None):
    return _query_op(local_size, name or "HorovodLocalSize")


def rank_op(name=None):
    return _query_op(rank, name or "HorovodRank")


def local_rank_op(name=None):
    return _query_op(local_rank, name or "HorovodLocalRank")


def process_set_included_op(process_set_id=0, name=None):
    return _query_op(
        lambda: 1 if process_set_by_id(process_set_id).included() else 0,
        name or "HorovodProcessSetIncluded")


def broadcast_(variables, root_rank=0, name=None, process_set=None):
    """In-place broadcast: assign every variable its root-rank value and
    return the variables (reference: HorovodBroadcastInplace,
    tensorflow/mpi_ops.py broadcast_)."""
    for v in variables:
        v.assign(broadcast(v, root_rank=root_rank, name=name,
                           process_set=process_set))
    return variables


def grouped_allgather(tensors, name=None, process_set=None):
    """Gather a group of tensors in one fused program (reference:
    hvd.grouped_allgather tensorflow/mpi_ops.py)."""
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    def _np_core(*arrs):
        outs = C.grouped_allgather([_stack(np.asarray(a), ps) for a in arrs],
                                   process_set=process_set, name=name)
        return [np.asarray(o)[0].reshape((n * a.shape[0],) + a.shape[1:])
                .astype(np.asarray(a).dtype)
                for o, a in zip(outs, arrs)]

    if not tf.executing_eagerly():
        tensors = [tf.convert_to_tensor(t) for t in tensors]
        shapes = []
        for t in tensors:
            d0 = t.shape[0] if t.shape.rank else None
            shapes.append(tf.TensorShape(
                [n * d0 if d0 is not None else None] + list(t.shape[1:]))
                if t.shape.rank else None)
        return _graph_op(tensors, _np_core, "hvd_grouped_allgather",
                         out_shapes=shapes)
    arrs, dtypes = zip(*(_to_numpy(t) for t in tensors))
    return [_to_tf(o, dt) for o, dt in zip(_np_core(*arrs), dtypes)]


def grouped_reducescatter(tensors, op=Sum, name=None, process_set=None):
    """Reduce-scatter a group of tensors in one fused program (reference:
    hvd.grouped_reducescatter tensorflow/mpi_ops.py)."""
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    def _np_core(*arrs):
        outs = C.grouped_reducescatter(
            [_stack(np.asarray(a), ps) for a in arrs], op=op,
            process_set=process_set, name=name)
        return [np.asarray(o)[0].astype(np.asarray(a).dtype)
                for o, a in zip(outs, arrs)]

    if not tf.executing_eagerly():
        tensors = [tf.convert_to_tensor(t) for t in tensors]
        shapes = []
        for t in tensors:
            d0 = t.shape[0] if t.shape.rank else None
            shapes.append(tf.TensorShape(
                [d0 // n if d0 is not None else None] + list(t.shape[1:]))
                if t.shape.rank else None)
        return _graph_op(tensors, _np_core, "hvd_grouped_reducescatter",
                         out_shapes=shapes)
    arrs, dtypes = zip(*(_to_numpy(t) for t in tensors))
    return [_to_tf(o, dt) for o, dt in zip(_np_core(*arrs), dtypes)]


class DistributedGradientTape:
    """Wraps tf.GradientTape so ``gradient()`` returns cross-host-averaged
    gradients (reference: _DistributedGradientTape
    tensorflow/__init__.py:957-1110)."""

    def __init__(self, gradtape, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average, gradient_predivide_factor=1.0,
                 num_groups=0, process_set=None,
                 scale_local_gradients=True):
        self._tape = gradtape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op
        self._predivide = gradient_predivide_factor
        self._process_set = process_set
        self._local_sources = set()
        self._scale_local_gradients = scale_local_gradients

    def register_local_source(self, source):
        """Mark a source (tf.Variable) worker-local: its gradient stays
        local instead of being allreduced (reference:
        PartialDistributedGradientTape, tensorflow/__init__.py:1110+)."""
        self._local_sources.add(var_key(source))

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def gradient(self, target, sources, output_gradients=None):
        tf = _tf()
        grads = self._tape.gradient(target, sources, output_gradients)
        if self._sparse_as_dense:
            grads = [tf.convert_to_tensor(g)
                     if isinstance(g, tf.IndexedSlices) else g
                     for g in grads]
        for g in grads:
            if isinstance(g, tf.IndexedSlices):
                raise ValueError(
                    "IndexedSlices gradient (embedding layer?): pass "
                    "sparse_as_dense=True to DistributedGradientTape "
                    "(the TPU data plane is dense)")
        src_list = list(sources) if isinstance(sources, (list, tuple)) \
            else [sources]

        def _is_local(i):
            if not self._local_sources or i >= len(src_list):
                return False
            s = src_list[i]
            return var_key(s) in self._local_sources

        reduce_idx = [i for i, g in enumerate(grads)
                      if g is not None and not _is_local(i)]
        if not reduce_idx:
            return grads
        op = self._op
        prescale = postscale = 1.0
        if self._predivide != 1.0 and op == Average:
            prescale = 1.0 / self._predivide
            postscale = self._predivide / _ps(self._process_set).size()
            op = Sum
        reduced = grouped_allreduce(
            [grads[i] for i in reduce_idx], op=op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=self._process_set,
            compression=self._compression)
        out = list(grads)
        for i, r in zip(reduce_idx, reduced):
            out[i] = r
        if self._local_sources and self._scale_local_gradients:
            # Scale worker-local gradients down by the world size so their
            # magnitude matches the averaged global ones (reference
            # rationale: pull/3695).
            n = _ps(self._process_set).size()
            for i, g in enumerate(out):
                if g is not None and _is_local(i):
                    out[i] = g / n
        return out


def PartialDistributedGradientTape(gradtape, local_layers=None, **kwargs):
    """A DistributedGradientTape that keeps the gradients of ``local_layers``
    worker-local (reference: tensorflow/__init__.py:1110+ — used for models
    with rank-specific towers, e.g. embedding shards)."""
    tape = DistributedGradientTape(gradtape, **kwargs)
    for layer in (local_layers or []):
        variables = getattr(layer, "trainable_variables", None)
        if variables is None:  # a bare variable was passed
            variables = [layer]
        for v in variables:
            tape.register_local_source(v)
    return tape


def _make_allreduce_grads_fn(op, gradient_predivide_factor, compression,
                             sparse_as_dense, process_set, groups):
    """Build the grads→reduced-grads function shared by DistributedOptimizer
    and the aggregation helper (reference: tensorflow/__init__.py
    _make_allreduce_grads_fn). ``groups``: None, an int (chunk count), or a
    list of variable lists for explicit grouping — each group rides one
    fused program."""
    tf = _tf()

    def fn(grads, variables=None):
        grads = list(grads)
        if sparse_as_dense:
            grads = [tf.convert_to_tensor(g)
                     if isinstance(g, tf.IndexedSlices) else g for g in grads]
        live_idx = [i for i, g in enumerate(grads) if g is not None]
        if not live_idx:
            return grads
        op_, prescale, postscale = op, 1.0, 1.0
        if gradient_predivide_factor != 1.0 and op == Average:
            prescale = 1.0 / gradient_predivide_factor
            postscale = gradient_predivide_factor / _ps(process_set).size()
            op_ = Sum

        if isinstance(groups, int) and groups > 0:
            # More groups than live gradients leaves empty trailing chunks —
            # drop them (grouped_allreduce([]) is an error).
            chunks = [c for c in split_list(live_idx, groups) if c]
        elif isinstance(groups, (list, tuple)) and variables is not None:
            by_ref = {}
            for gi, group in enumerate(groups):
                for v in group:
                    by_ref[var_key(v)] = gi
            chunks_map = {}
            for i in live_idx:
                key = by_ref.get(var_key(variables[i]), f"solo{i}")
                chunks_map.setdefault(key, []).append(i)
            chunks = list(chunks_map.values())
        else:
            chunks = [live_idx]

        out = list(grads)
        for chunk in chunks:
            reduced = grouped_allreduce(
                [grads[i] for i in chunk], op=op_,
                prescale_factor=prescale, postscale_factor=postscale,
                process_set=process_set, compression=compression)
            for i, r in zip(chunk, reduced):
                out[i] = r
        return out

    return fn


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False, backward_passes_per_step=1,
                         op=Average, gradient_predivide_factor=1.0,
                         average_aggregated_gradients=False,
                         num_groups=0, groups=None, process_set=None,
                         scale_local_gradients=True):
    """Wrap an optimizer so gradients are combined across the process set
    before updates (reference: tensorflow/__init__.py:822 DistributedOptimizer).

    Keras(-3) optimizers route to the Keras frontend's apply_gradients
    interception; legacy ``tf.compat.v1.train.Optimizer``s get the
    compute_gradients wrapper with optional local aggregation
    (backward_passes_per_step) and explicit grouping (``groups``).
    """
    tf = _tf()
    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError(
            "gradient_predivide_factor not supported with op != Average")
    if op == Adasum and average_aggregated_gradients:
        raise ValueError(
            "Adasum does not support average_aggregated_gradients == True")
    if op == Adasum:
        # reference: tensorflow/__init__.py:161 — the VHDD combine order is
        # only defined for power-of-two worlds.
        check_num_rank_power_of_2(size())
    if num_groups != 0 and groups is None:
        groups = num_groups

    if not hasattr(optimizer, "compute_gradients"):
        # Keras-3 / tf.keras optimizer: the class-swap wrapper owns the
        # apply_gradients choke point.
        from horovod_tpu import keras as hvd_keras
        return hvd_keras.DistributedOptimizer(
            optimizer, name=name, compression=compression,
            sparse_as_dense=sparse_as_dense, op=op,
            backward_passes_per_step=backward_passes_per_step,
            average_aggregated_gradients=average_aggregated_gradients,
            gradient_predivide_factor=gradient_predivide_factor,
            groups=groups, process_set=process_set,
            scale_local_gradients=scale_local_gradients)

    allreduce_grads = _make_allreduce_grads_fn(
        op, gradient_predivide_factor, compression, sparse_as_dense,
        process_set, groups)

    class _DistributedOptimizer(tf.compat.v1.train.Optimizer):
        """Legacy-optimizer wrapper (reference: _DistributedOptimizer,
        tensorflow/__init__.py:602-725)."""

        def __init__(self):
            super().__init__(
                name=name or f"Distributed{type(optimizer).__name__}",
                use_locking=use_locking)
            self._optimizer = optimizer
            self._local_vars = set()
            self.process_set = _ps(process_set)
            if backward_passes_per_step > 1:
                from horovod_tpu.tensorflow.gradient_aggregation import \
                    LocalGradientAggregationHelper
                self._agg_helper = LocalGradientAggregationHelper(
                    backward_passes_per_step, allreduce_grads,
                    sparse_as_dense=sparse_as_dense,
                    average_aggregated_gradients=average_aggregated_gradients,
                    rank=rank(), process_set=process_set,
                    scale_local_gradients=scale_local_gradients)
            else:
                self._agg_helper = None

        def register_local_var(self, var):
            self._local_vars.add(var_key(var))
            if self._agg_helper:
                self._agg_helper.register_local_var(var)

        def compute_gradients(self, *args, **kwargs):
            gradients = self._optimizer.compute_gradients(*args, **kwargs)
            grads, variables = zip(*gradients)
            grads, variables = list(grads), list(variables)
            if self._agg_helper:
                avg = self._agg_helper.compute_gradients(grads, variables)
            else:
                reduce_idx = [i for i, v in enumerate(variables)
                              if var_key(v) not in self._local_vars]
                reduced = allreduce_grads([grads[i] for i in reduce_idx],
                                          [variables[i] for i in reduce_idx])
                avg = list(grads)
                for i, r in zip(reduce_idx, reduced):
                    avg[i] = r
                if scale_local_gradients and self._local_vars:
                    n = self.process_set.size()
                    for i, v in enumerate(variables):
                        if var_key(v) in self._local_vars \
                                and avg[i] is not None:
                            avg[i] = avg[i] / n
            return list(zip(avg, variables))

        def apply_gradients(self, grads_and_vars, global_step=None,
                            name=None):
            if self._agg_helper:
                return self._agg_helper.apply_gradients(
                    lambda: self._optimizer.apply_gradients(
                        grads_and_vars, global_step=global_step, name=name),
                    self._optimizer)
            return self._optimizer.apply_gradients(
                grads_and_vars, global_step=global_step, name=name)

        def get_slot(self, *args, **kwargs):
            return self._optimizer.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._optimizer.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._optimizer.variables(*args, **kwargs)

        def minimize(self, *args, **kwargs):
            return tf.compat.v1.train.Optimizer.minimize(
                self, *args, **kwargs)

    return _DistributedOptimizer()
