"""TensorFlow frontend — the ``horovod.tensorflow`` API surface.

Reference: horovod/tensorflow/__init__.py (allreduce & friends :58-170,
DistributedGradientTape :957-1110, broadcast_variables), mpi_ops.py.

Design: like the torch frontend, TF here is a host-side frontend over the XLA
eager runtime — a tf.Tensor is bridged via numpy (TF already yields ml_dtypes
bfloat16 arrays, so the bf16 wire path is zero-copy in dtype terms), rides the
host's mesh slices, and the chip-axis collective equals the cross-host
collective. There is no TF custom-op/kernel registration (reference:
tensorflow/mpi_ops.cc AsyncOpKernels) because there is no C++ scheduler to
feed — dispatch is JAX's async dispatch.
"""

import numpy as np

from horovod_tpu.common.basics import (init, shutdown, is_initialized, rank,
                                       local_rank, cross_rank, size,
                                       local_size, cross_size)
from horovod_tpu.common.process_sets import (ProcessSet, add_process_set,
                                             global_process_set,
                                             process_set_by_id,
                                             remove_process_set)
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            ReduceOp, Sum)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "cross_rank",
    "size", "local_size", "cross_size", "ProcessSet", "add_process_set",
    "global_process_set", "process_set_by_id", "remove_process_set",
    "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max", "Product",
    "allreduce", "grouped_allreduce", "allgather", "broadcast", "alltoall",
    "reducescatter", "broadcast_variables", "broadcast_object",
    "DistributedGradientTape", "Compression", "join", "barrier",
]


def _tf():
    import tensorflow as tf
    return tf


def _to_numpy(t):
    tf = _tf()
    if isinstance(t, tf.IndexedSlices):
        raise ValueError(
            "IndexedSlices reach the dense path via convert_to_tensor first "
            "(see allreduce(sparse_as_dense) below)")
    if isinstance(t, tf.Variable):
        t = t.value()
    if not isinstance(t, tf.Tensor):
        t = tf.convert_to_tensor(t)
    return t.numpy(), t.dtype


def _to_tf(a, tf_dtype):
    tf = _tf()
    return tf.constant(np.asarray(a), dtype=tf_dtype)


def _stack(a, ps):
    return np.broadcast_to(a, (ps.size(),) + a.shape)


def _ps(process_set):
    return process_set if process_set is not None else C.global_process_set


class Compression:
    """reference: horovod/tensorflow/compression.py."""

    class none:
        @staticmethod
        def compress(a):
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a

    class fp16:
        @staticmethod
        def compress(a):
            if a.dtype in (np.float32, np.float64):
                return a.astype(np.float16), a.dtype
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a if ctx is None else np.asarray(a).astype(ctx)

    class bf16:
        @staticmethod
        def compress(a):
            import ml_dtypes
            if a.dtype in (np.float32, np.float64):
                return a.astype(ml_dtypes.bfloat16), a.dtype
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a if ctx is None else np.asarray(a).astype(ctx)


def allreduce(tensor, average=None, op=None, prescale_factor=1.0,
              postscale_factor=1.0, compression=Compression.none,
              sparse_as_dense=False, name=None, process_set=None):
    """reference: hvd.allreduce (tensorflow/__init__.py:58-170, incl. the
    IndexedSlices→dense path when sparse_as_dense)."""
    tf = _tf()
    if op is not None and average is not None:
        raise ValueError("specify either op or the legacy average flag")
    if op is None:
        op = Average if (average is None or average) else Sum
    if isinstance(tensor, tf.IndexedSlices):
        if not sparse_as_dense:
            # dense allgather of values+indices is the reference's default
            # sparse path; here sparse inputs require opt-in densification.
            raise ValueError(
                "IndexedSlices input requires sparse_as_dense=True "
                "(the TPU data plane is dense)")
        tensor = tf.convert_to_tensor(tensor)
    a, dtype = _to_numpy(tensor)
    compressed, ctx = compression.compress(a)
    ps = _ps(process_set)
    out = C.allreduce(_stack(compressed, ps), op=op,
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor,
                      process_set=process_set, name=name)
    return _to_tf(compression.decompress(np.asarray(out)[0], ctx), dtype)


def grouped_allreduce(tensors, average=None, op=None, prescale_factor=1.0,
                      postscale_factor=1.0, name=None, process_set=None,
                      compression=None):
    if op is None:
        op = Average if (average is None or average) else Sum
    compression = compression or Compression.none
    tf = _tf()
    if not tf.executing_eagerly():
        # Inside tf.function (Keras compiled train steps): the collective
        # rides a host-callback op in the graph — the numpy_function here is
        # the moral equivalent of the reference's HorovodAllreduce custom op
        # (reference: tensorflow/mpi_ops.cc:443-516 AsyncOpKernel).
        return _graph_grouped_allreduce(tensors, op, prescale_factor,
                                        postscale_factor, process_set,
                                        compression)
    arrs, dtypes = zip(*(_to_numpy(t) for t in tensors))
    ps = _ps(process_set)
    wires, ctxs = zip(*(compression.compress(a) for a in arrs))
    outs = C.grouped_allreduce([_stack(a, ps) for a in wires], op=op,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor,
                               process_set=process_set, name=name)
    return [_to_tf(compression.decompress(np.asarray(o)[0], ctx), dt)
            for o, ctx, dt in zip(outs, ctxs, dtypes)]


def _graph_grouped_allreduce(tensors, op, prescale_factor, postscale_factor,
                             process_set, compression):
    tf = _tf()
    # numpy_function has no bf16/f16 kernel coverage; widen those lanes.
    wire = [t if t.dtype not in (tf.bfloat16, tf.float16)
            else tf.cast(t, tf.float32) for t in tensors]

    def _np_fn(*arrs):
        ps = _ps(process_set)
        compressed, ctxs = zip(*(compression.compress(np.asarray(a))
                                 for a in arrs))
        outs = C.grouped_allreduce([_stack(c, ps) for c in compressed],
                                   op=op, prescale_factor=prescale_factor,
                                   postscale_factor=postscale_factor,
                                   process_set=process_set)
        return [np.asarray(compression.decompress(np.asarray(o)[0], ctx))
                .astype(a.dtype)
                for o, ctx, a in zip(outs, ctxs, arrs)]

    outs = tf.numpy_function(_np_fn, wire, [t.dtype for t in wire],
                             name="hvd_grouped_allreduce")
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    results = []
    for o, t in zip(outs, tensors):
        o.set_shape(t.shape)
        results.append(tf.cast(o, t.dtype) if o.dtype != t.dtype else o)
    return results


def allgather(tensor, name=None, process_set=None):
    a, dtype = _to_numpy(tensor)
    ps = _ps(process_set)
    out = C.allgather(_stack(a, ps), process_set=process_set, name=name)
    flat = np.asarray(out)[0]
    return _to_tf(flat.reshape((ps.size() * a.shape[0],) + a.shape[1:]),
                  dtype)


def broadcast(tensor, root_rank=0, name=None, process_set=None):
    a, dtype = _to_numpy(tensor)
    ps = _ps(process_set)
    out = C.broadcast(_stack(a, ps), root_rank, process_set=process_set,
                      name=name)
    return _to_tf(np.asarray(out)[0], dtype)


def alltoall(tensor, splits=None, name=None, process_set=None):
    a, dtype = _to_numpy(tensor)
    ps = _ps(process_set)
    n = ps.size()
    if splits is None:
        out = C.alltoall(_stack(a, ps), process_set=process_set, name=name)
        return _to_tf(np.asarray(out)[0], dtype)
    splits = np.asarray(splits)
    mat = np.broadcast_to(splits, (n, n))
    rows, received = C.alltoall(_stack(a, ps), splits=mat,
                                process_set=process_set, name=name)
    return _to_tf(np.asarray(rows[0]), dtype), _tf().constant(received[0])


def reducescatter(tensor, op=Sum, name=None, process_set=None):
    a, dtype = _to_numpy(tensor)
    out = C.reducescatter(_stack(a, _ps(process_set)), op=op,
                          process_set=process_set, name=name)
    return _to_tf(np.asarray(out)[0], dtype)


def broadcast_object(obj, root_rank=0, name=None, process_set=None):
    return C.broadcast_object(obj, root_rank=root_rank, name=name,
                              process_set=process_set)


def broadcast_variables(variables, root_rank=0, process_set=None):
    """Assign every variable its root-rank value (reference:
    hvd.broadcast_variables tensorflow/functions.py)."""
    for v in variables:
        v.assign(broadcast(v, root_rank=root_rank, process_set=process_set))


def join():
    return C.join()


def barrier(process_set=None):
    C.barrier(process_set=process_set)


class DistributedGradientTape:
    """Wraps tf.GradientTape so ``gradient()`` returns cross-host-averaged
    gradients (reference: _DistributedGradientTape
    tensorflow/__init__.py:957-1110)."""

    def __init__(self, gradtape, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average, gradient_predivide_factor=1.0,
                 num_groups=0, process_set=None):
        self._tape = gradtape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op
        self._predivide = gradient_predivide_factor
        self._process_set = process_set

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def gradient(self, target, sources, output_gradients=None):
        tf = _tf()
        grads = self._tape.gradient(target, sources, output_gradients)
        if self._sparse_as_dense:
            grads = [tf.convert_to_tensor(g)
                     if isinstance(g, tf.IndexedSlices) else g
                     for g in grads]
        for g in grads:
            if isinstance(g, tf.IndexedSlices):
                raise ValueError(
                    "IndexedSlices gradient (embedding layer?): pass "
                    "sparse_as_dense=True to DistributedGradientTape "
                    "(the TPU data plane is dense)")
        flat = [g for g in grads if g is not None]
        if not flat:
            return grads
        op = self._op
        prescale = postscale = 1.0
        if self._predivide != 1.0 and op == Average:
            prescale = 1.0 / self._predivide
            postscale = self._predivide / _ps(self._process_set).size()
            op = Sum
        reduced = iter(grouped_allreduce(
            flat, op=op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=self._process_set,
            compression=self._compression))
        return [None if g is None else next(reduced) for g in grads]
