"""TensorFlow frontend — the ``horovod.tensorflow`` API surface.

Reference: horovod/tensorflow/__init__.py (allreduce & friends :58-170,
DistributedGradientTape :957-1110, broadcast_variables), mpi_ops.py.

Design: like the torch frontend, TF here is a host-side frontend over the XLA
eager runtime — a tf.Tensor is bridged via numpy (TF already yields ml_dtypes
bfloat16 arrays, so the bf16 wire path is zero-copy in dtype terms), rides the
host's mesh slices, and the chip-axis collective equals the cross-host
collective. Inside ``tf.function``/graphs every collective rides a
``tf.numpy_function`` host-callback op (see :func:`_graph_op`) — the moral
equivalent of the reference's AsyncOpKernel registrations (reference:
tensorflow/mpi_ops.cc:443-1656) without a C++ scheduler to feed, since
dispatch is JAX's async dispatch.
"""

import numpy as np

from horovod_tpu.common.basics import (init, shutdown, is_initialized, rank,
                                       local_rank, cross_rank, size,
                                       local_size, cross_size)
from horovod_tpu.common.process_sets import (ProcessSet, add_process_set,
                                             global_process_set,
                                             process_set_by_id,
                                             remove_process_set)
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            ReduceOp, Sum)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "cross_rank",
    "size", "local_size", "cross_size", "ProcessSet", "add_process_set",
    "global_process_set", "process_set_by_id", "remove_process_set",
    "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max", "Product",
    "allreduce", "grouped_allreduce", "allgather", "broadcast", "alltoall",
    "reducescatter", "broadcast_variables", "broadcast_object",
    "DistributedGradientTape", "Compression", "join", "barrier",
]


def _tf():
    import tensorflow as tf
    return tf


def _to_numpy(t):
    tf = _tf()
    if isinstance(t, tf.IndexedSlices):
        raise ValueError(
            "IndexedSlices reach the dense path via convert_to_tensor first "
            "(see allreduce(sparse_as_dense) below)")
    if isinstance(t, tf.Variable):
        t = t.value()
    if not isinstance(t, tf.Tensor):
        t = tf.convert_to_tensor(t)
    return t.numpy(), t.dtype


def _to_tf(a, tf_dtype):
    tf = _tf()
    return tf.constant(np.asarray(a), dtype=tf_dtype)


def _stack(a, ps):
    # One row per rank this process owns (all of them single-controller,
    # the local chips multi-process) — the eager stacked contract.
    n_rows = C._expected_rows(ps.mesh, ps.size())
    return np.broadcast_to(a, (n_rows,) + a.shape)


def _ps(process_set):
    return process_set if process_set is not None else C.global_process_set


def _in_graph(tensor):
    """True when building a tf.function/graph: every input (symbolic
    tensors, Variables, python/numpy values) must ride the host-callback op
    — ``.numpy()`` bridging only exists eagerly."""
    return not _tf().executing_eagerly()


def _graph_op(inputs, np_fn, name, out_dtypes=None, out_shapes=None,
              cast_back=None):
    """In-graph collective: a ``tf.numpy_function`` host callback around the
    eager numpy core — the moral equivalent of the reference's
    ``HorovodAllreduce``/... AsyncOpKernels usable inside graphs
    (reference: tensorflow/mpi_ops.cc:443-1656).

    Graph-mode contract: the callback runs on the host at graph execution
    time, ordered by TF's data dependencies; bf16/fp16 lanes are widened to
    fp32 across the numpy_function boundary (it has no half kernels) and
    cast back; outputs get static shapes from ``out_shapes`` (None entries
    stay dynamic). ``out_dtypes``/``cast_back`` default to one output per
    input with the same (widened / original) dtype — pass them explicitly
    only when outputs differ from inputs (e.g. alltoall's received splits).
    """
    tf = _tf()
    half = (tf.bfloat16, tf.float16)
    inputs = [tf.convert_to_tensor(t) for t in inputs]
    wire = [tf.cast(t, tf.float32) if t.dtype in half else t for t in inputs]
    if out_dtypes is None:
        out_dtypes = [w.dtype for w in wire]
    if cast_back is None:
        cast_back = [t.dtype for t in inputs]
    if out_shapes is None:
        out_shapes = [t.shape for t in inputs]

    def _np(*arrs):
        return [np.asarray(o) for o in np_fn(*arrs)]

    outs = tf.numpy_function(_np, wire, out_dtypes, name=name)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    results = []
    for i, o in enumerate(outs):
        if out_shapes[i] is not None:
            o.set_shape(out_shapes[i])
        back = cast_back[i] if i < len(cast_back) else None
        results.append(tf.cast(o, back) if back is not None
                       and o.dtype != back else o)
    return results


class Compression:
    """reference: horovod/tensorflow/compression.py."""

    class none:
        @staticmethod
        def compress(a):
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a

    class fp16:
        @staticmethod
        def compress(a):
            if a.dtype in (np.float32, np.float64):
                return a.astype(np.float16), a.dtype
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a if ctx is None else np.asarray(a).astype(ctx)

    class bf16:
        @staticmethod
        def compress(a):
            import ml_dtypes
            if a.dtype in (np.float32, np.float64):
                return a.astype(ml_dtypes.bfloat16), a.dtype
            return a, None

        @staticmethod
        def decompress(a, ctx):
            return a if ctx is None else np.asarray(a).astype(ctx)


def allreduce(tensor, average=None, op=None, prescale_factor=1.0,
              postscale_factor=1.0, compression=Compression.none,
              sparse_as_dense=False, name=None, process_set=None):
    """reference: hvd.allreduce (tensorflow/__init__.py:58-170, incl. the
    IndexedSlices→dense path when sparse_as_dense)."""
    tf = _tf()
    if op is not None and average is not None:
        raise ValueError("specify either op or the legacy average flag")
    if op is None:
        op = Average if (average is None or average) else Sum
    if isinstance(tensor, tf.IndexedSlices):
        if not sparse_as_dense:
            # dense allgather of values+indices is the reference's default
            # sparse path; here sparse inputs require opt-in densification.
            raise ValueError(
                "IndexedSlices input requires sparse_as_dense=True "
                "(the TPU data plane is dense)")
        tensor = tf.convert_to_tensor(tensor)

    def _np_core(a):
        compressed, ctx = compression.compress(np.asarray(a))
        ps = _ps(process_set)
        out = C.allreduce(_stack(compressed, ps), op=op,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor,
                          process_set=process_set, name=name)
        return np.asarray(
            compression.decompress(np.asarray(out)[0], ctx)).astype(a.dtype)

    if _in_graph(tensor):
        return _graph_op([tensor], lambda a: [_np_core(a)],
                         "hvd_allreduce")[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def grouped_allreduce(tensors, average=None, op=None, prescale_factor=1.0,
                      postscale_factor=1.0, name=None, process_set=None,
                      compression=None):
    if op is None:
        op = Average if (average is None or average) else Sum
    compression = compression or Compression.none
    tf = _tf()

    def _np_core(*arrs):
        ps = _ps(process_set)
        compressed, ctxs = zip(*(compression.compress(np.asarray(a))
                                 for a in arrs))
        outs = C.grouped_allreduce([_stack(c, ps) for c in compressed],
                                   op=op, prescale_factor=prescale_factor,
                                   postscale_factor=postscale_factor,
                                   process_set=process_set, name=name)
        return [np.asarray(compression.decompress(np.asarray(o)[0], ctx))
                .astype(a.dtype)
                for o, ctx, a in zip(outs, ctxs, arrs)]

    if not tf.executing_eagerly():
        return _graph_op(tensors, _np_core, "hvd_grouped_allreduce")
    arrs, dtypes = zip(*(_to_numpy(t) for t in tensors))
    return [_to_tf(o, dt) for o, dt in zip(_np_core(*arrs), dtypes)]


def allgather(tensor, name=None, process_set=None):
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    def _np_core(a):
        a = np.asarray(a)
        out = C.allgather(_stack(a, ps), process_set=process_set, name=name)
        flat = np.asarray(out)[0]
        return flat.reshape((n * a.shape[0],) + a.shape[1:]).astype(a.dtype)

    if _in_graph(tensor):
        tensor = tf.convert_to_tensor(tensor)
        d0 = tensor.shape[0] if tensor.shape.rank else None
        shape = tf.TensorShape(
            [n * d0 if d0 is not None else None]
            + list(tensor.shape[1:])) if tensor.shape.rank else None
        return _graph_op([tensor], lambda a: [_np_core(a)], "hvd_allgather",
                         out_shapes=[shape])[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def broadcast(tensor, root_rank=0, name=None, process_set=None):
    tf = _tf()

    def _np_core(a):
        a = np.asarray(a)
        out = C.broadcast(_stack(a, _ps(process_set)), root_rank,
                          process_set=process_set, name=name)
        return np.asarray(out)[0].astype(a.dtype)

    if _in_graph(tensor):
        return _graph_op([tensor], lambda a: [_np_core(a)],
                         "hvd_broadcast")[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def alltoall(tensor, splits=None, name=None, process_set=None):
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    if splits is None:
        def _np_core(a):
            a = np.asarray(a)
            out = C.alltoall(_stack(a, ps), process_set=process_set,
                             name=name)
            return np.asarray(out)[0].astype(a.dtype)

        if _in_graph(tensor):
            return _graph_op([tensor], lambda a: [_np_core(a)],
                             "hvd_alltoall")[0]
        a, dtype = _to_numpy(tensor)
        return _to_tf(_np_core(a), dtype)

    def _np_core2(a, sp):
        a = np.asarray(a)
        mat = np.broadcast_to(np.asarray(sp), (n, n))
        rows, received = C.alltoall(_stack(a, ps), splits=mat,
                                    process_set=process_set, name=name)
        return (np.asarray(rows[0]).astype(a.dtype),
                np.asarray(received[0], np.int64))

    if _in_graph(tensor):
        tensor = tf.convert_to_tensor(tensor)
        half = (tf.bfloat16, tf.float16)
        out_dt = tf.float32 if tensor.dtype in half else tensor.dtype
        sp = tf.cast(tf.convert_to_tensor(splits), tf.int64)
        # First dim of the received rows is data-dependent: dynamic.
        vshape = tf.TensorShape([None] + list(tensor.shape[1:])) \
            if tensor.shape.rank else None
        out, received = _graph_op(
            [tensor, sp], _np_core2, "hvd_alltoall",
            out_dtypes=[out_dt, tf.int64],
            out_shapes=[vshape, tf.TensorShape([n])],
            cast_back=[tensor.dtype, None])
        return out, received
    a, dtype = _to_numpy(tensor)
    vals, received = _np_core2(a, splits)
    return _to_tf(vals, dtype), tf.constant(received)


def reducescatter(tensor, op=Sum, name=None, process_set=None):
    tf = _tf()
    ps = _ps(process_set)
    n = ps.size()

    def _np_core(a):
        a = np.asarray(a)
        out = C.reducescatter(_stack(a, ps), op=op,
                              process_set=process_set, name=name)
        return np.asarray(out)[0].astype(a.dtype)

    if _in_graph(tensor):
        tensor = tf.convert_to_tensor(tensor)
        d0 = tensor.shape[0] if tensor.shape.rank else None
        shape = tf.TensorShape(
            [d0 // n if d0 is not None else None]
            + list(tensor.shape[1:])) if tensor.shape.rank else None
        return _graph_op([tensor], lambda a: [_np_core(a)],
                         "hvd_reducescatter", out_shapes=[shape])[0]
    a, dtype = _to_numpy(tensor)
    return _to_tf(_np_core(a), dtype)


def broadcast_object(obj, root_rank=0, name=None, process_set=None):
    return C.broadcast_object(obj, root_rank=root_rank, name=name,
                              process_set=process_set)


def broadcast_variables(variables, root_rank=0, process_set=None):
    """Assign every variable its root-rank value (reference:
    hvd.broadcast_variables tensorflow/functions.py)."""
    for v in variables:
        v.assign(broadcast(v, root_rank=root_rank, process_set=process_set))


def join():
    return C.join()


def barrier(process_set=None):
    C.barrier(process_set=process_set)


class DistributedGradientTape:
    """Wraps tf.GradientTape so ``gradient()`` returns cross-host-averaged
    gradients (reference: _DistributedGradientTape
    tensorflow/__init__.py:957-1110)."""

    def __init__(self, gradtape, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average, gradient_predivide_factor=1.0,
                 num_groups=0, process_set=None):
        self._tape = gradtape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op
        self._predivide = gradient_predivide_factor
        self._process_set = process_set

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def gradient(self, target, sources, output_gradients=None):
        tf = _tf()
        grads = self._tape.gradient(target, sources, output_gradients)
        if self._sparse_as_dense:
            grads = [tf.convert_to_tensor(g)
                     if isinstance(g, tf.IndexedSlices) else g
                     for g in grads]
        for g in grads:
            if isinstance(g, tf.IndexedSlices):
                raise ValueError(
                    "IndexedSlices gradient (embedding layer?): pass "
                    "sparse_as_dense=True to DistributedGradientTape "
                    "(the TPU data plane is dense)")
        flat = [g for g in grads if g is not None]
        if not flat:
            return grads
        op = self._op
        prescale = postscale = 1.0
        if self._predivide != 1.0 and op == Average:
            prescale = 1.0 / self._predivide
            postscale = self._predivide / _ps(self._process_set).size()
            op = Sum
        reduced = iter(grouped_allreduce(
            flat, op=op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=self._process_set,
            compression=self._compression))
        return [None if g is None else next(reduced) for g in grads]
