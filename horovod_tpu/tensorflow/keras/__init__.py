"""``horovod_tpu.tensorflow.keras`` — the tf.keras-flavored Keras frontend.

Reference: horovod/tensorflow/keras/__init__.py (same surface as
horovod/keras but bound to ``tf.keras``). Here both standalone Keras 3 and
``tf.keras`` funnel optimizer updates through ``apply_gradients``, so the
implementation is shared with :mod:`horovod_tpu.keras`; this module keeps
the reference's import path working.
"""

from horovod_tpu.keras import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, is_homogeneous, mpi_threads_supported,
    mpi_enabled, mpi_built, gloo_enabled, gloo_built, nccl_built, ddl_built,
    ccl_built, cuda_built, rocm_built, xla_built, ici_built, start_timeline,
    stop_timeline, global_process_set,
    Adasum, Average, Max, Min, Product, Sum, Compression,
    allreduce, allgather, broadcast, alltoall, reducescatter,
    broadcast_object, broadcast_variables, broadcast_global_variables,
    DistributedOptimizer, PartialDistributedOptimizer, load_model,
    callbacks,
)

from horovod_tpu.keras import __all__ as _keras_all

__all__ = list(_keras_all)


def __getattr__(name):
    if name == "elastic":
        import horovod_tpu.keras.elastic as elastic
        return elastic
    raise AttributeError(name)
