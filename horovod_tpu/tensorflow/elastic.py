"""Elastic state handlers for the TF/Keras frontend.

Reference: horovod/tensorflow/elastic.py (run:31, TensorFlowKerasState:91,
TensorFlowState:157). Built on the shared elastic machinery
(:mod:`horovod_tpu.elastic.state`): ``@hvd.elastic.run`` retries the wrapped
train function across membership changes, restoring the last in-memory
commit on collective failure. Variable snapshots live in host numpy arrays
(a membership change can rebuild the XLA backend; device-side copies would
dangle — same reason the base ``ObjectState`` snapshots host-side under an
elastic launch).
"""

from horovod_tpu.elastic.state import ObjectState
from horovod_tpu.elastic.state import run  # noqa: F401  (re-export)


def _assign_all(variables, values):
    for var, val in zip(variables, values):
        var.assign(val)


class TensorFlowState(ObjectState):
    """State of a flat list of tf.Variables (reference:
    tensorflow/elastic.py:157-199)."""

    def __init__(self, variables=None, **kwargs):
        import tensorflow as tf
        self.variables = list(variables) if variables is not None \
            else tf.compat.v1.global_variables()
        self._tf_state = [v.numpy() for v in self.variables]
        super().__init__(**kwargs)

    def save(self):
        self._tf_state = [v.numpy() for v in self.variables]
        super().save()

    def restore(self):
        _assign_all(self.variables, self._tf_state)
        super().restore()

    def sync(self):
        from horovod_tpu.tensorflow import broadcast_variables
        broadcast_variables(self.variables, root_rank=0)
        self._tf_state = [v.numpy() for v in self.variables]
        super().sync()


class TensorFlowKerasState(ObjectState):
    """State of a Keras model + optimizer (reference:
    tensorflow/elastic.py:91-154)."""

    def __init__(self, model, optimizer=None, backend=None, **kwargs):
        if hasattr(model, "built") and not model.built:
            raise ValueError(
                "Model must be built first. Run `model.build(input_shape)`.")
        self.model = model
        self.optimizer = optimizer if optimizer is not None \
            else model.optimizer
        self._save_model()
        super().__init__(**kwargs)

    def _opt_vars(self):
        v = self.optimizer.variables
        return list(v() if callable(v) else v)

    def _save_model(self):
        self._saved_model_state = [v.numpy() for v in self.model.variables]
        self._saved_opt_state = [v.numpy() for v in self._opt_vars()]

    def _load_model(self):
        _assign_all(self.model.variables, self._saved_model_state)
        _assign_all(self._opt_vars(), self._saved_opt_state)

    def save(self):
        self._save_model()
        super().save()

    def restore(self):
        self._load_model()
        super().restore()

    def sync(self):
        from horovod_tpu.tensorflow import broadcast_variables
        broadcast_variables(self.model.variables, root_rank=0)
        broadcast_variables(self._opt_vars(), root_rank=0)
        self._save_model()
        super().sync()
