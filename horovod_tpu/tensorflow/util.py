"""TF-specific helpers (reference: horovod/tensorflow/util.py).

``vars_to_refs``/``refs_to_vars`` make tf.Variables hashable for the
local-variable bookkeeping in DistributedOptimizer/PartialDistributedGradientTape
(TF2 Variables are unhashable; ``var.ref()`` is the stable key —
reference: tensorflow/util.py:77-95).
"""


def vars_to_refs(vars_):
    """Map (nested) tf.Variables to hashable refs (reference:
    tensorflow/util.py:77-84)."""
    if isinstance(vars_, (list, tuple)):
        return type(vars_)(vars_to_refs(v) for v in vars_)
    return vars_.ref()


def refs_to_vars(refs):
    """Inverse of :func:`vars_to_refs` (reference: tensorflow/util.py:87-95)."""
    if isinstance(refs, (list, tuple)):
        return type(refs)(refs_to_vars(r) for r in refs)
    return refs.deref()
