"""Cross-worker synchronous batch normalization for Keras/TF.

Reference: horovod/tensorflow/sync_batch_norm.py (SyncBatchNormalization:22 —
overrides the layer's ``_moments`` to average E[x] and E[x^2] across workers
with one stacked allreduce). Keras 3 removed the ``_moments`` hook, so this
implementation overrides ``call`` for the training path and computes the
group moments itself; inference delegates to the stock layer (moving stats).

The two local statistics ride ONE stacked allreduce — same wire shape as the
reference — and Var[X] = E[X^2] - E[X]^2 is reconstructed from the group
means, which is exact for equal per-worker batch sizes (the reference makes
the same assumption).

TensorFlow is imported lazily (module ``__getattr__``) so importing the
frontend stays TF-free until a TF symbol is actually touched.
"""

_cls = None


def _build_class():
    global _cls
    if _cls is not None:
        return _cls
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    class SyncBatchNormalization(tf.keras.layers.BatchNormalization):
        """Drop-in ``tf.keras.layers.BatchNormalization`` whose training-time
        moments are averaged over the process set (reference:
        sync_batch_norm.py:22-53)."""

        def __init__(self, fused=False, process_set=None, **kwargs):
            if fused in (True, None):
                raise ValueError(
                    "SyncBatchNormalization does not support fused=True.")
            kwargs.setdefault("name", "sync_batch_normalization")
            kwargs.pop("fused", None)
            super().__init__(**kwargs)
            self._process_set = process_set

        def _group_size(self):
            ps = (self._process_set if self._process_set is not None
                  else hvd.global_process_set)
            return ps.size()

        def call(self, inputs, training=None, mask=None):
            if not training or self._group_size() == 1 or mask is not None:
                return super().call(inputs, training=training, mask=mask)

            x = tf.cast(inputs, self.compute_dtype)
            ndim = len(x.shape)
            axis = self.axis if self.axis >= 0 else ndim + self.axis
            reduction_axes = [i for i in range(ndim) if i != axis]

            mean = tf.reduce_mean(x, axis=reduction_axes)
            mean_sq = tf.reduce_mean(tf.square(x), axis=reduction_axes)
            # One stacked allreduce for both statistics, like the reference.
            group = hvd.allreduce(tf.stack([mean, mean_sq]), op=hvd.Average,
                                  process_set=self._process_set)
            mean, mean_sq = tf.unstack(group)
            variance = mean_sq - tf.square(mean)

            m = tf.cast(self.momentum, self.moving_mean.dtype)
            self.moving_mean.assign(
                self.moving_mean * m
                + tf.cast(mean, self.moving_mean.dtype) * (1.0 - m))
            self.moving_variance.assign(
                self.moving_variance * m
                + tf.cast(variance, self.moving_variance.dtype) * (1.0 - m))

            shape = [1] * ndim
            shape[axis] = x.shape[axis]

            def _r(t):
                return tf.reshape(tf.cast(t, x.dtype), shape)

            out = (x - _r(mean)) * tf.math.rsqrt(
                _r(variance) + tf.cast(self.epsilon, x.dtype))
            if self.scale:
                out = out * _r(self.gamma)
            if self.center:
                out = out + _r(self.beta)
            return tf.cast(out, inputs.dtype)

    _cls = SyncBatchNormalization
    return _cls


def __getattr__(name):
    if name == "SyncBatchNormalization":
        return _build_class()
    raise AttributeError(name)
