"""Request-level distributed tracing: propagated trace context + span trees.

The metrics registry says *how much* and the flight recorder says *what
broke*; this module says *where one request's (or one training step's)
time went*. A **trace id** is minted per serving request (and per
training step) and propagated through every layer that touches it:

- serving — ``Request`` carries its ``tid`` from admission through queue
  wait, chunked prefill, slot install, every decode-iteration batch,
  elastic commit/restore/requeue, and stream completion. The id rides
  the elastic request snapshot, so ONE contiguous trace spans an engine
  restart or a worker kill (the chaos soak asserts exactly that).
- training — ``flight.step_marker`` rotates a per-step trace; span
  hooks in the ops layer (negotiation rounds, fusion flush, cross-leg
  ``cross_wait``) record into it.
- flight — the ACTIVE trace ref is injected into every flight-ring
  event (the ``trace`` field), so ``flight.analyze`` reconstructs one
  request/step across ranks keyed by the recorder's per-process-set
  collective seq.

Spans are plain dicts in a bounded per-process store (requests and
steps evict independently, so a long decode run can never push live
request traces out). Read them live at ``GET /debug/trace/<rid>`` on
the serving frontend, or dump per-rank shards (:func:`dump`) and merge
them into one Perfetto-loadable view with
``python -m horovod_tpu.trace.analyze``.

Span-tree schema (``tree()``): the root is the request/step; its
children are PHASE spans (``queue``, ``prefill``, ``decode``,
``stream`` — plus ``requeue``/``restore``/``commit`` instants); phase
children are the fine-grained spans (``chunk``, ``install``,
``decode_step``). A phase that is never recorded explicitly (``decode``)
is synthesized from its children's envelope. Every write is fail-soft
and O(1) under one lock; the perf guard bounds the tracing-on dispatch
host cost at <= 2x tracing-off (tests/test_trace.py).
"""

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque

from horovod_tpu.common.config import _env_bool, _env_int

armed = _env_bool("HOROVOD_TRACE", True)

_counter = itertools.count(1)
# Process-unique salt: rids and step numbers are process-local, so the
# trace id must not collide across workers whose shards get merged.
_SALT = f"{os.getpid():x}"

_lock = threading.Lock()
_traces = {}                  # tid -> record
_rid_index = {}               # str(rid) -> tid
_order = {}                   # kind -> deque of tids (eviction order)
_capacity = {"request": _env_int("HOROVOD_TRACE_CAPACITY", 256),
             "step": 64}
_MAX_SPANS = 4096             # per-trace span cap (drops counted)

_tls = threading.local()      # .tid — the active trace ref


def configure(config):
    """Re-arm from a :class:`~horovod_tpu.common.config.Config` (called
    from init alongside the flight recorder's configure)."""
    global armed
    armed = bool(getattr(config, "trace", armed))
    cap = int(getattr(config, "trace_capacity", 0) or 0)
    if cap > 0:
        # _evict_locked reads _capacity under _lock; an unlocked write
        # here could race a concurrent register()'s eviction decision.
        with _lock:
            _capacity["request"] = cap


# --- ids and the active context -----------------------------------------

def mint(kind="request"):
    """A fresh trace id (no registration — cheap enough to mint even
    when tracing is disarmed, so elastic snapshots always carry one)."""
    return f"t{_SALT}-{kind[0]}{next(_counter):x}"


def get_active():
    return getattr(_tls, "tid", None)


def set_active(tid):
    _tls.tid = tid


def clear_active():
    _tls.tid = None


@contextlib.contextmanager
def activate(tid):
    prev = get_active()
    _tls.tid = tid
    try:
        yield
    finally:
        _tls.tid = prev


# --- the span store ------------------------------------------------------

def _evict_locked(kind):
    order = _order.setdefault(kind, deque())
    cap = _capacity.get(kind, 256)
    while len(order) > cap:
        old = order.popleft()
        rec = _traces.pop(old, None)
        if rec is not None and rec.get("rid") is not None \
                and _rid_index.get(str(rec["rid"])) == old:
            del _rid_index[str(rec["rid"])]


def register(tid, rid=None, kind="request", t0=None, args=None):
    """Create (or re-open) the trace record for ``tid``. Idempotent: a
    requeued request re-registers under its original id and keeps every
    span already recorded — that is the continuity the chaos soak
    asserts."""
    if not armed or tid is None:
        return tid
    with _lock:
        rec = _traces.get(tid)
        if rec is None:
            rec = {"tid": tid, "rid": rid, "kind": kind,
                   "t0": time.time() if t0 is None else float(t0),
                   "spans": [], "dropped": 0, "done": False, "dur": None}
            if args:
                rec["args"] = dict(args)
            _traces[tid] = rec
            _order.setdefault(kind, deque()).append(tid)
            _evict_locked(kind)
        if rid is not None:
            rec["rid"] = rid
            _rid_index[str(rid)] = tid
    return tid


def _append_locked(rec, span):
    if len(rec["spans"]) >= _MAX_SPANS:
        rec["dropped"] += 1
        return
    rec["spans"].append(span)


def _parent_index_locked(rec, parent, t0):
    """Resolve a child's parent by name against the CURRENT top-level
    span — phases repeat across elastic incarnations (queue/prefill
    again after a requeue), so "the current one" is the last top-level
    non-instant span unless a barrier instant (requeue/restore) has
    broken the chain since. A missing parent (``decode``) is synthesized
    from its children's envelope."""
    spans = rec["spans"]
    for i in range(len(spans) - 1, -1, -1):
        s = spans[i]
        if s.get("parent") is not None:
            continue
        if s.get("ph") == "instant":
            if s.get("barrier"):
                break                 # requeue/restore: new incarnation
            continue
        if s["name"] == parent:
            return i
        break                         # a different phase started since
    synth = {"name": parent, "t0": float(t0), "dur": 0.0, "synth": True}
    if len(spans) >= _MAX_SPANS:
        rec["dropped"] += 1
        return None
    spans.append(synth)
    return len(spans) - 1


def add_span(tid, name, t0, dur, parent=None, cat=None, args=None):
    """One completed span (wall-clock ``t0``, seconds ``dur``)."""
    if not armed or tid is None:
        return
    with _lock:
        rec = _traces.get(tid)
        if rec is None:
            return
        span = {"name": name, "t0": float(t0), "dur": float(dur)}
        if cat:
            span["cat"] = cat
        if args:
            span["args"] = dict(args)
        if parent is not None:
            pi = _parent_index_locked(rec, parent, t0)
            if pi is None:
                return
            span["parent"] = pi
            p = rec["spans"][pi]
            if p.get("synth"):
                p["t0"] = min(p["t0"], span["t0"])
                p["dur"] = max(p["dur"],
                               span["t0"] + span["dur"] - p["t0"])
        _append_locked(rec, span)


def add_instant(tid, name, t=None, cat=None, args=None, barrier=False):
    """A zero-duration marker. ``barrier=True`` (requeue/restore) closes
    the current phase chain: spans recorded after it start a fresh
    incarnation of their phase."""
    if not armed or tid is None:
        return
    with _lock:
        rec = _traces.get(tid)
        if rec is None:
            return
        span = {"name": name, "t0": time.time() if t is None else float(t),
                "dur": 0.0, "ph": "instant"}
        if cat:
            span["cat"] = cat
        if args:
            span["args"] = dict(args)
        if barrier:
            span["barrier"] = True
        _append_locked(rec, span)


def finish(tid, dur=None):
    """Close the trace root (stream completion / step end)."""
    if not armed or tid is None:
        return
    with _lock:
        rec = _traces.get(tid)
        if rec is None:
            return
        rec["done"] = True
        rec["dur"] = float(dur) if dur is not None \
            else time.time() - rec["t0"]


@contextlib.contextmanager
def span(name, parent=None, cat=None, tid=None):
    """Record a span around a block, into ``tid`` or the active trace.
    No-op (one attribute read) when tracing is off or nothing is
    active — cheap enough for the ops hot path."""
    t = tid if tid is not None else get_active()
    if not armed or t is None:
        yield
        return
    t0 = time.time()
    try:
        yield
    finally:
        add_span(t, name, t0, time.time() - t0, parent=parent, cat=cat)


def step_trace(step):
    """Rotate the per-step training trace (called by
    ``flight.step_marker``): registers ``tid`` for the NEW step and
    makes it the active ref, so ops-layer spans and flight events
    land under it. Returns the tid (None when disarmed)."""
    if not armed:
        return None
    prev = get_active()
    if prev is not None and prev.startswith(f"t{_SALT}-s"):
        finish(prev)
    tid = mint("step")
    register(tid, kind="step",
             args=None if step is None else {"step": int(step)})
    set_active(tid)
    return tid


# --- reading -------------------------------------------------------------

def for_rid(rid):
    """The trace id serving request ``rid`` (None when unknown or
    evicted)."""
    with _lock:
        return _rid_index.get(str(rid))


def get(tid):
    """The raw trace record (a JSON-able copy; None when unknown)."""
    with _lock:
        rec = _traces.get(tid)
        if rec is None:
            return None
        rec = dict(rec)
        rec["spans"] = [dict(s) for s in rec["spans"]]
        return rec


def tree(tid, now=None):
    """The assembled span tree: root (request/step) -> phase children ->
    fine-grained grandchildren. A live (unfinished) trace reports its
    root duration as elapsed-so-far."""
    rec = get(tid)
    if rec is None:
        return None
    now = time.time() if now is None else now
    root = {"name": rec["kind"], "tid": rec["tid"], "t0": rec["t0"],
            "dur": rec["dur"] if rec["dur"] is not None
            else max(now - rec["t0"], 0.0),
            "done": rec["done"], "children": []}
    if rec.get("rid") is not None:
        root["rid"] = rec["rid"]
    if rec.get("args"):
        root["args"] = rec["args"]
    if rec.get("dropped"):
        root["dropped_spans"] = rec["dropped"]
    nodes = []
    for s in rec["spans"]:
        n = {k: v for k, v in s.items() if k not in ("parent", "barrier")}
        n["children"] = []
        nodes.append(n)
    for s, n in zip(rec["spans"], nodes):
        parent = s.get("parent")
        if parent is None:
            root["children"].append(n)
        else:
            nodes[parent]["children"].append(n)
    for n in nodes:
        if not n["children"]:
            n.pop("children")
    return root


def tree_for_rid(rid, now=None):
    tid = for_rid(rid)
    return None if tid is None else tree(tid, now=now)


def snapshot():
    """Every live trace as one JSON-able shard (per-rank dump payload
    for ``trace.analyze``)."""
    with _lock:
        tids = list(_traces)
    return {"t": time.time(), "pid": os.getpid(),
            "traces": [r for r in (get(t) for t in tids)
                       if r is not None]}


def dump(path, rank=None):
    """Write this process's trace shard to ``path`` (JSON). Returns the
    number of traces written."""
    snap = snapshot()
    if rank is not None:
        snap["rank"] = int(rank)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f)
    os.replace(tmp, path)
    return len(snap["traces"])


def reset():
    """Drop every trace and the active ref (tests)."""
    with _lock:
        _traces.clear()
        _rid_index.clear()
        _order.clear()
    clear_active()
