"""``python -m horovod_tpu.trace.analyze`` — merge per-rank trace shards.

Inputs are JSON shards written by :func:`horovod_tpu.trace.dump` (or
saved ``GET /debug/trace/<rid>`` bodies): point it at files or at a
directory of ``trace_*.json``. Output: a JSON report of per-trace phase
fractions (queue / prefill / decode / stream of the root duration —
"where did my request's latency go?", docs/troubleshooting.md) and,
with ``--trace``, ONE merged Perfetto-loadable Chrome trace — one
process track per rank, one thread track per request/step, spans
nested exactly like the live span tree. The ``clock_sync`` metadata
anchor matches ``flight.analyze --trace``'s convention, so a flight
forensics trace and this view rebase onto one axis.
"""

import argparse
import glob
import json
import os
import sys


def load(paths):
    """Shards from files and/or directories (``trace_*.json``)."""
    shards = []
    for p in paths:
        files = sorted(glob.glob(os.path.join(p, "trace_*.json"))) \
            if os.path.isdir(p) else [p]
        for f in files:
            try:
                with open(f) as fh:
                    shard = json.load(fh)
            except (OSError, json.JSONDecodeError):
                continue
            if "traces" in shard:
                shard["file"] = f
                shards.append(shard)
    return shards


def merge(shards):
    """Flatten shards into trace records, each stamped with the rank (or
    pid) of its shard. The SAME tid can appear in several shards — each
    rank holds the spans it recorded locally (a request that migrated
    across an elastic kill leaves a shard per incarnation host); the
    merged view keeps them as separate rows under one id."""
    rows = []
    for shard in shards:
        who = shard.get("rank", shard.get("pid"))
        for rec in shard.get("traces", ()):
            rec = dict(rec)
            rec["rank"] = who
            rows.append(rec)
    return rows


def _windows(rec, names):
    """Top-level span windows grouped by name (from a RAW record: spans
    whose parent is unset and which are not instants)."""
    out = {}
    for s in rec.get("spans", ()):
        if s.get("parent") is not None or s.get("ph") == "instant":
            continue
        out.setdefault(s["name"], []).append(
            (s["t0"], s["t0"] + s.get("dur", 0.0)))
    return {n: out.get(n, []) for n in names} if names else out


def _union(intervals):
    # Plain sweep: merge overlapping [t0, t1) intervals.
    merged = []
    for t0, t1 in sorted(intervals):
        if merged and t0 <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], t1))
        else:
            merged.append((t0, t1))
    return sum(t1 - t0 for t0, t1 in merged)


PHASES = ("queue", "prefill", "decode", "stream")


def summarize(rec):
    """Phase fractions of one RAW trace record: the union of each
    phase's windows over the root duration, plus coverage (union of ALL
    four phases — the end-to-end guard asserts >= 0.95) and the elastic
    disruption markers."""
    dur = rec.get("dur")
    spans = rec.get("spans", ())
    if dur is None:
        ends = [s["t0"] + s.get("dur", 0.0) for s in spans]
        dur = max(ends) - rec["t0"] if ends else 0.0
    wins = _windows(rec, PHASES)
    fractions = {n: round(_union(w) / dur, 4) if dur else 0.0
                 for n, w in wins.items()}
    all_wins = [iv for w in wins.values() for iv in w]
    out = {"tid": rec.get("tid"), "rid": rec.get("rid"),
           "kind": rec.get("kind"), "dur_s": round(dur, 6),
           "done": rec.get("done", False), "fractions": fractions,
           "coverage": round(_union(all_wins) / dur, 4) if dur else 0.0,
           "requeues": sum(1 for s in spans if s["name"] == "requeue"),
           "restores": sum(1 for s in spans if s["name"] == "restore"),
           "spans": len(spans)}
    if rec.get("dropped"):
        out["dropped_spans"] = rec["dropped"]
    return out


def write_perfetto(rows, path):
    """Merged Chrome trace: pid = rank track, tid = one row per
    trace id (requests and steps side by side), spans as complete
    events, instants as instant events."""
    t0s = [r["t0"] for r in rows if "t0" in r]
    ts0 = min(t0s, default=0.0)
    events = [{"ph": "M", "name": "clock_sync", "pid": 0,
               "args": {"wall_t0_us": ts0 * 1e6}}]
    tids = {}
    for r in rows:
        pid = r.get("rank") or 0
        if not any(e.get("pid") == pid and e["name"] == "process_name"
                   for e in events):
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "args": {"name": f"rank {pid}"}})
        row = tids.setdefault((pid, r["tid"]), len(tids) + 1)
        label = f"r{r['rid']}" if r.get("rid") is not None \
            else r.get("kind", "trace")
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": row,
                       "args": {"name": f"{label} {r['tid']}"}})
        dur = r.get("dur")
        root = {"ph": "X", "pid": pid, "tid": row, "cat": r.get("kind"),
                "name": r.get("kind", "trace"),
                "ts": (r["t0"] - ts0) * 1e6,
                "args": {"tid": r["tid"]}}
        if dur is not None:
            root["dur"] = dur * 1e6
            events.append(root)
        for s in r.get("spans", ()):
            ts = (s["t0"] - ts0) * 1e6
            if s.get("ph") == "instant":
                events.append({"ph": "i", "s": "t", "pid": pid,
                               "tid": row, "name": s["name"],
                               "cat": s.get("cat", "trace"), "ts": ts,
                               "args": s.get("args", {})})
            else:
                events.append({"ph": "X", "pid": pid, "tid": row,
                               "name": s["name"],
                               "cat": s.get("cat", "trace"), "ts": ts,
                               "dur": s.get("dur", 0.0) * 1e6,
                               "args": s.get("args", {})})
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.trace.analyze",
        description="Merge per-rank request/step trace shards into one "
                    "report and a Perfetto-loadable view.")
    p.add_argument("inputs", nargs="+",
                   help="trace shard files or directories of "
                        "trace_*.json")
    p.add_argument("--trace", help="write the merged Chrome trace here")
    p.add_argument("--rid", help="only report the trace(s) of this "
                                 "request id")
    args = p.parse_args(argv)
    rows = merge(load(args.inputs))
    if args.rid is not None:
        rows = [r for r in rows if str(r.get("rid")) == str(args.rid)]
    if not rows:
        print(json.dumps({"error": "no traces found"}))
        return 1
    report = {"traces": [summarize(r) for r in rows],
              "ranks": sorted({r.get("rank") for r in rows
                               if r.get("rank") is not None})}
    if args.trace:
        report["trace_events_written"] = write_perfetto(rows, args.trace)
        report["trace_path"] = args.trace
    json.dump(report, sys.stdout, indent=1)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
