"""Declarative serving SLOs and rolling-window burn rates.

Two objectives, both env-declared (docs/observability.md):

- ``HOROVOD_SLO_TTFT_P99_MS`` — p99 time-to-first-token target. Budget
  is the p99's own 1%: over the rolling window, ``burn = (fraction of
  requests whose TTFT exceeded the target) / 0.01``.
- ``HOROVOD_SLO_TPS`` — generated-tokens/sec floor. A 1% shortfall
  consumes the whole budget: ``burn = max(0, target - observed) /
  (0.01 * target)``.

Either way the reading is the SRE convention: 0 = inside the SLO,
1.0 = consuming the error budget exactly, > 1 = burning it (alert);
the window is ``HOROVOD_SLO_WINDOW_S`` (default 60 s). The serving
engine feeds observations inline (one deque append per request /
decode step); :func:`burn_rates` prunes the window and exports
``slo_burn_rate{objective}`` to the metrics registry — the signal
``telemetry top --serving`` renders as a column and the autopilot
``SignalFrame`` carries for ROADMAP item 1's resize-on-SLO loop.
"""

import threading
import time
from collections import deque

from horovod_tpu.common.config import _env_float

# p99 objectives budget 1% of requests; the tps floor budgets a 1%
# shortfall — one shared constant so both burn scales read the same way.
_BUDGET = 0.01


class SloEngine:
    """Rolling-window burn-rate computation (pure; fake-clock testable:
    every method takes ``now``)."""

    def __init__(self, ttft_p99_ms=0.0, tps=0.0, window_s=60.0):
        self.ttft_p99_ms = float(ttft_p99_ms)
        self.tps = float(tps)
        self.window_s = max(float(window_s), 1e-3)
        self._ttft = deque()          # (t, ttft_seconds)
        self._tokens = deque()        # (t, n)
        self._lock = threading.Lock()

    def configured(self):
        return self.ttft_p99_ms > 0.0 or self.tps > 0.0

    def observe_ttft(self, seconds, now=None):
        if not self.configured():
            return
        with self._lock:
            self._ttft.append((time.time() if now is None else now,
                               float(seconds)))

    def observe_tokens(self, n, now=None):
        if not self.configured() or not n:
            return
        with self._lock:
            self._tokens.append((time.time() if now is None else now,
                                 int(n)))

    def _prune_locked(self, now):
        cut = now - self.window_s
        while self._ttft and self._ttft[0][0] < cut:
            self._ttft.popleft()
        while self._tokens and self._tokens[0][0] < cut:
            self._tokens.popleft()

    def burn_rates(self, now=None):
        """{objective: burn} for every configured objective ({} when
        none are, or nothing was observed in the window yet)."""
        if not self.configured():
            return {}
        now = time.time() if now is None else now
        out = {}
        with self._lock:
            self._prune_locked(now)
            if self.ttft_p99_ms > 0.0 and self._ttft:
                target = self.ttft_p99_ms / 1000.0
                bad = sum(1 for _, s in self._ttft if s > target)
                out["ttft_p99"] = round(
                    bad / len(self._ttft) / _BUDGET, 4)
            if self.tps > 0.0 and self._tokens:
                # Rate over the span the window actually saw — a young
                # window measures its own elapsed time, not the full
                # period (one early token must not read as a huge tps,
                # nor a near-empty window as a violation).
                span = max(now - self._tokens[0][0], 1e-6)
                observed = sum(n for _, n in self._tokens) / span
                out["tps"] = round(
                    max(0.0, self.tps - observed) / (_BUDGET * self.tps),
                    4)
        return out


_engine = None
_lock = threading.Lock()
_last_export = 0.0


def configure(config):
    """(Re)build the singleton from a Config (init path; tests call it
    directly)."""
    global _engine
    with _lock:
        _engine = SloEngine(
            ttft_p99_ms=getattr(config, "slo_ttft_p99_ms", 0.0),
            tps=getattr(config, "slo_tps", 0.0),
            window_s=getattr(config, "slo_window_s", 60.0))
    return _engine


def _get():
    global _engine
    if _engine is None:
        with _lock:
            if _engine is None:
                _engine = SloEngine(
                    ttft_p99_ms=_env_float("HOROVOD_SLO_TTFT_P99_MS", 0.0),
                    tps=_env_float("HOROVOD_SLO_TPS", 0.0),
                    window_s=_env_float("HOROVOD_SLO_WINDOW_S", 60.0))
    return _engine


def observe_ttft(seconds):
    """One request's TTFT (the serving engine's first-token commit)."""
    _get().observe_ttft(seconds)
    _export(throttled=True)


def observe_tokens(n):
    """Tokens committed by one decode step."""
    _get().observe_tokens(n)
    _export(throttled=True)


def burn_rates():
    """Current burn per objective; also refreshes the
    ``slo_burn_rate{objective}`` gauges so a scrape that follows reads
    the same numbers."""
    rates = _get().burn_rates()
    _export(rates=rates)
    return rates


def _export(rates=None, throttled=False):
    """Push burn rates into the metrics registry (fail-soft; lazy import
    keeps metrics -> telemetry import order acyclic)."""
    global _last_export
    if not _get().configured():
        return
    now = time.time()
    if throttled and now - _last_export < 1.0:
        return
    _last_export = now
    try:
        from horovod_tpu.metrics import instruments as _metrics
        for objective, burn in (rates if rates is not None
                                else _get().burn_rates()).items():
            _metrics.record_slo_burn(objective, burn)
    except Exception:  # noqa: BLE001 — metrics off/mid-reset
        pass


def reset():
    """Tests: drop the singleton (next use re-reads the env)."""
    global _engine
    with _lock:
        _engine = None
